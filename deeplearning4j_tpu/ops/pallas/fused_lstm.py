"""Fused LSTM recurrence — single-kernel sequence loop, tiled over hidden.

Reference analog: CudnnLSTMHelper (deeplearning4j-cuda ::
org.deeplearning4j.nn.layers.recurrent.CudnnLSTMHelper), which replaces the
per-timestep Java loop with one cuDNN persistent-RNN launch — for BOTH the
forward and the backward pass. Same split here: the [B*T, F]x[F,4H] input
projection is left to XLA (it is a single MXU-shaped matmul); the
irreducibly-sequential part — T iterations of h@R + gate elementwise — runs
inside ONE Pallas kernel with h/c resident in VMEM scratch, so the
recurrence never round-trips HBM per step, and the whole T-loop is a single
pipelined program instead of T dispatched step-fusions (the reason cuDNN's
persistent kernels win — per-step launch/fusion overhead is the dominant
cost of the XLA scan at these shapes, not FLOPs).

Tiling: grid (B/Bc, T, H/Hb) — batch block outermost (r4), hidden tile
innermost. Each (t, j) step computes gate columns for hidden slice j from
the FULL previous h (double-buffered in scratch: h_prev is stable while
h_next accumulates tiles, swapped after the last tile of each timestep),
so R never needs to fit VMEM whole — R is pre-laid-out as [nH, H, 4*Hb]
per-tile panels. The (Bc, Hb) plan is chosen by a VMEM budget (lstm_plan):
one hidden tile spanning H keeps the R panel's block index grid-constant,
so Pallas fetches R exactly once for the ENTIRE grid — including across
batch blocks, which is what un-demoted the r3 losing regime (B=256/H=1024
re-streamed R per step at 0.4-0.9x; batch-blocked it measures 1.10x fwd /
1.33x train, BASELINE.md r4). The forward and backward choose their batch
blocks independently (the fwd must stay fully resident and wants the
largest resident block for MXU row fill; the bwd tolerates nj=2 and
prefers batch rows — (64, 512) measured faster than the fully-resident
(32, 1024)); the shared [T, B, H] residual layouts make that free.

Matmul precision: panels are pre-cast to bfloat16 with f32 accumulation —
the SAME truncation XLA applies to f32 dot operands on TPU under the
default matmul precision, so the kernel matches the scan lowering's
numerics while running the MXU at full rate (an earlier all-f32 variant of
these kernels measured 0.75x the scan for exactly this reason). Off-TPU
(interpret mode) the cast is skipped, matching XLA-CPU's full-f32 dots.

Backward: a dedicated reverse-time Pallas kernel (_lstm_bwd_kernel), the
cuDNN-parity counterpart of cudnnRNNBackwardData, with the same reserve-
space strategy cuDNN uses: the training forward saves the POST-activation
gates (i, f, o, z, per-gate [T, B, H] f32 — layouts chosen so no consumer
ever transposes them) and the cell sequence, so the backward never re-runs
the h@R recurrence matmul. The backward walks t in reverse via BlockSpec
index maps, forms the pre-activation gate gradients dg for hidden slice j
from the saved tiles entirely in VMEM, and emits four per-gate dg
sequences. The two recurrent carries (dh_rec, accumulated over j via
dg_j @ R_j^T against pre-transposed bf16 panels, and dc, per-slice in
place) live in VMEM scratch with the forward's double-buffer discipline.
Everything that is NOT sequential — dW = x^T dg, dR = h_prev^T dg, db,
dx = dg W^T, peephole sums — is assembled OUTSIDE the kernel as large MXU
matmuls (the cudnnRNNBackwardWeights split), so the kernel only pays for
the O(T) dependent chain.

GravesLSTM peepholes (i,f from c_{t-1}; o from c_t — DL4J semantics,
matching ops/recurrent.lstm_layer) are fused in the same kernels; gate order
IFOG throughout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.common.env import env
from deeplearning4j_tpu.ops.registry import register_impl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _panel_dtype(dtype):
    """MXU operand dtype for the R panels: bf16 on TPU (XLA's own default-
    precision truncation for f32 dots), operand dtype in interpret mode
    (XLA-CPU does full-f32 dots — the parity target off-TPU)."""
    return jnp.bfloat16 if not _interpret() else dtype


def _lstm_kernel(xg_ref, r_ref, h0_ref, c0_ref, p_ref, out_ref, hT_ref,
                 cT_ref, *rest, hb, has_peephole, save_residuals):
    if save_residuals:
        cseq_ref, gi_ref, gf_ref, go_ref, gz_ref = rest[:5]
        hprev_scr, hnext_scr, c_scr = rest[5:]
    else:
        hprev_scr, hnext_scr, c_scr = rest
    # grid (nb, T, nj): batch-block OUTERMOST (r4) — each block runs the
    # whole T recurrence with its own h/c scratch; R's block index ignores
    # every axis, so when one hidden tile spans H the panel is fetched ONCE
    # for ALL batch blocks (the batch-tiled persistent-RNN regime)
    t = pl.program_id(1)
    j = pl.program_id(2)
    nt = pl.num_programs(1)
    nj = pl.num_programs(2)

    @pl.when((t == 0) & (j == 0))
    def _init():
        hprev_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    cols = (slice(None), pl.ds(j * hb, hb))
    # gates for hidden slice j from the FULL previous h (double buffer)
    g = xg_ref[0, 0].astype(jnp.float32) + jax.lax.dot_general(
        hprev_scr[:].astype(r_ref.dtype), r_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [B, 4*hb]
    gi = g[:, :hb]
    gf = g[:, hb:2 * hb]
    go = g[:, 2 * hb:3 * hb]
    gz = g[:, 3 * hb:]
    c_old = c_scr[cols]
    if has_peephole:
        p = p_ref[0].astype(jnp.float32)               # [3, hb]
        gi = gi + c_old * p[0:1, :]
        gf = gf + c_old * p[1:2, :]
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf)
    z = jnp.tanh(gz)
    c_new = f * c_old + i * z
    if has_peephole:
        go = go + c_new * p[2:3, :]
    o = jax.nn.sigmoid(go)
    h_new = o * jnp.tanh(c_new)
    c_scr[cols] = c_new
    hnext_scr[cols] = h_new
    out_ref[0] = h_new.astype(out_ref.dtype)
    if save_residuals:
        cseq_ref[0] = c_new
        gi_ref[0] = i
        gf_ref[0] = f
        go_ref[0] = o
        gz_ref[0] = z

    @pl.when(j == nj - 1)
    def _advance():
        hprev_scr[:] = hnext_scr[:]

    @pl.when(t == nt - 1)
    def _final():
        hT_ref[:] = h_new.astype(hT_ref.dtype)
        cT_ref[:] = c_new.astype(cT_ref.dtype)


def lstm_tile(B, H, rdtype_bytes=2, budget=13 << 20, save_residuals=False):
    """Largest hidden tile (multiple of 128, dividing H) for a batch block
    of B rows; None when even Hb=128 does not fit (fall back).

    Grid-VARYING blocks (R/xg/peephole panels indexed by t or j, and the
    out/hT/cT[/cseq/gate] tiles) are double-buffered by the Pallas
    pipeline, so they count twice; grid-invariant blocks and the three
    scratch buffers count once. When ONE tile spans H the R panel's block
    index is grid-constant, so it is fetched once and counts ONCE — that
    accounting unlocks full-residency at H=1024/small-B, measured 1.2-1.5x
    the scan on-chip (BASELINE.md r3). Blocks whose index varies only on
    the outermost batch-block axis (h0/c0) count once: Pallas skips the
    DMA while the block index is unchanged, so they re-fetch only at chunk
    boundaries. If the pipeline still allocates a second buffer for them,
    the under-count is bounded by 2*B*H*4 (<= 0.5 MB at every shipped
    chunk size) and is absorbed by the ~3 MB gap between this 13 MB budget
    and the ~16 MB scoped-VMEM limit; `bench.py smoke` compiles the
    batch-blocked plans on the real chip continuously, so a budget
    violation surfaces there, not in production. R panels are bf16 on TPU
    (rdtype_bytes=2)."""
    for hb in (H, 1024, 512, 256, 128):
        if hb > H or H % hb:
            continue
        r_bufs = 1 if hb == H else 2           # grid-invariant panel: once
        est = (r_bufs * H * 4 * hb * rdtype_bytes  # R panel
               + 2 * B * 4 * hb * 4            # xg block (dbl-buffered)
               + 2 * 3 * B * hb * 4            # out/hT/cT tiles (dbl)
               + 3 * B * H * 4                 # h double buffer + c scratch
               + 2 * B * H * 4)                # h0 + c0 (refetch amortized)
        if save_residuals:
            est += 2 * 5 * B * hb * 4          # cseq + 4 gate tiles (dbl)
        if est <= budget:
            return hb
    return None


def lstm_bwd_tile(B, H, rdtype_bytes=2, budget=13 << 20):
    """Tile selector for the backward kernel. Its working set is smaller
    than the forward's: no xg / h_prev inputs (gates come from the saved
    reserve), one transposed R panel (read only for dg_j @ R_j^T; counted
    once when grid-invariant, i.e. hb == H)."""
    for hb in (H, 1024, 512, 256, 128):
        if hb > H or H % hb:
            continue
        r_bufs = 1 if hb == H else 2
        est = (r_bufs * H * 4 * hb * rdtype_bytes  # R^T panel
               + 2 * 4 * B * hb * 4            # gate tiles (dbl)
               + 3 * 2 * B * hb * 4            # c_prev/c/dout tiles (dbl)
               + 2 * 4 * B * hb * 4            # dg out tiles (dbl)
               + 2 * B * hb * 4                # dc0 out tile (dbl)
               + B * H * 4                     # dcT (refetch amortized)
               + 3 * B * H * 4)                # dh carry + dh accum + dc
        if est <= budget:
            return hb
    return None


def _plan(tile_fn, B, H, **kw):
    """(Bc, hb) for the FORWARD: batch-block size and hidden tile.

    The forward must keep R grid-invariant (hb == H): per step it runs ONE
    dot against the full R, so any panel re-streaming is exposed —
    measured 0.33-0.60x at B=256/H=1024 for every nj > 1 or
    under-resident plan. When the full batch cannot be resident, split it
    into batch blocks (r4) and take the LARGEST resident block (MXU row
    fill: Bc=64 measured 1.10x fwd where Bc=32 measured 0.60x). Falls
    back to hidden tiling at full B (reachable via FORCE_PALLAS only) and
    (None, None) when nothing fits."""
    hb = tile_fn(B, H, **kw)
    if hb == H:
        return B, hb
    for Bc in (128, 64, 32):
        if B % Bc == 0 and Bc < B and tile_fn(Bc, H, **kw) == H:
            return Bc, H
    return (B, hb) if hb else (None, None)


def _bwd_plan(tile_fn, B, H, **kw):
    """(Bc, hb) for the BACKWARD: unlike the forward, nj == 2 is fine —
    each reverse step runs FOUR dots against the R^T panels (one per
    gate), so the alternating-panel traffic hides under compute. Measured
    at B=256/H=1024: (64, 512) runs the bwd in ~1.4 ms where the fully-
    resident (32, 1024) takes ~2.6 ms — batch rows beat residency. Rank:
    largest batch block whose tile keeps nj <= 2."""
    fallback = None
    for Bc in (B, 128, 64, 32):
        if Bc > B or B % Bc:
            continue
        hb = tile_fn(Bc, H, **kw)
        if hb is None:
            continue
        if 2 * hb >= H:
            return Bc, hb
        if fallback is None:
            fallback = (Bc, hb)
    return fallback or (None, None)


def lstm_plan(B, H, rdtype_bytes=2, save_residuals=False):
    return _plan(lstm_tile, B, H, rdtype_bytes=rdtype_bytes,
                 save_residuals=save_residuals)


def lstm_bwd_plan(B, H, rdtype_bytes=2):
    return _bwd_plan(lstm_bwd_tile, B, H, rdtype_bytes=rdtype_bytes)


def _fused_recurrence(xg, R, h0, c0, peephole, *, interpret,
                      save_residuals=False):
    """xg [T, B, 4H] time-major pre-projected gates; returns
    (outputs [T, B, H], hT, cT, residuals-or-None). Residuals are
    (cseq, i, f, o, z), each [T, B, H] f32 post-activation — the reserve
    space for the backward kernel, in layouts no consumer transposes."""
    T, B, G = xg.shape
    H = G // 4
    pdt = _panel_dtype(R.dtype)
    Bc, hb = lstm_plan(B, H, rdtype_bytes=jnp.dtype(pdt).itemsize,
                       save_residuals=save_residuals)
    if hb is None:
        raise ValueError(f"no VMEM-feasible LSTM tile for B={B}, H={H}")
    nb = B // Bc
    nj = H // hb
    # per-tile panels: R [nH, H, 4*Hb]; xg [T, nH, B, 4*Hb]
    Rl = (R.reshape(H, 4, nj, hb).transpose(2, 0, 1, 3)
          .reshape(nj, H, 4 * hb).astype(pdt))
    xgl = (xg.reshape(T, B, 4, nj, hb).transpose(0, 3, 1, 2, 4)
           .reshape(T, nj, B, 4 * hb))
    has_p = peephole is not None
    if has_p:
        pll = peephole.reshape(3, nj, hb).transpose(1, 0, 2)  # [nH, 3, hb]
    else:
        pll = jnp.zeros((nj, 3, hb), xg.dtype)

    tile_tj = pl.BlockSpec((1, Bc, hb), lambda b, t, j: (t, b, j),
                           memory_space=pltpu.VMEM)
    out_shape = [jax.ShapeDtypeStruct((T, B, H), xg.dtype),
                 jax.ShapeDtypeStruct((B, H), xg.dtype),
                 jax.ShapeDtypeStruct((B, H), xg.dtype)]
    out_specs = [
        tile_tj,
        pl.BlockSpec((Bc, hb), lambda b, t, j: (b, j),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((Bc, hb), lambda b, t, j: (b, j),
                     memory_space=pltpu.VMEM),
    ]
    if save_residuals:
        for _ in range(5):                     # cseq + 4 post-activation gates
            out_shape.append(jax.ShapeDtypeStruct((T, B, H), jnp.float32))
            out_specs.append(tile_tj)

    res = pl.pallas_call(
        functools.partial(_lstm_kernel, hb=hb, has_peephole=has_p,
                          save_residuals=save_residuals),
        out_shape=tuple(out_shape),
        grid=(nb, T, nj),
        in_specs=[
            pl.BlockSpec((1, 1, Bc, 4 * hb), lambda b, t, j: (t, j, b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H, 4 * hb), lambda b, t, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Bc, H), lambda b, t, j: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Bc, H), lambda b, t, j: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 3, hb), lambda b, t, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((Bc, H), jnp.float32),
            pltpu.VMEM((Bc, H), jnp.float32),
            pltpu.VMEM((Bc, H), jnp.float32),
        ],
        interpret=interpret,
    )(xgl, Rl, h0, c0, pll)
    if save_residuals:
        out, hT, cT = res[:3]
        residuals = res[3:]                    # (cseq, i, f, o, z)
    else:
        (out, hT, cT), residuals = res, None
    return out, hT, cT, residuals


def _project_gates(x, W, b, H, forget_gate_bias, reverse):
    """The non-sequential input projection: one [B*T,F]x[F,4H] MXU matmul,
    time-major, kernel domain."""
    xg = x @ W + b
    if forget_gate_bias:
        xg = xg.at[..., H:2 * H].add(forget_gate_bias)
    xg = jnp.swapaxes(xg, 0, 1)  # [T, B, 4H]
    if reverse:
        xg = jnp.flip(xg, axis=0)
    return xg


def _kernel_forward(x, h0, c0, W, R, b, peephole, forget_gate_bias, reverse,
                    save_residuals=False):
    H = R.shape[0]
    xg = _project_gates(x, W, b, H, forget_gate_bias, reverse)
    out, hT, cT, residuals = _fused_recurrence(
        xg, R, h0, c0, peephole, interpret=_interpret(),
        save_residuals=save_residuals)
    if reverse:
        out = jnp.flip(out, axis=0)
    return (jnp.swapaxes(out, 0, 1), (hT, cT)), residuals


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _fused(x, h0, c0, W, R, b, peephole, forget_gate_bias, reverse):
    out, _ = _kernel_forward(x, h0, c0, W, R, b, peephole, forget_gate_bias,
                             reverse)
    return out


def _kernel_bwd_enabled(B, H, rdtype) -> bool:
    """Trace-time decision shared by _fused_fwd and _fused_bwd: save (and
    consume) the reserve space only when the backward kernel will run, so
    the scan-backward arm (flag or infeasible tile) pays no reserve cost."""
    return (not env.lstm_scan_bwd
            and lstm_bwd_plan(
                B, H, rdtype_bytes=jnp.dtype(_panel_dtype(rdtype)).itemsize)[1]
            is not None)


def _fused_fwd(x, h0, c0, W, R, b, peephole, forget_gate_bias, reverse):
    save = _kernel_bwd_enabled(x.shape[0], R.shape[0], R.dtype)
    out, residuals = _kernel_forward(x, h0, c0, W, R, b, peephole,
                                     forget_gate_bias, reverse,
                                     save_residuals=save)
    # residuals are kept in KERNEL time order (flipped when reverse=True) —
    # the backward kernel walks the same domain
    return out, (x, h0, c0, W, R, b, peephole, out[0], residuals)


# --------------------------------------------------------------------------
# backward kernel
# --------------------------------------------------------------------------


def _lstm_bwd_kernel(i_ref, f_ref, o_ref, z_ref, rt_ref, cprev_ref, c_ref,
                     dout_ref, dcT_ref, p_ref,
                     dgi_ref, dgf_ref, dgo_ref, dgz_ref, dc0_ref,
                     dh_scr, dhn_scr, dc_scr, *, hb, has_peephole):
    """One reverse-time step for hidden slice j.

    Reads the saved post-activation gates (the reserve space — NO h@R
    recompute), forms the pre-activation gate gradients dg and the two
    carries: dh_rec (accumulated over j via dg_j @ R_j^T against the
    pre-transposed panel) and dc (per-slice, in place). Time reversal is
    done by the BlockSpec index maps, not by flipping arrays in HBM.
    Grid (nb, T, nj) with the batch block outermost (r4), mirroring the
    forward: each batch block replays the reverse recurrence with its own
    carries while the R^T panel stays grid-invariant.
    """
    t = pl.program_id(1)
    j = pl.program_id(2)
    nt = pl.num_programs(1)
    nj = pl.num_programs(2)

    @pl.when((t == 0) & (j == 0))
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = dcT_ref[:].astype(jnp.float32)

    cols = (slice(None), pl.ds(j * hb, hb))

    i = i_ref[0]                                       # [B, hb] f32
    f = f_ref[0]
    o = o_ref[0]
    z = z_ref[0]
    c_old = cprev_ref[0].astype(jnp.float32)
    th = jnp.tanh(c_ref[0].astype(jnp.float32))
    if has_peephole:
        p = p_ref[0].astype(jnp.float32)               # [3, hb]

    # ---- gate gradients
    dh_tot = dout_ref[0].astype(jnp.float32) + dh_scr[cols]
    dgo = (dh_tot * th) * o * (1.0 - o)
    dc = dc_scr[cols] + dh_tot * o * (1.0 - th * th)
    if has_peephole:
        dc = dc + dgo * p[2:3, :]
    dgi = (dc * z) * i * (1.0 - i)
    dgf = (dc * c_old) * f * (1.0 - f)
    dgz = (dc * i) * (1.0 - z * z)
    dc_prev = dc * f
    if has_peephole:
        dc_prev = dc_prev + dgi * p[0:1, :] + dgf * p[1:2, :]
    dc_scr[cols] = dc_prev
    dgi_ref[0] = dgi
    dgf_ref[0] = dgf
    dgo_ref[0] = dgo
    dgz_ref[0] = dgz

    # ---- dh_rec for step t-1: accumulate sum_g dg_g @ R_g^T over slices
    pdt = rt_ref.dtype
    contrib = jax.lax.dot_general(
        dgi.astype(pdt), rt_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [B, H]
    for dgx, gate in ((dgf, 1), (dgo, 2), (dgz, 3)):
        contrib = contrib + jax.lax.dot_general(
            dgx.astype(pdt), rt_ref[0, gate], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _first():
        dhn_scr[:] = contrib

    @pl.when(j != 0)
    def _acc():
        dhn_scr[:] = dhn_scr[:] + contrib

    @pl.when(j == nj - 1)
    def _advance():
        dh_scr[:] = dhn_scr[:]

    @pl.when(t == nt - 1)
    def _final():
        dc0_ref[:] = dc_prev


def _bwd_recurrence(residuals, R, cprev_seq, dout, dcT, peephole, *,
                    plan, interpret):
    """Run the reverse-time kernel. ``residuals`` = (cseq, i, f, o, z) from
    the forward, KERNEL time order. Returns (dgi, dgf, dgo, dgz — each
    [T, B, H] f32 in kernel time order — and dc0). ``plan`` = (Bc, hb):
    the backward's batch block is chosen independently of the forward's
    (measured at B=256/H=1024: the bwd's best plan is (64, 512) — nj=2
    with more batch rows beats the fully-resident (32, 1024), ~1.4 ms vs
    ~2.6 ms — while the fwd must stay resident; the shared [T, B, H]
    layouts make the re-chunk free)."""
    cseq, gi, gf, go, gz = residuals
    T, B, H = cseq.shape
    Bc, hb = plan
    nb = B // Bc
    nj = H // hb
    pdt = _panel_dtype(R.dtype)
    # pre-transposed panels: Rt[j, g] = R[:, g*H + j*hb : ...]^T  [hb, H]
    Rt = (R.reshape(H, 4, nj, hb).transpose(2, 1, 3, 0)   # [nj, 4, hb, H]
          .astype(pdt))
    has_p = peephole is not None
    if has_p:
        pll = peephole.reshape(3, nj, hb).transpose(1, 0, 2)  # [nH, 3, hb]
    else:
        pll = jnp.zeros((nj, 3, hb), R.dtype)

    revj = lambda b, t, j: (T - 1 - t, b, j)       # reverse-time j-tiles
    tile = pl.BlockSpec((1, Bc, hb), revj, memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        functools.partial(_lstm_bwd_kernel, hb=hb, has_peephole=has_p),
        out_shape=(jax.ShapeDtypeStruct((T, B, H), jnp.float32),) * 4
        + (jax.ShapeDtypeStruct((B, H), jnp.float32),),
        grid=(nb, T, nj),
        in_specs=[
            tile, tile, tile, tile,                    # i, f, o, z
            pl.BlockSpec((1, 4, hb, H), lambda b, t, j: (j, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            tile,                                      # c_prev
            tile,                                      # c
            tile,                                      # dout
            pl.BlockSpec((Bc, H), lambda b, t, j: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 3, hb), lambda b, t, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(tile,) * 4 + (
            pl.BlockSpec((Bc, hb), lambda b, t, j: (b, j),
                         memory_space=pltpu.VMEM),),
        scratch_shapes=[
            pltpu.VMEM((Bc, H), jnp.float32),  # dh_rec carry (stable per t)
            pltpu.VMEM((Bc, H), jnp.float32),  # dh_rec accumulator
            pltpu.VMEM((Bc, H), jnp.float32),  # dc carry (per-slice in place)
        ],
        interpret=interpret,
    )(gi, gf, go, gz, Rt, cprev_seq, cseq, dout, dcT, pll)
    return out                                          # (dgi..dgz, dc0)


def _scan_bwd(forget_gate_bias, reverse, res, g):
    """Fallback backward: autodiff through the XLA scan lowering (used when
    no VMEM-feasible backward tile exists, or when forced via
    DL4J_TPU_LSTM_SCAN_BWD for A/B measurement)."""
    from deeplearning4j_tpu.ops.recurrent import lstm_layer

    x, h0, c0, W, R, b, peephole = res
    diff_args = (x, h0, c0, W, R, b) + (() if peephole is None else (peephole,))

    def ref(*args):
        if peephole is None:
            xx, hh, cc, WW, RR, bb = args
            pp = None
        else:
            xx, hh, cc, WW, RR, bb, pp = args
        return lstm_layer(xx, hh, cc, WW, RR, bb, peephole=pp,
                          forget_gate_bias=forget_gate_bias, reverse=reverse)

    _, vjp = jax.vjp(ref, *diff_args)
    grads = vjp(g)
    if peephole is None:
        grads = grads + (None,)
    return grads


def _fused_bwd(forget_gate_bias, reverse, res, g):
    x, h0, c0, W, R, b, peephole, out, residuals = res
    B, T, F = x.shape
    H = R.shape[0]
    if residuals is None:   # forward already decided: scan backward
        return _scan_bwd(forget_gate_bias, reverse,
                         (x, h0, c0, W, R, b, peephole), g)
    plan = lstm_bwd_plan(
        B, H, rdtype_bytes=jnp.dtype(_panel_dtype(R.dtype)).itemsize)

    g_out, (g_hT, g_cT) = g
    cseq = residuals[0]

    # kernel time domain (flipped when reverse=True), matching residuals
    out_k = jnp.swapaxes(out, 0, 1)
    dout_k = jnp.swapaxes(g_out, 0, 1)
    if reverse:
        out_k = jnp.flip(out_k, axis=0)
        dout_k = jnp.flip(dout_k, axis=0)
    # hT aliases out[T-1]; its cotangent joins the last step's output grad
    dout_k = dout_k.at[T - 1].add(g_hT)
    hprev_k = jnp.concatenate([h0[None].astype(out_k.dtype), out_k[:-1]], 0)
    cprev_k = jnp.concatenate([c0[None].astype(cseq.dtype), cseq[:-1]], 0)

    dgi, dgf, dgo, dgz, dc0 = _bwd_recurrence(
        residuals, R, cprev_k, dout_k, g_cT, peephole, plan=plan,
        interpret=_interpret())
    dgs = (dgi, dgf, dgo, dgz)

    # ---- everything non-sequential: big MXU matmuls outside the kernel
    # (the cudnnRNNBackwardWeights split), all on untransposed [T,B,H]
    # operands — dot_general contracts (t,b) directly, no relayouts.
    xf = x.astype(jnp.float32)
    hpf = hprev_k.astype(jnp.float32)
    # h0 feeds only g_0: dh0 = sum_g dg_g[0] @ R_g^T
    dh0 = sum(jax.lax.dot_general(
        dg[0], R.astype(jnp.float32)[:, gi_ * H:(gi_ + 1) * H],
        (((1,), (1,)), ((), ()))) for gi_, dg in enumerate(dgs))
    dR = jnp.concatenate(
        [jnp.einsum("tbh,tbg->hg", hpf, dg) for dg in dgs], axis=1)
    # x-coupled products need NATURAL time order (dgs are kernel order)
    dgs_nat = tuple(jnp.flip(dg, axis=0) for dg in dgs) if reverse else dgs
    dW = jnp.concatenate(
        [jnp.einsum("btf,tbg->fg", xf, dg) for dg in dgs_nat], axis=1)
    db = jnp.concatenate([dg.sum((0, 1)) for dg in dgs])
    # dx = sum_g dg_g @ W_g^T, emitted batch-major
    Wf = W.astype(jnp.float32)
    dx_nat = sum(jax.lax.dot_general(
        dg, Wf[:, gi_ * H:(gi_ + 1) * H], (((2,), (1,)), ((), ())))
        for gi_, dg in enumerate(dgs_nat))             # [T, B, F]
    dx = jnp.swapaxes(dx_nat, 0, 1)
    if peephole is not None:
        cpf = cprev_k.astype(jnp.float32)
        dp = jnp.concatenate([
            (dgi * cpf).sum((0, 1)),
            (dgf * cpf).sum((0, 1)),
            (dgo * cseq).sum((0, 1)),
        ])
        dp = dp.astype(peephole.dtype)
    else:
        dp = None
    return (dx.astype(x.dtype), dh0.astype(h0.dtype), dc0.astype(c0.dtype),
            dW.astype(W.dtype), dR.astype(R.dtype), db.astype(b.dtype), dp)


_fused.defvjp(_fused_fwd, _fused_bwd)


def _pad_to_lanes(H: int) -> int:
    """Next lane multiple: the padded hidden size the kernel entry point
    runs AND the size the selection predicates must evaluate (one shared
    definition so predicate and kernel can never disagree)."""
    return -(-H // 128) * 128


def _pad_gates(a, H, Hp, axis):
    """Zero-pad the per-gate H-blocks of a gate-major [..., G*H] axis to
    [..., G*Hp] (G inferred), keeping IFOG block order."""
    G = a.shape[axis] // H
    shape = list(a.shape)
    shape[axis:axis + 1] = [G, H]
    widths = [(0, 0)] * len(shape)
    widths[axis + 1] = (0, Hp - H)
    out = jnp.pad(a.reshape(shape), widths)
    shape2 = list(a.shape)
    shape2[axis] = G * Hp
    return out.reshape(shape2)


def fused_lstm_layer(x, h0, c0, W, R, b, *, peephole=None,
                     forget_gate_bias=0.0, reverse=False):
    """Drop-in accelerated impl of the "lstm_layer" op (same signature).

    Unaligned hidden sizes (H % 128 != 0 — e.g. the reference's stock
    200-unit GravesLSTM configs, which cuDNN accelerates too) are
    zero-PADDED to the next lane multiple: padded gate columns see zero
    pre-activations, so z = tanh(0) = 0 keeps c = h = 0 in every padded
    lane through the whole recurrence (forget-gate bias and peepholes
    included: they multiply a zero c), and the backward's padded gate
    gradients vanish the same way — slicing after the kernel is exact,
    not approximate. The pad/slice is differentiable, so the
    custom_vjp'd core needs no changes."""
    H = R.shape[0]
    Hp = _pad_to_lanes(H)
    if Hp == H:
        return _fused(x, h0, c0, W, R, b, peephole, float(forget_gate_bias),
                      bool(reverse))
    padh = lambda a: jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, Hp - H)])
    Wp = _pad_gates(W, H, Hp, 1)
    Rp = _pad_gates(jnp.pad(R, [(0, Hp - H), (0, 0)]), H, Hp, 1)
    bp = _pad_gates(b, H, Hp, 0)
    pp = None if peephole is None else _pad_gates(peephole, H, Hp, 0)
    out, (hT, cT) = _fused(x, padh(h0), padh(c0), Wp, Rp, bp, pp,
                           float(forget_gate_bias), bool(reverse))
    return out[..., :H], (hT[..., :H], cT[..., :H])


def _lstm_requires(x, h0, c0, W, R, b, *, peephole=None, **kw):
    # structural: a VMEM-feasible plan must exist (incl. reserve outputs),
    # sized with the SAME panel dtype _fused_recurrence will actually use
    # (f32 in interpret mode, bf16 on TPU) and the PADDED hidden size the
    # kernel will actually run
    Hp = _pad_to_lanes(R.shape[0])
    rb = jnp.dtype(_panel_dtype(R.dtype)).itemsize
    return lstm_plan(x.shape[0], Hp, rdtype_bytes=rb,
                     save_residuals=True)[1] is not None


def _lstm_applicable(x, h0, c0, W, R, b, *, peephole=None, **kw):
    """Perf heuristic (measured on v5e, r3+r4): the kernel wins when R is
    grid-invariant — ONE hidden tile spans H, fetched once, the recurrence
    runs out of VMEM (fwd up to 2.0x, train 1.1-1.6x vs the scan). r4
    extends that regime to LARGE batches by batch-blocking the grid: at
    B=256/H=1024 (the r3 demoted shape) the fwd runs resident batch
    blocks (Bc=64 infer / Bc=32 train) and the bwd runs (64, 512),
    measured 1.10x fwd / 1.33x train — numbers in BASELINE.md. Only
    shapes with no resident plan at all (H too big for any block to keep
    R in VMEM, e.g. H >= 2048) stay on the XLA scan, as do non-f32/bf16
    dtypes — the measured A/B evidence (and the MXU panel layout) covers
    only those."""
    if x.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    Hp = _pad_to_lanes(R.shape[0])         # unaligned H runs zero-padded
    rb = jnp.dtype(_panel_dtype(R.dtype)).itemsize
    return (x.shape[0] % 8 == 0
            and lstm_plan(x.shape[0], Hp, rdtype_bytes=rb,
                          save_residuals=True)[1] == Hp)


register_impl("lstm_layer", platform="pallas", predicate=_lstm_applicable,
              requires=_lstm_requires, priority=1)(fused_lstm_layer)
