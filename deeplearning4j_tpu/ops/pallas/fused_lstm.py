"""Fused LSTM recurrence — single-kernel sequence loop, tiled over hidden.

Reference analog: CudnnLSTMHelper (deeplearning4j-cuda ::
org.deeplearning4j.nn.layers.recurrent.CudnnLSTMHelper), which replaces the
per-timestep Java loop with one cuDNN persistent-RNN launch. Same split
here: the [B*T, F]x[F,4H] input projection is left to XLA (it is a single
MXU-shaped matmul); the irreducibly-sequential part — T iterations of
h@R + gate elementwise — runs inside ONE Pallas kernel with h/c resident in
VMEM scratch, so the recurrence never round-trips HBM per step (the reason
cuDNN's persistent kernels win).

Tiling: grid (T, H/Hb), hidden-tile innermost. Each (t, j) step computes
gate columns for hidden slice j from the FULL previous h (double-buffered
in scratch: h_prev is stable while h_next accumulates tiles, swapped after
the last tile of each timestep), so R never needs to fit VMEM whole —
R is pre-laid-out as [nH, H, 4*Hb] per-tile panels. The tile size is chosen
by a VMEM budget (lstm_tile), which is also the selection predicate: big
models (H=1024, B=256+) now use the kernel instead of silently falling back.

GravesLSTM peepholes (i,f from c_{t-1}; o from c_t — DL4J semantics,
matching ops/recurrent.lstm_layer) are fused in the same kernel; gate order
IFOG throughout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.registry import register_impl


def _lstm_kernel(xg_ref, r_ref, h0_ref, c0_ref, p_ref, out_ref, hT_ref,
                 cT_ref, hprev_scr, hnext_scr, c_scr, *, hb, has_peephole):
    t = pl.program_id(0)
    j = pl.program_id(1)
    nt = pl.num_programs(0)
    nj = pl.num_programs(1)

    @pl.when((t == 0) & (j == 0))
    def _init():
        hprev_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    cols = (slice(None), pl.ds(j * hb, hb))
    # gates for hidden slice j from the FULL previous h (double buffer)
    g = xg_ref[0, 0].astype(jnp.float32) + jax.lax.dot_general(
        hprev_scr[:].astype(r_ref.dtype), r_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [B, 4*hb]
    gi = g[:, :hb]
    gf = g[:, hb:2 * hb]
    go = g[:, 2 * hb:3 * hb]
    gz = g[:, 3 * hb:]
    c_old = c_scr[cols]
    if has_peephole:
        p = p_ref[0].astype(jnp.float32)               # [3, hb]
        gi = gi + c_old * p[0:1, :]
        gf = gf + c_old * p[1:2, :]
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf)
    z = jnp.tanh(gz)
    c_new = f * c_old + i * z
    if has_peephole:
        go = go + c_new * p[2:3, :]
    o = jax.nn.sigmoid(go)
    h_new = o * jnp.tanh(c_new)
    c_scr[cols] = c_new
    hnext_scr[cols] = h_new
    out_ref[0] = h_new.astype(out_ref.dtype)

    @pl.when(j == nj - 1)
    def _advance():
        hprev_scr[:] = hnext_scr[:]

    @pl.when(t == nt - 1)
    def _final():
        hT_ref[:] = h_new.astype(hT_ref.dtype)
        cT_ref[:] = c_new.astype(cT_ref.dtype)


def lstm_tile(B, H, rdtype_bytes=4, budget=13 << 20):
    """Largest hidden tile (multiple of 128, dividing H) whose working set
    fits the VMEM budget; None when even Hb=128 does not fit (fall back).

    Grid-VARYING blocks (R/xg/peephole panels indexed by t or j, and the
    out/hT/cT tiles) are double-buffered by the Pallas pipeline, so they
    count twice; the grid-invariant h0/c0 blocks and the three scratch
    buffers count once. Budget is set under the ~16M scoped-VMEM limit."""
    for hb in (H, 1024, 512, 256, 128):
        if hb > H or H % hb:
            continue
        est = (2 * H * 4 * hb * rdtype_bytes   # R panel (dbl-buffered)
               + 2 * B * 4 * hb * 4            # xg block (dbl-buffered)
               + 2 * 3 * B * hb * 4            # out/hT/cT tiles (dbl)
               + 3 * B * H * 4                 # h double buffer + c scratch
               + 2 * B * H * 4)                # h0 + c0 (invariant)
        if est <= budget:
            return hb
    return None


def _fused_recurrence(xg, R, h0, c0, peephole, *, interpret):
    """xg [T, B, 4H] time-major pre-projected gates; returns
    (outputs [T, B, H], hT, cT)."""
    T, B, G = xg.shape
    H = G // 4
    hb = lstm_tile(B, H, rdtype_bytes=R.dtype.itemsize)
    if hb is None:
        raise ValueError(f"no VMEM-feasible LSTM tile for B={B}, H={H}")
    nj = H // hb
    # per-tile panels: R [nH, H, 4*Hb]; xg [T, nH, B, 4*Hb]
    Rl = R.reshape(H, 4, nj, hb).transpose(2, 0, 1, 3).reshape(nj, H, 4 * hb)
    xgl = (xg.reshape(T, B, 4, nj, hb).transpose(0, 3, 1, 2, 4)
           .reshape(T, nj, B, 4 * hb))
    has_p = peephole is not None
    if has_p:
        pll = peephole.reshape(3, nj, hb).transpose(1, 0, 2)  # [nH, 3, hb]
    else:
        pll = jnp.zeros((nj, 3, hb), xg.dtype)

    out, hT, cT = pl.pallas_call(
        functools.partial(_lstm_kernel, hb=hb, has_peephole=has_p),
        out_shape=(jax.ShapeDtypeStruct((T, B, H), xg.dtype),
                   jax.ShapeDtypeStruct((B, H), xg.dtype),
                   jax.ShapeDtypeStruct((B, H), xg.dtype)),
        grid=(T, nj),
        in_specs=[
            pl.BlockSpec((1, 1, B, 4 * hb), lambda t, j: (t, j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H, 4 * hb), lambda t, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 3, hb), lambda t, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, B, hb), lambda t, j: (t, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, hb), lambda t, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, hb), lambda t, j: (0, j),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(xgl, Rl, h0, c0, pll)
    return out, hT, cT


def _kernel_forward(x, h0, c0, W, R, b, peephole, forget_gate_bias, reverse):
    H = R.shape[0]
    xg = x @ W + b
    if forget_gate_bias:
        xg = xg.at[..., H:2 * H].add(forget_gate_bias)
    xg = jnp.swapaxes(xg, 0, 1)  # [T, B, 4H]
    if reverse:
        xg = jnp.flip(xg, axis=0)
    interpret = jax.default_backend() != "tpu"
    out, hT, cT = _fused_recurrence(xg, R, h0, c0, peephole,
                                    interpret=interpret)
    if reverse:
        out = jnp.flip(out, axis=0)
    return jnp.swapaxes(out, 0, 1), (hT, cT)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _fused(x, h0, c0, W, R, b, peephole, forget_gate_bias, reverse):
    return _kernel_forward(x, h0, c0, W, R, b, peephole, forget_gate_bias,
                           reverse)


def _fused_fwd(x, h0, c0, W, R, b, peephole, forget_gate_bias, reverse):
    out = _kernel_forward(x, h0, c0, W, R, b, peephole, forget_gate_bias,
                          reverse)
    return out, (x, h0, c0, W, R, b, peephole)


def _fused_bwd(forget_gate_bias, reverse, res, g):
    # backward recomputes through the XLA scan lowering: the recurrence
    # gradient is itself a reverse-time scan, which XLA compiles well; a
    # dedicated Pallas backward kernel is the remaining cuDNN-parity gap
    from deeplearning4j_tpu.ops.recurrent import lstm_layer

    x, h0, c0, W, R, b, peephole = res
    diff_args = (x, h0, c0, W, R, b) + (() if peephole is None else (peephole,))

    def ref(*args):
        if peephole is None:
            xx, hh, cc, WW, RR, bb = args
            pp = None
        else:
            xx, hh, cc, WW, RR, bb, pp = args
        return lstm_layer(xx, hh, cc, WW, RR, bb, peephole=pp,
                          forget_gate_bias=forget_gate_bias, reverse=reverse)

    _, vjp = jax.vjp(ref, *diff_args)
    grads = vjp(g)
    if peephole is None:
        grads = grads + (None,)
    return grads


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_lstm_layer(x, h0, c0, W, R, b, *, peephole=None,
                     forget_gate_bias=0.0, reverse=False):
    """Drop-in accelerated impl of the "lstm_layer" op (same signature)."""
    return _fused(x, h0, c0, W, R, b, peephole, float(forget_gate_bias),
                  bool(reverse))


def _lstm_requires(x, h0, c0, W, R, b, *, peephole=None, **kw):
    # structural: a VMEM-feasible tile must exist
    H = R.shape[0]
    return lstm_tile(x.shape[0], H,
                     rdtype_bytes=R.dtype.itemsize) is not None


def _lstm_applicable(x, h0, c0, W, R, b, *, peephole=None, **kw):
    # perf heuristic: lane-aligned hidden size, sublane-aligned batch
    H = R.shape[0]
    return H % 128 == 0 and x.shape[0] % 8 == 0


register_impl("lstm_layer", platform="pallas", predicate=_lstm_applicable,
              requires=_lstm_requires, priority=1)(fused_lstm_layer)
