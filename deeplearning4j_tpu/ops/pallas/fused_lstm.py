"""Fused LSTM recurrence — single-kernel sequence loop.

Reference analog: CudnnLSTMHelper (deeplearning4j-cuda ::
org.deeplearning4j.nn.layers.recurrent.CudnnLSTMHelper), which replaces the
per-timestep Java loop with one cuDNN persistent-RNN launch. Same split
here: the [B*T, F]x[F,4H] input projection is left to XLA (it is a single
MXU-shaped matmul); the irreducibly-sequential part — T iterations of
h@R + gate elementwise — runs inside ONE Pallas kernel with h/c resident in
VMEM scratch and R pinned in VMEM, so the recurrence never round-trips HBM
per step (the reason cuDNN's persistent kernels win).

Grid: (T,) sequential; xg block [B, 4H] per step; gate order IFOG matching
ops/recurrent.lstm_layer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.registry import register_impl


def _lstm_kernel(xg_ref, r_ref, h0_ref, c0_ref, out_ref, hT_ref, cT_ref,
                 h_scr, c_scr, *, hidden):
    t = pl.program_id(0)
    nt = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    g = xg_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h_scr[:], r_ref[:].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [B, 4H]
    i = jax.nn.sigmoid(g[:, :H])
    f = jax.nn.sigmoid(g[:, H:2 * H])
    o = jax.nn.sigmoid(g[:, 2 * H:3 * H])
    z = jnp.tanh(g[:, 3 * H:])
    c_new = f * c_scr[:] + i * z
    h_new = o * jnp.tanh(c_new)
    c_scr[:] = c_new
    h_scr[:] = h_new
    out_ref[0] = h_new.astype(out_ref.dtype)

    @pl.when(t == nt - 1)
    def _final():
        hT_ref[:] = h_new.astype(hT_ref.dtype)
        cT_ref[:] = c_new.astype(cT_ref.dtype)


def _fused_recurrence(xg, R, h0, c0, *, interpret):
    """xg [T, B, 4H] time-major pre-projected gates; returns
    (outputs [T, B, H], hT, cT)."""
    T, B, G = xg.shape
    H = G // 4
    out, hT, cT = pl.pallas_call(
        functools.partial(_lstm_kernel, hidden=H),
        out_shape=(jax.ShapeDtypeStruct((T, B, H), xg.dtype),
                   jax.ShapeDtypeStruct((B, H), xg.dtype),
                   jax.ShapeDtypeStruct((B, H), xg.dtype)),
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, G), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((H, G), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((B, H), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(xg, R, h0, c0)
    return out, hT, cT


def fused_lstm_layer(x, h0, c0, W, R, b, *, peephole=None,
                     forget_gate_bias=0.0, reverse=False):
    """Drop-in accelerated impl of the "lstm_layer" op (same signature)."""
    H = R.shape[0]
    xg = x @ W + b
    if forget_gate_bias:
        xg = xg.at[..., H:2 * H].add(forget_gate_bias)
    xg = jnp.swapaxes(xg, 0, 1)  # [T, B, 4H]
    if reverse:
        xg = jnp.flip(xg, axis=0)
    interpret = jax.default_backend() != "tpu"
    out, hT, cT = _fused_recurrence(xg, R, h0, c0, interpret=interpret)
    if reverse:
        out = jnp.flip(out, axis=0)
    return jnp.swapaxes(out, 0, 1), (hT, cT)


def _lstm_requires(x, h0, c0, W, R, b, *, peephole=None, **kw):
    # structural: the kernel has no peephole terms (GravesLSTM stays on scan)
    return peephole is None


def _lstm_applicable(x, h0, c0, W, R, b, *, peephole=None, **kw):
    # perf heuristic: lane-aligned hidden size, batch fits a VMEM tile
    H = R.shape[0]
    return H % 128 == 0 and x.shape[0] % 8 == 0


register_impl("lstm_layer", platform="pallas", predicate=_lstm_applicable,
              requires=_lstm_requires, priority=1)(fused_lstm_layer)
