"""Pallas (Mosaic) TPU kernels — the cuDNN-helper tier.

Reference analog: deeplearning4j-cuda's LayerHelper kernels
(CudnnConvolutionHelper, CudnnLSTMHelper, ...) and libnd4j's platform
helpers (libnd4j/include/ops/declarable/platform/cudnn/). Each kernel here
registers over a named op in the registry via register_impl with an
applicability predicate — the runtime-selection seam SURVEY.md §2.1 calls
for. Importing this package performs the registration.
"""

from deeplearning4j_tpu.ops.pallas.flash_attention import flash_attention
from deeplearning4j_tpu.ops.pallas.fused_lstm import fused_lstm_layer
from deeplearning4j_tpu.ops.pallas.fused_gru import fused_gru_layer
from deeplearning4j_tpu.ops.pallas.lrn import pallas_lrn

__all__ = ["flash_attention", "fused_lstm_layer", "fused_gru_layer",
           "pallas_lrn"]
