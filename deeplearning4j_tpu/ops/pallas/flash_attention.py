"""Flash attention — blocked online-softmax Pallas kernels, fwd AND bwd.

Reference analog: the role cuDNN's fused multi-head attention plays for the
reference's SelfAttentionLayer (deeplearning4j-cuda LayerHelper tier); the
algorithm is FlashAttention-style blocking: the [Tq, Tk] score matrix is
never materialized in HBM — each (batch*head, q-block) program streams
k/v-blocks through VMEM maintaining running max/denominator, so HBM traffic
is O(T*D) instead of O(T^2).

Forward grid: (B*H, Tq/bq, Tk/bk) with the k-axis innermost; m/l/acc scratch
persists across the k iterations of one q-block (TPU grids execute the
minor-most dimension sequentially). The forward also emits the per-row
logsumexp, which makes the backward pass O(T*D) too: instead of
re-materializing softmax(QK^T), the dq kernel (q-blocks outer) and the dk/dv
kernel (k-blocks outer) recompute only one [bq, bk] probability tile at a
time as exp(s - lse).

Block-level primitives ``flash_block_fwd`` / ``flash_block_bwd`` are exposed
for ring attention (parallel/sequence.py): the ring merges per-step (o, lse)
pairs online and runs the backward with the *global* lse, so sequence-
parallel long-context training inherits the same sub-quadratic memory.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.registry import register_impl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _sds(shape, dtype, vma=None):
    """ShapeDtypeStruct with varying-mesh-axes annotation when running under
    shard_map (ring attention) with VMA checking on."""
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
        except TypeError:  # pragma: no cover — pre-0.7 jax tracks no VMA
            pass           # (shard_map runs check_rep there; see _compat)
    return jax.ShapeDtypeStruct(shape, dtype)


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, *rest, causal, scale, block_q, block_k,
                  seq_k, has_kmask):
    if has_kmask:
        km_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        km_ref = None
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal block skipping: a k-block whose first key is past this q-block's
    # last query contributes nothing — skip its FLOPs entirely (roughly
    # halves the causal work; the standard flash-attention optimization)
    visible = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(visible)
    def _body():
        # native-dtype MXU dot with f32 accumulation (bf16 inputs run at
        # full MXU rate); the scale is applied to the f32 product
        q = q_ref[0]                                      # [bq, D]
        k = k_ref[0]                                      # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 1)
        # mask the ragged tail block (out-of-bounds key columns read padding)
        s = jnp.where(kpos < seq_k, s, -jnp.inf)
        if km_ref is not None:
            # key-padding mask [1, bk]: broadcast over the q rows. The
            # existing -inf machinery (m_safe / p guard / lse=+inf) already
            # handles rows where every key is masked.
            s = jnp.where(km_ref[0] > 0, s, -jnp.inf)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 0)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)

        m_prev = m_scr[:]                                  # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        # all-masked rows keep m=-inf; guard the exp
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1, keepdims=True)
        v = v_ref[0]
        # zero padded tail rows of v: 0-weight x NaN-padding would poison the dot
        vrow = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        v = jnp.where(vrow < seq_k, v, jnp.zeros((), v.dtype))
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        m_safe = jnp.where(jnp.isfinite(m_scr[:]), m_scr[:], 0.0)
        # +inf for fully-masked rows so the bwd's exp(s - lse) is exactly 0
        lse_ref[0] = jnp.where(l > 0.0, m_safe + jnp.log(jnp.maximum(l, 1e-30)),
                               jnp.inf)


def _flash_forward(q, k, v, *, causal, scale, block_q, block_k, interpret,
                   kmask=None, vma=None):
    """Returns (out [B,H,Tq,D], lse [B,H,Tq,1] float32).

    ``kmask``: optional key-padding mask [B, Tk] (>0 = key visible) — the
    shape DL4J's per-example feature masks reduce to; blocked per (batch,
    k-block) with the batch index derived as ``b // H`` from the flattened
    batch*head grid axis, so the mask is never materialized per-head."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)
    grid = (B * H, pl.cdiv(Tq, bq), pl.cdiv(Tk, bk))
    in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [qf, kf, vf]
    if kmask is not None:
        # [B, 1, Tk] so the block's trailing dims are (1, bk) — Mosaic's
        # (8, 128)-divisibility rule applies to the last two dims and a
        # middle dim of exactly 1 satisfies the equal-to-array case
        in_specs.append(pl.BlockSpec((1, 1, bk), lambda b, i, j: (b // H, 0, j),
                                     memory_space=pltpu.VMEM))
        operands.append(kmask.astype(jnp.float32).reshape(B, 1, Tk))
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, seq_k=Tk,
                          has_kmask=kmask is not None),
        out_shape=(_sds(qf.shape, q.dtype, vma),
                   _sds((B * H, Tq, 1), jnp.float32, vma)),
        grid=grid,
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return out.reshape(B, H, Tq, D), lse.reshape(B, H, Tq, 1)


# --------------------------------------------------------------------------
# backward kernels
# --------------------------------------------------------------------------


def _recompute_p(q_ref, k_ref, lse_ref, km_ref, *, qi, ki, causal, scale,
                 block_q, block_k, seq_q, seq_k):
    """Recompute one [bq, bk] probability tile exp(s - lse), fully masked."""
    q = q_ref[0]
    k = k_ref[0]
    krow = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, k.shape, 0)
    k = jnp.where(krow < seq_k, k, jnp.zeros((), k.dtype))
    s = scale * jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse_ref[0])                           # lse [bq, 1]
    qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                   (block_q, block_k), 1)
    valid = (qpos < seq_q) & (kpos < seq_k)
    if km_ref is not None:
        valid &= km_ref[0] > 0                            # [1, bk] broadcast
    if causal:
        valid &= qpos >= kpos
    return jnp.where(valid, p, 0.0), k, valid


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                     causal, scale, block_q, block_k, seq_q, seq_k, has_kmask):
    if has_kmask:
        km_ref, dq_ref, dq_scr = rest
    else:
        km_ref = None
        dq_ref, dq_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    visible = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(visible)
    def _body():
        p, k, valid = _recompute_p(q_ref, k_ref, lse_ref, km_ref, qi=qi, ki=ki,
                                   causal=causal, scale=scale, block_q=block_q,
                                   block_k=block_k, seq_q=seq_q, seq_k=seq_k)
        do = do_ref[0]
        v = v_ref[0]
        vrow = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        v = jnp.where(vrow < seq_k, v, jnp.zeros((), v.dtype))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq,bk]
        ds = jnp.where(valid, p * (dp - delta_ref[0]), 0.0)
        dq_scr[:] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                      causal, scale, block_q, block_k, seq_q, seq_k,
                      has_kmask):
    if has_kmask:
        km_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        km_ref = None
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    visible = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(visible)
    def _body():
        p, _, valid = _recompute_p(q_ref, k_ref, lse_ref, km_ref, qi=qi, ki=ki,
                                   causal=causal, scale=scale, block_q=block_q,
                                   block_k=block_k, seq_q=seq_q, seq_k=seq_k)
        q = q_ref[0]
        qrow = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, q.shape, 0)
        q = jnp.where(qrow < seq_q, q, jnp.zeros((), q.dtype))
        do = do_ref[0]
        do = jnp.where(qrow < seq_q, do, jnp.zeros((), do.dtype))
        # dv += p^T @ do
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        v = v_ref[0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq,bk]
        ds = jnp.where(valid, p * (dp - delta_ref[0]), 0.0)
        # dk += ds^T @ q, with the chain-rule scale
        dk_scr[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, do, lse, delta, *, causal, scale, block_q,
                    block_k, interpret, kmask=None, vma=None):
    """O(T*D)-memory flash backward. lse/delta: [B,H,Tq,1] float32.

    Returns (dq, dk, dv) in float32 (callers cast to input dtypes)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)
    dof = do.reshape(B * H, Tq, D)
    lsef = lse.reshape(B * H, Tq, 1)
    deltaf = delta.reshape(B * H, Tq, 1)
    has_km = kmask is not None
    kmf = kmask.astype(jnp.float32).reshape(B, 1, Tk) if has_km else None

    q_spec = pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                          memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
    operands = [qf, kf, vf, dof, lsef, deltaf]
    if has_km:
        in_specs.append(pl.BlockSpec((1, 1, bk), lambda b, i, j: (b // H, 0, j),
                                     memory_space=pltpu.VMEM))
        operands.append(kmf)

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, seq_q=Tq, seq_k=Tk,
                          has_kmask=has_km),
        out_shape=_sds(qf.shape, jnp.float32, vma),
        grid=(B * H, pl.cdiv(Tq, bq), pl.cdiv(Tk, bk)),
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*operands)

    # k-blocks outer, q-blocks inner: index maps swap i<->j roles
    q_spec2 = pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    k_spec2 = pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0),
                           memory_space=pltpu.VMEM)
    row_spec2 = pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0),
                             memory_space=pltpu.VMEM)
    in_specs2 = [q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2]
    if has_km:
        in_specs2.append(pl.BlockSpec((1, 1, bk),
                                      lambda b, j, i: (b // H, 0, j),
                                      memory_space=pltpu.VMEM))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, seq_q=Tq, seq_k=Tk,
                          has_kmask=has_km),
        out_shape=(_sds(kf.shape, jnp.float32, vma),
                   _sds(vf.shape, jnp.float32, vma)),
        grid=(B * H, pl.cdiv(Tk, bk), pl.cdiv(Tq, bq)),
        in_specs=in_specs2,
        out_specs=(k_spec2, k_spec2),
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*operands)
    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


# --------------------------------------------------------------------------
# block-level primitives (used here and by ring attention)
# --------------------------------------------------------------------------


def flash_block_fwd(q, k, v, *, causal, scale, block_q=512, block_k=1024,
                    kmask=None, vma=None):
    """(o, lse) for one attention block pair; lse is [B,H,Tq,1] float32."""
    return _flash_forward(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=_interpret(), kmask=kmask, vma=vma)


def flash_block_bwd(q, k, v, do, lse, delta, *, causal, scale,
                    block_q=1024, block_k=1024, kmask=None, vma=None):
    """(dq, dk, dv) float32 given the (possibly global) lse and
    delta = rowsum(do * o)."""
    return _flash_backward(q, k, v, do, lse, delta, causal=causal,
                           scale=scale, block_q=block_q, block_k=block_k,
                           interpret=_interpret(), kmask=kmask, vma=vma)


# --------------------------------------------------------------------------
# custom_vjp wiring
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, kmask, causal, scale, block_q, block_k):
    out, _ = _flash_forward(q, k, v, causal=causal, scale=scale,
                            block_q=block_q, block_k=block_k,
                            interpret=_interpret(), kmask=kmask)
    return out


def _flash_fwd(q, k, v, kmask, causal, scale, block_q, block_k):
    out, lse = _flash_forward(q, k, v, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=_interpret(), kmask=kmask)
    return out, (q, k, v, kmask, out, lse)


def bwd_tiles(block_q, block_k, head_dim, vmem_budget=15 << 20):
    """VMEM-budget-aware backward tile sizes.

    Measured on v5e: the bwd kernels want much larger tiles than the fwd
    (1024x1024 is ~3x faster than 128x128 at T=8192 — grid overhead
    dominates small tiles), but the [bq, bk] f32 probability/ds tiles plus
    the [tile, D] operands must fit the ~16M scoped-VMEM limit, so large
    head dims scale the tiles back down. The budget is calibrated against
    the 16M scoped-VMEM limit: (1024,1024) at head_dim 128 estimates 14.7M
    and compiles/runs on v5e; (2048,1024) estimates 25M and is rejected by
    Mosaic (measured 18.79M actual). Tiles also clamp to the actual
    sequence lengths inside _flash_backward."""
    bq, bk = max(block_q, 1024), max(block_k, 1024)

    def est(bq, bk):
        return 3 * bq * bk * 4 + 4 * max(bq, bk) * head_dim * 4

    while est(bq, bk) > vmem_budget and max(bq, bk) > 128:
        if bq >= bk:
            bq //= 2
        else:
            bk //= 2
    return bq, bk


def _flash_bwd(causal, scale, block_q, block_k, res, g):
    # flash backward: only [bq, bk] probability tiles are ever materialized,
    # recomputed from the saved logsumexp — HBM stays O(T*D), which is what
    # makes long-context *training* (not just inference) sub-quadratic
    q, k, v, kmask, out, lse = res
    bq, bk = bwd_tiles(block_q, block_k, q.shape[-1])
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(
        axis=-1, keepdims=True)
    dq, dk, dv = _flash_backward(q, k, v, g, lse, delta, causal=causal,
                                 scale=scale, block_q=bq, block_k=bk,
                                 interpret=_interpret(), kmask=kmask)
    dkm = None if kmask is None else jnp.zeros_like(kmask)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dkm


_flash.defvjp(_flash_fwd, _flash_bwd)


def _as_key_padding(mask, batch, seq_k):
    """Reduce a broadcastable-to-[B,H,Tq,Tk] mask to a [B, Tk] key-padding
    mask, or return None (mask=None) / raise (not expressible).

    DL4J feature masks arrive as [B, Tk] per-example time masks; the layer
    tier (nn/layers/attention.py:_attn_mask) lifts them to [B,1,1,Tk]. Both
    forms — plus head/query-broadcast variants — reduce losslessly."""
    if mask is None:
        return None
    m = jnp.asarray(mask)
    if m.ndim == 4 and m.shape[1] == 1 and m.shape[2] == 1:
        m = m[:, 0, 0, :]
    elif m.ndim != 2:
        raise ValueError(
            f"flash_attention supports key-padding masks ([B, Tk] or "
            f"[B, 1, 1, Tk]); got mask shape {mask.shape} — the registry "
            f"predicate routes general masks to the XLA lowering")
    if m.shape[-1] != seq_k:
        raise ValueError(f"mask key axis {m.shape[-1]} != Tk {seq_k}")
    m = jnp.broadcast_to(m, (batch, seq_k))
    return m.astype(jnp.float32)


def _is_key_padding(mask, q, k):
    if mask is None:
        return True
    shp = tuple(mask.shape)
    if len(shp) == 4:
        return (shp[1] == 1 and shp[2] == 1 and shp[3] == k.shape[-2]
                and shp[0] in (1, q.shape[0]))
    return (len(shp) == 2 and shp[1] == k.shape[-2]
            and shp[0] in (1, q.shape[0]))


def flash_attention(q, k, v, *, mask=None, bias=None, scale=None,
                    causal=False, block_q: int = 512, block_k: int = 1024):
    """Public entry: same signature as the XLA dot_product_attention.

    Default tiles are the v5e sweet spot measured at T=8192 (fwd 512x1024,
    bwd 1024x1024 via _flash_bwd): small 128-tiles leave >2x on the table —
    grid overhead dominates; 2048-tiles exceed the 16M VMEM scoped limit.
    Tiles clamp to the actual sequence lengths for short inputs.

    ``mask`` accepts key-padding masks ([B, Tk] or the layer tier's
    [B, 1, 1, Tk]); general [Tq, Tk]-varying masks are structurally
    rejected (registry routes them to the XLA lowering)."""
    if bias is not None:
        raise ValueError(
            "flash_attention does not support additive logit biases; the "
            "registry's requires predicate routes bias calls to the XLA "
            "lowering")
    km = _as_key_padding(mask, q.shape[0], k.shape[-2])
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash(q, k, v, km, causal, float(scale), block_q, block_k)


def _flash_requires(q, k, v, *, mask=None, scale=None, causal=False, **kw):
    # structural: masks are supported iff they reduce to a key-padding mask
    # over Tk; the kernel's causal mask is start-aligned (query i sees keys
    # <= i) which only matches the XLA lowering's end-aligned tril when
    # Tq == Tk. Additive logit biases (the import optimizer's fused
    # exporter-mask form) are not expressible in the kernel — XLA lowering.
    return (kw.get("bias") is None
            and _is_key_padding(mask, q, k)
            and (not causal or q.shape[-2] == k.shape[-2]))


def _flash_applicable(q, k, v, *, mask=None, scale=None, causal=False, **kw):
    # perf heuristic: long-sequence, lane/block-aligned shapes. head_dim 64
    # (the BERT-class geometry) runs natively: the QK^T contraction fills
    # half the MXU's K dimension but the kernel's win is HBM traffic, and
    # the P@V / dV contractions (over bk) stay full-rate.
    #
    # The T >= 2048 threshold is MEASURED, not assumed (r4, v5e two-point
    # A/B, BASELINE.md): at T=512/1024 XLA's fused attention wins (0.27x-
    # 0.92x for the kernel across D=64/128, fwd and train — the [T,T]
    # scores still fit on-chip and the kernel's grid overhead dominates);
    # from T=2048 the kernel wins ~1.7x and grows with T (2.7-2.9x at
    # 4096). The r1-r3 threshold of 512 was selecting the kernel in
    # regimes where it loses.
    return (q.shape[-2] >= 2048 and q.shape[-1] % 64 == 0
            and q.shape[-2] % 128 == 0 and k.shape[-2] % 128 == 0)


register_impl("dot_product_attention", platform="pallas",
              predicate=_flash_applicable, requires=_flash_requires,
              priority=1)(flash_attention)
