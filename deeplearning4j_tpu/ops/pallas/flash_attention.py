"""Flash attention — blocked online-softmax Pallas kernel.

Reference analog: the role cuDNN's fused multi-head attention plays for the
reference's SelfAttentionLayer (deeplearning4j-cuda LayerHelper tier); the
algorithm is FlashAttention-style blocking: the [Tq, Tk] score matrix is
never materialized in HBM — each (batch*head, q-block) program streams
k/v-blocks through VMEM maintaining running max/denominator, so HBM traffic
is O(T*D) instead of O(T^2).

Grid: (B*H, Tq/bq, Tk/bk) with the k-axis innermost; m/l/acc scratch
persists across the k iterations of one q-block (TPU grids execute the
minor-most dimension sequentially). Registered over "dot_product_attention"
for long unmasked sequences; the backward pass recomputes attention via the
XLA lowering (memory-optimal fwd, standard bwd).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.registry import register_impl


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  causal, scale, block_q, block_k, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal block skipping: a k-block whose first key is past this q-block's
    # last query contributes nothing — skip its FLOPs entirely (roughly
    # halves the causal work; the standard flash-attention optimization)
    visible = (qi * block_q + block_q - 1 >= ki * block_k) if causal else True

    @pl.when(visible)
    def _body():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, D]
        k = k_ref[0].astype(jnp.float32)                  # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 1)
        # mask the ragged tail block (out-of-bounds key columns read padding)
        s = jnp.where(kpos < seq_k, s, -jnp.inf)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32,
                                                           (block_q, block_k), 0)
            s = jnp.where(qpos >= kpos, s, -jnp.inf)

        m_prev = m_scr[:]                                  # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        # all-masked rows keep m=-inf; guard the exp
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
        l_scr[:] = l_scr[:] * corr + p.sum(axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)
        # zero padded tail rows of v: 0-weight x NaN-padding would poison the dot
        vrow = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        v = jnp.where(vrow < seq_k, v, 0.0)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] /
                    jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal, scale, block_q, block_k, interpret):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)
    grid = (B * H, pl.cdiv(Tq, bq), pl.cdiv(Tk, bk))
    out = pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal, scale=scale,
                          block_q=bq, block_k=bk, seq_k=Tk),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    return _flash(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, res, g):
    # recompute-standard backward: memory already saved on the forward; the
    # bwd uses XLA's fused softmax-attention gradient
    q, k, v = res

    def ref(q, k, v):
        from deeplearning4j_tpu.ops.attention import dot_product_attention

        return dot_product_attention(q, k, v, scale=scale, causal=causal)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, mask=None, scale=None, causal=False,
                    block_q: int = 128, block_k: int = 128):
    """Public entry: same signature as the XLA dot_product_attention."""
    if mask is not None:
        raise ValueError("flash_attention kernel handles mask=None only "
                         "(causal flag supported); registry predicate "
                         "routes masked calls to the XLA lowering")
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _flash(q, k, v, causal, float(scale), block_q, block_k)


def _flash_requires(q, k, v, *, mask=None, scale=None, causal=False, **kw):
    # structural: the kernel cannot express masks, and its causal mask is
    # start-aligned (query i sees keys <= i) which only matches the XLA
    # lowering's end-aligned tril when Tq == Tk
    return mask is None and (not causal or q.shape[-2] == k.shape[-2])


def _flash_applicable(q, k, v, *, mask=None, scale=None, causal=False, **kw):
    # perf heuristic: long-sequence, lane/block-aligned shapes
    return (q.shape[-2] >= 512 and q.shape[-1] % 128 == 0
            and q.shape[-2] % 128 == 0 and k.shape[-2] % 128 == 0)


register_impl("dot_product_attention", platform="pallas",
              predicate=_flash_applicable, requires=_flash_requires,
              priority=1)(flash_attention)
