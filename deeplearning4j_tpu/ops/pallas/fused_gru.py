"""Fused GRU recurrence — single-kernel sequence loop, tiled over hidden.

Reference analog: cuDNN's CUDNN_GRU persistent-RNN mode (the same
cudnnRNNForward/Backward family CudnnLSTMHelper drives for LSTM; DL4J's GRU
layer runs the generic libnd4j gruCell loop — this kernel gives the TPU
build the fused tier the reference reserved for LSTM). Design mirrors
ops/pallas/fused_lstm.py exactly: the [B*T, F]x[F,3H] input projection
stays one XLA MXU matmul; the irreducibly-sequential h@R chain runs inside
ONE Pallas kernel with h resident in VMEM scratch (grid (T, H/Hb), hidden
tile innermost, double-buffered h), R pre-laid-out as [nH, H, 3*Hb] bf16
panels (XLA's own default-precision truncation for f32 dots — see the
precision note in fused_lstm.py).

Gate semantics match ops/recurrent.gru_layer (order r, z, n with cuDNN's
linear-before-reset coupling): r = s(xr + hr), z = s(xz + hz),
n = tanh(xn + r * hn), h' = (1-z)*n + z*h — the xg and hg projections must
therefore stay SEPARATE inside the kernel (n mixes them through r).

Backward: reverse-time Pallas kernel with the cuDNN reserve-space strategy:
the training forward saves post-activation r, z, n and the raw recurrent
candidate projection hg_n (each [T, B, H] f32), so the backward never
re-runs h@R. Per reverse step it forms the three pre-activation gate
gradients and the dh carry — z*dh_tot (direct path) plus
[ga_r, ga_z, r*ga_n] @ R^T against pre-transposed panels — and the final
carry IS dh0. Everything non-sequential (dW/dR/db/dx) is assembled outside
as large MXU matmuls, exactly the cudnnRNNBackwardWeights split.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.common.env import env
from deeplearning4j_tpu.ops.pallas.fused_lstm import (_interpret, _pad_gates,
                                                      _pad_to_lanes,
                                                      _panel_dtype)
from deeplearning4j_tpu.ops.registry import register_impl


def _gru_kernel(xg_ref, r_ref, h0_ref, out_ref, hT_ref, *rest, hb,
                save_residuals):
    if save_residuals:
        rr_ref, rz_ref, rn_ref, rhgn_ref = rest[:4]
        hprev_scr, hnext_scr = rest[4:]
    else:
        hprev_scr, hnext_scr = rest
    # grid (nb, T, nj): batch block outermost (r4) — see fused_lstm.py
    t = pl.program_id(1)
    j = pl.program_id(2)
    nt = pl.num_programs(1)
    nj = pl.num_programs(2)

    @pl.when((t == 0) & (j == 0))
    def _init():
        hprev_scr[:] = h0_ref[:].astype(jnp.float32)

    cols = (slice(None), pl.ds(j * hb, hb))
    # recurrent projection for hidden slice j from the FULL previous h
    hg = jax.lax.dot_general(
        hprev_scr[:].astype(r_ref.dtype), r_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [B, 3*hb]
    xg = xg_ref[0, 0].astype(jnp.float32)              # [B, 3*hb]
    r = jax.nn.sigmoid(xg[:, :hb] + hg[:, :hb])
    z = jax.nn.sigmoid(xg[:, hb:2 * hb] + hg[:, hb:2 * hb])
    hgn = hg[:, 2 * hb:]
    n = jnp.tanh(xg[:, 2 * hb:] + r * hgn)
    h_old = hprev_scr[cols]
    h_new = (1.0 - z) * n + z * h_old
    hnext_scr[cols] = h_new
    out_ref[0] = h_new.astype(out_ref.dtype)
    if save_residuals:
        rr_ref[0] = r
        rz_ref[0] = z
        rn_ref[0] = n
        rhgn_ref[0] = hgn

    @pl.when(j == nj - 1)
    def _advance():
        hprev_scr[:] = hnext_scr[:]

    @pl.when(t == nt - 1)
    def _final():
        hT_ref[:] = h_new.astype(hT_ref.dtype)


def gru_tile(B, H, rdtype_bytes=2, budget=13 << 20, save_residuals=False):
    """Largest hidden tile (multiple of 128, dividing H) for a batch block
    of B rows; None when even Hb=128 does not fit. Same accounting
    discipline as fused_lstm.lstm_tile (grid-varying blocks are
    double-buffered by the pipeline and count twice; batch-block-only
    variation re-fetches at chunk boundaries and counts once)."""
    for hb in (H, 1024, 512, 256, 128):
        if hb > H or H % hb:
            continue
        r_bufs = 1 if hb == H else 2           # grid-invariant panel: once
        est = (r_bufs * H * 3 * hb * rdtype_bytes  # R panel
               + 2 * B * 3 * hb * 4            # xg block (dbl-buffered)
               + 2 * 2 * B * hb * 4            # out/hT tiles (dbl)
               + 2 * B * H * 4                 # h double buffer
               + B * H * 4)                    # h0 (refetch amortized)
        if save_residuals:
            est += 2 * 4 * B * hb * 4          # r/z/n/hgn tiles (dbl)
        if est <= budget:
            return hb
    return None


def gru_bwd_tile(B, H, rdtype_bytes=2, budget=13 << 20):
    for hb in (H, 1024, 512, 256, 128):
        if hb > H or H % hb:
            continue
        r_bufs = 1 if hb == H else 2
        est = (r_bufs * H * 3 * hb * rdtype_bytes  # R^T panel
               + 2 * 6 * B * hb * 4            # r/z/n/hgn/hprev/dout (dbl)
               + 2 * 3 * B * hb * 4            # dgr/dgz/dgn out tiles (dbl)
               + B * H * 4                     # dh0 full-H block
               + 2 * B * H * 4)                # dh carry + dh accumulator
        if est <= budget:
            return hb
    return None


def gru_plan(B, H, rdtype_bytes=2, save_residuals=False):
    from deeplearning4j_tpu.ops.pallas.fused_lstm import _plan

    return _plan(gru_tile, B, H, rdtype_bytes=rdtype_bytes,
                 save_residuals=save_residuals)


def gru_bwd_plan(B, H, rdtype_bytes=2):
    from deeplearning4j_tpu.ops.pallas.fused_lstm import _bwd_plan

    return _bwd_plan(gru_bwd_tile, B, H, rdtype_bytes=rdtype_bytes)


def _fused_gru_recurrence(xg, R, h0, *, interpret, save_residuals=False):
    """xg [T, B, 3H] time-major; returns (out [T, B, H], hT,
    residuals-or-None) where residuals = (r, z, n, hg_n) each [T, B, H] f32
    post-activation — the reserve space for the backward kernel."""
    T, B, G = xg.shape
    H = G // 3
    pdt = _panel_dtype(R.dtype)
    Bc, hb = gru_plan(B, H, rdtype_bytes=jnp.dtype(pdt).itemsize,
                      save_residuals=save_residuals)
    if hb is None:
        raise ValueError(f"no VMEM-feasible GRU tile for B={B}, H={H}")
    nb = B // Bc
    nj = H // hb
    Rl = (R.reshape(H, 3, nj, hb).transpose(2, 0, 1, 3)
          .reshape(nj, H, 3 * hb).astype(pdt))
    xgl = (xg.reshape(T, B, 3, nj, hb).transpose(0, 3, 1, 2, 4)
           .reshape(T, nj, B, 3 * hb))

    tile_tj = pl.BlockSpec((1, Bc, hb), lambda b, t, j: (t, b, j),
                           memory_space=pltpu.VMEM)
    out_shape = [jax.ShapeDtypeStruct((T, B, H), xg.dtype),
                 jax.ShapeDtypeStruct((B, H), xg.dtype)]
    out_specs = [
        tile_tj,
        pl.BlockSpec((Bc, hb), lambda b, t, j: (b, j),
                     memory_space=pltpu.VMEM),
    ]
    if save_residuals:
        for _ in range(4):                     # r, z, n, hg_n
            out_shape.append(jax.ShapeDtypeStruct((T, B, H), jnp.float32))
            out_specs.append(tile_tj)

    res = pl.pallas_call(
        functools.partial(_gru_kernel, hb=hb, save_residuals=save_residuals),
        out_shape=tuple(out_shape),
        grid=(nb, T, nj),
        in_specs=[
            pl.BlockSpec((1, 1, Bc, 3 * hb), lambda b, t, j: (t, j, b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, H, 3 * hb), lambda b, t, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Bc, H), lambda b, t, j: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((Bc, H), jnp.float32),
            pltpu.VMEM((Bc, H), jnp.float32),
        ],
        interpret=interpret,
    )(xgl, Rl, h0)
    if save_residuals:
        out, hT = res[:2]
        residuals = res[2:]
    else:
        (out, hT), residuals = res, None
    return out, hT, residuals


def _project_gates(x, W, b, reverse):
    xg = jnp.swapaxes(x @ W + b, 0, 1)         # [T, B, 3H]
    if reverse:
        xg = jnp.flip(xg, axis=0)
    return xg


def _kernel_forward(x, h0, W, R, b, reverse, save_residuals=False):
    xg = _project_gates(x, W, b, reverse)
    out, hT, residuals = _fused_gru_recurrence(
        xg, R, h0, interpret=_interpret(), save_residuals=save_residuals)
    if reverse:
        out = jnp.flip(out, axis=0)
    return (jnp.swapaxes(out, 0, 1), hT), residuals


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused(x, h0, W, R, b, reverse):
    out, _ = _kernel_forward(x, h0, W, R, b, reverse)
    return out


def _kernel_bwd_enabled(B, H, rdtype) -> bool:
    return (not env.gru_scan_bwd
            and gru_bwd_plan(
                B, H, rdtype_bytes=jnp.dtype(_panel_dtype(rdtype)).itemsize)[1]
            is not None)


def _fused_fwd(x, h0, W, R, b, reverse):
    save = _kernel_bwd_enabled(x.shape[0], R.shape[0], R.dtype)
    out, residuals = _kernel_forward(x, h0, W, R, b, reverse,
                                     save_residuals=save)
    return out, (x, h0, W, R, b, out[0], residuals)


def _gru_bwd_kernel(r_ref, z_ref, n_ref, hgn_ref, rt_ref, hprev_ref,
                    dout_ref, dgr_ref, dgz_ref, dgn_ref, dh0_ref,
                    dh_scr, dhn_scr, *, hb):
    """One reverse-time step for hidden slice j.

    dh_tot = dout_t + dh carry; then
      dn = dh_tot*(1-z);   ga_n = dn*(1-n^2)       (xg_n gradient)
      dz = dh_tot*(h_prev - n); ga_z = dz*z*(1-z)
      dr = ga_n*hg_n;      ga_r = dr*r*(1-r)
    carry' = z*dh_tot (direct path, per slice)
           + [ga_r, ga_z, r*ga_n] @ R^T (accumulated over slices).
    The final carry is dh0 — emitted on the last step. Grid (nb, T, nj)
    with the batch block outermost (r4), as in the forward.
    """
    t = pl.program_id(1)
    j = pl.program_id(2)
    nt = pl.num_programs(1)
    nj = pl.num_programs(2)

    @pl.when((t == 0) & (j == 0))
    def _init():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    cols = (slice(None), pl.ds(j * hb, hb))

    r = r_ref[0]
    z = z_ref[0]
    n = n_ref[0]
    hgn = hgn_ref[0]
    h_prev = hprev_ref[0].astype(jnp.float32)

    dh_tot = dout_ref[0].astype(jnp.float32) + dh_scr[cols]
    dn = dh_tot * (1.0 - z)
    ga_n = dn * (1.0 - n * n)
    dz = dh_tot * (h_prev - n)
    ga_z = dz * z * (1.0 - z)
    dr = ga_n * hgn
    ga_r = dr * r * (1.0 - r)
    dgr_ref[0] = ga_r
    dgz_ref[0] = ga_z
    dgn_ref[0] = ga_n

    pdt = rt_ref.dtype
    contrib = jax.lax.dot_general(
        ga_r.astype(pdt), rt_ref[0, 0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [B, H]
    contrib = contrib + jax.lax.dot_general(
        ga_z.astype(pdt), rt_ref[0, 1], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    contrib = contrib + jax.lax.dot_general(
        (r * ga_n).astype(pdt), rt_ref[0, 2], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == 0)
    def _first():
        dhn_scr[:] = contrib

    @pl.when(j != 0)
    def _acc():
        dhn_scr[:] = dhn_scr[:] + contrib

    # the direct z*dh_tot path lands only in this slice's columns
    dhn_scr[cols] = dhn_scr[cols] + z * dh_tot

    @pl.when(j == nj - 1)
    def _advance():
        dh_scr[:] = dhn_scr[:]

    # dh0 couples across hidden slices (each j adds a full-H matmul
    # contribution), so it can only be emitted once the LAST slice of the
    # final reverse step has accumulated — unlike the LSTM's dc0, which is
    # per-slice and writes tile-by-tile
    @pl.when((t == nt - 1) & (j == nj - 1))
    def _final():
        dh0_ref[:] = dhn_scr[:]


def _bwd_recurrence(residuals, R, hprev_seq, dout, *, plan, interpret):
    """Reverse-time kernel. residuals/hprev_seq/dout in KERNEL time order.
    Returns (ga_r, ga_z, ga_n — each [T, B, H] f32, kernel order — and
    dh0 [B, H]). ``plan`` = (Bc, hb), chosen independently of the
    forward's (see fused_lstm._bwd_recurrence)."""
    rr, rz, rn, rhgn = residuals
    T, B, H = rr.shape
    Bc, hb = plan
    nb = B // Bc
    nj = H // hb
    pdt = _panel_dtype(R.dtype)
    Rt = (R.reshape(H, 3, nj, hb).transpose(2, 1, 3, 0)   # [nj, 3, hb, H]
          .astype(pdt))

    revj = lambda b, t, j: (T - 1 - t, b, j)
    tile = pl.BlockSpec((1, Bc, hb), revj, memory_space=pltpu.VMEM)

    return pl.pallas_call(
        functools.partial(_gru_bwd_kernel, hb=hb),
        out_shape=(jax.ShapeDtypeStruct((T, B, H), jnp.float32),) * 3
        + (jax.ShapeDtypeStruct((B, H), jnp.float32),),
        grid=(nb, T, nj),
        in_specs=[
            tile, tile, tile, tile,                    # r, z, n, hg_n
            pl.BlockSpec((1, 3, hb, H), lambda b, t, j: (j, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            tile,                                      # h_prev
            tile,                                      # dout
        ],
        out_specs=(tile,) * 3 + (
            pl.BlockSpec((Bc, H), lambda b, t, j: (b, 0),
                         memory_space=pltpu.VMEM),),
        scratch_shapes=[
            pltpu.VMEM((Bc, H), jnp.float32),  # dh carry (stable per t)
            pltpu.VMEM((Bc, H), jnp.float32),  # dh accumulator
        ],
        interpret=interpret,
    )(rr, rz, rn, rhgn, Rt, hprev_seq, dout)


def _scan_bwd(reverse, res, g):
    from deeplearning4j_tpu.ops.recurrent import gru_layer

    x, h0, W, R, b = res

    def ref(xx, hh, WW, RR, bb):
        return gru_layer(xx, hh, WW, RR, bb, reverse=reverse)

    _, vjp = jax.vjp(ref, x, h0, W, R, b)
    return vjp(g)


def _fused_bwd(reverse, res, g):
    x, h0, W, R, b, out, residuals = res
    B, T, F = x.shape
    H = R.shape[0]
    if residuals is None:
        return _scan_bwd(reverse, (x, h0, W, R, b), g)
    plan = gru_bwd_plan(
        B, H, rdtype_bytes=jnp.dtype(_panel_dtype(R.dtype)).itemsize)

    g_out, g_hT = g
    rr = residuals[0]

    out_k = jnp.swapaxes(out, 0, 1)
    dout_k = jnp.swapaxes(g_out, 0, 1)
    if reverse:
        out_k = jnp.flip(out_k, axis=0)
        dout_k = jnp.flip(dout_k, axis=0)
    dout_k = dout_k.at[T - 1].add(g_hT)
    hprev_k = jnp.concatenate([h0[None].astype(out_k.dtype), out_k[:-1]], 0)

    ga_r, ga_z, ga_n, dh0 = _bwd_recurrence(
        residuals, R, hprev_k, dout_k, plan=plan, interpret=_interpret())
    # hg_n's gradient (for dR's n block and the recurrent path already
    # inside the kernel) is r*ga_n; cheap elementwise, XLA fuses it here
    ga_hn = rr * ga_n
    dgs_h = (ga_r, ga_z, ga_hn)                # h-path gate grads (for dR)
    dgs_x = (ga_r, ga_z, ga_n)                 # x-path gate grads (W/b/dx)

    xf = x.astype(jnp.float32)
    hpf = hprev_k.astype(jnp.float32)
    dR = jnp.concatenate(
        [jnp.einsum("tbh,tbg->hg", hpf, dg) for dg in dgs_h], axis=1)
    dgs_x_nat = (tuple(jnp.flip(dg, axis=0) for dg in dgs_x)
                 if reverse else dgs_x)
    dW = jnp.concatenate(
        [jnp.einsum("btf,tbg->fg", xf, dg) for dg in dgs_x_nat], axis=1)
    db = jnp.concatenate([dg.sum((0, 1)) for dg in dgs_x])
    Wf = W.astype(jnp.float32)
    dx_nat = sum(jax.lax.dot_general(
        dg, Wf[:, gi_ * H:(gi_ + 1) * H], (((2,), (1,)), ((), ())))
        for gi_, dg in enumerate(dgs_x_nat))           # [T, B, F]
    dx = jnp.swapaxes(dx_nat, 0, 1)
    return (dx.astype(x.dtype), dh0.astype(h0.dtype), dW.astype(W.dtype),
            dR.astype(R.dtype), db.astype(b.dtype))


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_gru_layer(x, h0, W, R, b, *, reverse=False):
    """Drop-in accelerated impl of the "gru_layer" op (same signature).

    Unaligned hidden sizes zero-pad to the next lane multiple. Padding is
    exact for GRU even though padded r/z sit at sigmoid(0)=0.5: padded
    lanes have hg_n = 0 and xg_n = 0, so n = tanh(0) = 0 and
    h' = (1-z)*0 + z*h with h0's padded lanes zero — h stays 0 through the
    whole recurrence. Backward: padded-lane output cotangents are zero
    (outputs are sliced), padded gate columns of R/W are zero, so every
    padded gate gradient vanishes (dn ∝ dh_tot = 0 there) and real-lane
    gradients are untouched — the pad/slice is exact, matching the
    fused-LSTM padding contract."""
    H = R.shape[0]
    Hp = _pad_to_lanes(H)
    if Hp == H:
        return _fused(x, h0, W, R, b, bool(reverse))
    padh = lambda a: jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(0, Hp - H)])
    Wp = _pad_gates(W, H, Hp, 1)
    Rp = _pad_gates(jnp.pad(R, [(0, Hp - H), (0, 0)]), H, Hp, 1)
    bp = _pad_gates(b, H, Hp, 0)
    out, hT = _fused(x, padh(h0), Wp, Rp, bp, bool(reverse))
    return out[..., :H], hT[..., :H]


def _gru_requires(x, h0, W, R, b, **kw):
    Hp = _pad_to_lanes(R.shape[0])
    rb = jnp.dtype(_panel_dtype(R.dtype)).itemsize
    return gru_plan(x.shape[0], Hp, rdtype_bytes=rb,
                    save_residuals=True)[1] is not None


def _gru_applicable(x, h0, W, R, b, **kw):
    """Same measured selection policy as the fused LSTM: the kernel wins
    when R is grid-invariant (one hidden tile spans H, fetched once, the
    recurrence fully VMEM-resident) — which r4's batch-blocked grid now
    achieves at large B too. Verified by the bench `kernels` mode A/B
    rows. Non-f32/bf16 dtypes stay on the XLA scan — the A/B evidence
    and the MXU panel layout cover only those."""
    if x.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    Hp = _pad_to_lanes(R.shape[0])
    rb = jnp.dtype(_panel_dtype(R.dtype)).itemsize
    return (x.shape[0] % 8 == 0
            and gru_plan(x.shape[0], Hp, rdtype_bytes=rb,
                         save_residuals=True)[1] == Hp)


register_impl("gru_layer", platform="pallas", predicate=_gru_applicable,
              requires=_gru_requires, priority=1)(fused_gru_layer)
