"""Local response normalization — Pallas kernel.

Reference analog: deeplearning4j-cuda CudnnLocalResponseNormalizationHelper
(the cuDNN LRN helper swapped into LocalResponseNormalization layers) /
libnd4j's lrn declarable op. TPU-first formulation: the sliding channel
window sum is a banded-matrix product — sq @ B where B[i, j] = 1 iff
|i - j| <= depth//2 — one MXU dot per row-block instead of `depth` shifted
VPU adds, with the [R, C] pixels blocked through VMEM. Backward recomputes
through the XLA lowering (same pattern as the flash-attention kernel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.registry import register_impl


def _lrn_kernel(x_ref, band_ref, o_ref, *, alpha, beta, k):
    x = x_ref[...].astype(jnp.float32)          # [br, C]
    band = band_ref[...].astype(jnp.float32)    # [C, C]
    sq = x * x
    ssum = jax.lax.dot_general(sq, band, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    o_ref[...] = (x / (k + alpha * ssum) ** beta).astype(o_ref.dtype)


def _lrn_forward(x, *, depth, alpha, beta, k, block_rows, interpret):
    orig_shape = x.shape
    C = orig_shape[-1]
    xf = x.reshape(-1, C)
    R = xf.shape[0]
    br = min(block_rows, R)
    # the XLA lowering's window spans offsets [-half, depth-1-half] (exactly
    # `depth` channels — asymmetric when depth is even). Output channel j of
    # sq @ band sums input channels i with band[i, j] = 1, so the condition
    # is on i - j.
    half = depth // 2
    idx = jnp.arange(C)
    off = idx[:, None] - idx[None, :]
    band = ((off >= -half) & (off <= depth - 1 - half)).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_lrn_kernel, alpha=alpha, beta=beta, k=k),
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        grid=(pl.cdiv(R, br),),
        in_specs=[
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xf, band)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _lrn(x, depth, alpha, beta, k, block_rows):
    interpret = jax.default_backend() != "tpu"
    return _lrn_forward(x, depth=depth, alpha=alpha, beta=beta, k=k,
                        block_rows=block_rows, interpret=interpret)


def _lrn_fwd(x, depth, alpha, beta, k, block_rows):
    return _lrn(x, depth, alpha, beta, k, block_rows), x


def _lrn_bwd(depth, alpha, beta, k, block_rows, x, g):
    def ref(x):
        from deeplearning4j_tpu.ops.convolution import lrn as xla_lrn

        return xla_lrn(x, depth=depth, alpha=alpha, beta=beta, k=k)

    _, vjp = jax.vjp(ref, x)
    return vjp(g)


_lrn.defvjp(_lrn_fwd, _lrn_bwd)


def pallas_lrn(x, *, depth=5, alpha=1e-4, beta=0.75, k=2.0,
               block_rows: int = 512):
    """Public entry: same signature as the XLA lrn lowering."""
    return _lrn(x, depth, float(alpha), float(beta), float(k), block_rows)


def _lrn_requires(x, *, depth=5, **kw):
    # structural: enough pixels to fill row blocks; modest channel count so
    # the [C, C] band plus a row block fit VMEM comfortably
    n = 1
    for d in x.shape[:-1]:
        n *= d
    return n >= 2048 and 32 <= x.shape[-1] <= 1024


def _lrn_applicable(x, *, depth=5, **kw):
    """DEMOTED off-by-default (r3, measured, two-point on-chip A/B at the
    AlexNet conv2 shape [64,27,27,256]): forward-only the kernel wins
    (0.194 vs 0.236 ms, 1.22x) but the TRAIN step loses 0.45x (1.60 vs
    0.72 ms) because this kernel's backward recomputes through the XLA
    lowering — the grad path pays kernel-fwd PLUS a full XLA fwd+bwd.
    Selection cannot see whether grads will flow, and training is the
    primary workload, so the default is the XLA path; force with
    DL4J_TPU_FORCE_PALLAS for inference-only use."""
    return False


register_impl("lrn", platform="pallas", predicate=_lrn_applicable,
              requires=_lrn_requires, priority=1)(pallas_lrn)
