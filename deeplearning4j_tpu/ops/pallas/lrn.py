"""Local response normalization — Pallas kernel.

Reference analog: deeplearning4j-cuda CudnnLocalResponseNormalizationHelper
(the cuDNN LRN helper swapped into LocalResponseNormalization layers) /
libnd4j's lrn declarable op. TPU-first formulation: the sliding channel
window sum is a banded-matrix product — sq @ B where B[i, j] = 1 iff
|i - j| <= depth//2 — one MXU dot per row-block instead of `depth` shifted
VPU adds, with the [R, C] pixels blocked through VMEM.

The backward (r4) is the same band trick in reverse: with
d = k + alpha*ssum, the chain rule gives
    dx = g * d^-beta - 2*alpha*beta * x * ((g * x * d^(-beta-1)) @ B^T),
so one kernel recomputes d (one band dot) and applies the correction (a
second dot contracting the band's other axis — no transposed copy is
materialized). No residuals are saved: LRN sits between convs where HBM
bandwidth is the scarce resource, and the recompute is 2 MXU dots.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.registry import register_impl


def _lrn_kernel(x_ref, band_ref, o_ref, *, alpha, beta, k):
    x = x_ref[...].astype(jnp.float32)          # [br, C]
    band = band_ref[...].astype(jnp.float32)    # [C, C]
    sq = x * x
    ssum = jax.lax.dot_general(sq, band, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    o_ref[...] = (x / (k + alpha * ssum) ** beta).astype(o_ref.dtype)


def _band(C, depth):
    # the XLA lowering's window spans offsets [-half, depth-1-half] (exactly
    # `depth` channels — asymmetric when depth is even). Output channel j of
    # sq @ band sums input channels i with band[i, j] = 1, so the condition
    # is on i - j.
    half = depth // 2
    idx = jnp.arange(C)
    off = idx[:, None] - idx[None, :]
    return ((off >= -half) & (off <= depth - 1 - half)).astype(jnp.float32)


def _lrn_forward(x, *, depth, alpha, beta, k, block_rows, interpret):
    orig_shape = x.shape
    C = orig_shape[-1]
    xf = x.reshape(-1, C)
    R = xf.shape[0]
    br = min(_lrn_rows(C, 2, block_rows), R)
    band = _band(C, depth)
    out = pl.pallas_call(
        functools.partial(_lrn_kernel, alpha=alpha, beta=beta, k=k),
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        grid=(pl.cdiv(R, br),),
        in_specs=[
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xf, band)
    return out.reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _lrn(x, depth, alpha, beta, k, block_rows):
    interpret = jax.default_backend() != "tpu"
    return _lrn_forward(x, depth=depth, alpha=alpha, beta=beta, k=k,
                        block_rows=block_rows, interpret=interpret)


def _lrn_fwd(x, depth, alpha, beta, k, block_rows):
    return _lrn(x, depth, alpha, beta, k, block_rows), x


def _lrn_bwd_kernel(x_ref, g_ref, band_ref, dx_ref, *, alpha, beta, k):
    x = x_ref[...].astype(jnp.float32)          # [br, C]
    g = g_ref[...].astype(jnp.float32)          # [br, C]
    band = band_ref[...]                        # [C, C] f32
    ssum = jax.lax.dot_general(x * x, band, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
    d = k + alpha * ssum
    dpow = d ** (-beta)
    u = g * x * dpow / d                        # g * x * d^(-beta-1)
    # t_i = sum_j u_j band[i, j]: contract the band's SECOND axis — the
    # transposed-band product without materializing a transpose
    t = jax.lax.dot_general(u, band, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    dx_ref[...] = (g * dpow - 2.0 * alpha * beta * x * t).astype(dx_ref.dtype)


def _lrn_rows(C, n_blocks, block_rows=512, budget=13 << 20):
    """Largest row block whose working set fits the VMEM budget:
    ``n_blocks`` double-buffered [br, C] f32 blocks (fwd: x + out = 2;
    bwd: x + g + dx = 3) plus the grid-invariant [C, C] band. At C=1024
    the bwd's three blocks at br=512 would hit ~16.8 MB — over the ~16M
    scoped limit — so the bwd steps down to br=256 there."""
    br = block_rows
    while br > 8 and 2 * n_blocks * br * C * 4 + C * C * 4 > budget:
        br //= 2
    return br


def _lrn_backward(x, g, *, depth, alpha, beta, k, block_rows, interpret):
    orig_shape = x.shape
    C = orig_shape[-1]
    xf = x.reshape(-1, C)
    gf = g.reshape(-1, C)
    R = xf.shape[0]
    br = min(_lrn_rows(C, 3, block_rows), R)
    band = _band(C, depth)
    dx = pl.pallas_call(
        functools.partial(_lrn_bwd_kernel, alpha=alpha, beta=beta, k=k),
        out_shape=jax.ShapeDtypeStruct(xf.shape, x.dtype),
        grid=(pl.cdiv(R, br),),
        in_specs=[
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((br, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((br, C), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(xf, gf, band)
    return dx.reshape(orig_shape)


def _lrn_bwd(depth, alpha, beta, k, block_rows, x, g):
    interpret = jax.default_backend() != "tpu"
    return (_lrn_backward(x, g, depth=depth, alpha=alpha, beta=beta, k=k,
                          block_rows=block_rows, interpret=interpret),)


_lrn.defvjp(_lrn_fwd, _lrn_bwd)


def pallas_lrn(x, *, depth=5, alpha=1e-4, beta=0.75, k=2.0,
               block_rows: int = 512):
    """Public entry: same signature as the XLA lrn lowering."""
    return _lrn(x, depth, float(alpha), float(beta), float(k), block_rows)


def _lrn_requires(x, *, depth=5, **kw):
    # structural: enough pixels to fill row blocks; modest channel count so
    # the [C, C] band plus a row block fit VMEM comfortably
    n = 1
    for d in x.shape[:-1]:
        n *= d
    return n >= 2048 and 32 <= x.shape[-1] <= 1024


def _lrn_applicable(x, *, depth=5, **kw):
    """Default-ON (r4, measured, two-point on-chip A/B at the AlexNet conv2
    shape [64,27,27,256]): fwd 1.26x, train 1.47x. The r3 demotion (train
    0.45x) was caused by the backward recomputing through the XLA lowering
    — the grad path paid kernel-fwd PLUS a full XLA fwd+bwd; the r4 banded
    backward kernel (_lrn_bwd_kernel) removed that tax. Beyond the
    structural requires() bounds (enough rows to fill blocks, band fits
    VMEM), the only gate is dtype: the A/B evidence covers f32/bf16 — the
    MXU-native dtypes the band contraction was tuned for — so anything
    else (f64 emulation, exotic inputs) stays on the measured-safe XLA
    lowering."""
    return x.dtype in (jnp.float32, jnp.bfloat16)


register_impl("lrn", platform="pallas", predicate=_lrn_applicable,
              requires=_lrn_requires, priority=1)(pallas_lrn)
