"""Activation catalog, name-addressable.

Reference analog: nd4j-api :: org.nd4j.linalg.activations.Activation enum and
its IActivation impls (ActivationReLU, ActivationCube, ActivationRationalTanh,
...). DL4J activations are strings in layer JSON; we keep that contract so
configs round-trip. All are plain jnp — XLA fuses them into adjacent
matmuls/convs, so none need Pallas.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def _rational_tanh(x):
    # DL4J ActivationRationalTanh: fast tanh approximation
    # f(x) = 1.7159 * tanh_approx(2x/3) with tanh_approx rational.
    a = 1.7159
    y = (2.0 / 3.0) * x
    yabs = jnp.abs(y)
    approx = jnp.sign(y) * (1.0 - 1.0 / (1.0 + yabs + y * y + 1.41645 * y**4))
    return a * approx


def _rectified_tanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


ACTIVATIONS: dict[str, Callable] = {
    "identity": lambda x: x,
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.swish,
    "silu": jax.nn.silu,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "sigmoid": jax.nn.sigmoid,
    "hardsigmoid": jax.nn.hard_sigmoid,
    "tanh": jnp.tanh,
    "hardtanh": jax.nn.hard_tanh,
    "rationaltanh": _rational_tanh,
    "rectifiedtanh": _rectified_tanh,
    "softmax": jax.nn.softmax,
    "logsoftmax": jax.nn.log_softmax,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "cube": lambda x: x**3,
    "thresholdedrelu": lambda x: jnp.where(x > 1.0, x, 0.0),
}


_PARAMETRIC = {
    "leakyrelu": lambda a: lambda x: jax.nn.leaky_relu(x, negative_slope=a),
    "elu": lambda a: lambda x: jax.nn.elu(x, alpha=a),
    "relumax": lambda a: lambda x: jnp.clip(x, 0.0, a),
    "thresholdedrelu": lambda a: lambda x: jnp.where(x > a, x, 0.0),
}


def get_activation(name_or_fn) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower().replace("_", "")
    if ":" in key:
        # parameterized, JSON-serializable form: "leakyrelu:0.3", "elu:0.5"
        base, _, arg = key.partition(":")
        if base not in _PARAMETRIC:
            raise ValueError(f"activation '{base}' does not take a parameter")
        return _PARAMETRIC[base](float(arg))
    if key not in ACTIVATIONS:
        raise ValueError(f"unknown activation '{name_or_fn}'; known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]


def activation_name(fn_or_name) -> str:
    if isinstance(fn_or_name, str):
        return fn_or_name.lower().replace("_", "")
    for k, v in ACTIVATIONS.items():
        if v is fn_or_name:
            return k
    raise ValueError("cannot serialize custom activation function to JSON")
