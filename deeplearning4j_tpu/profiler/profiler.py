"""OpProfiler analog + NaN panic + jax.profiler trace wrapper."""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax
import numpy as np


@dataclasses.dataclass
class ProfilerConfig:
    """org.nd4j.linalg.profiler.ProfilerConfig analog."""

    check_for_nan: bool = False
    check_for_inf: bool = False
    stack_trace: bool = False  # accepted for parity; python tb is implicit


class OpProfiler:
    """Aggregated timing per labeled section (OpProfiler.getInstance()).

    Usage::

        prof = OpProfiler()
        with prof.section("train_step"):
            loss = step(...)
            jax.block_until_ready(loss)
        prof.summary()

    Timings are host-observed wall clock around device work; for the device
    timeline use profiler.trace(logdir) which records an XLA trace viewable
    in TensorBoard/Perfetto.
    """

    def __init__(self, config: Optional[ProfilerConfig] = None):
        self.config = config or ProfilerConfig()
        self.times: Dict[str, List[float]] = defaultdict(list)
        self.invocations: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def section(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.times[name].append(time.perf_counter() - t0)
            self.invocations[name] += 1

    def time_fn(self, name: str, fn, *args, sync: bool = True, **kwargs):
        with self.section(name):
            out = fn(*args, **kwargs)
            if sync:
                out = jax.block_until_ready(out)
        if self.config.check_for_nan or self.config.check_for_inf:
            check_numerics(out, name=name, inf=self.config.check_for_inf)
        return out

    def stats(self, name: str) -> Dict[str, float]:
        ts = np.asarray(self.times[name])
        if ts.size == 0:
            return {}
        return {"count": int(ts.size), "total_s": float(ts.sum()),
                "mean_ms": float(ts.mean() * 1e3),
                "p50_ms": float(np.percentile(ts, 50) * 1e3),
                "p99_ms": float(np.percentile(ts, 99) * 1e3)}

    def summary(self) -> str:
        lines = [f"{'section':<30}{'count':>8}{'mean ms':>12}{'total s':>10}"]
        for name in sorted(self.times, key=lambda n: -sum(self.times[n])):
            s = self.stats(name)
            lines.append(f"{name:<30}{s['count']:>8}{s['mean_ms']:>12.3f}"
                         f"{s['total_s']:>10.3f}")
        return "\n".join(lines)

    def reset(self):
        self.times.clear()
        self.invocations.clear()


def check_numerics(tree, name: str = "value", inf: bool = True):
    """Raise FloatingPointError on NaN (and optionally Inf) anywhere in a
    pytree — the OpProfiler PANIC mode, applied at step boundaries."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        a = np.asarray(leaf)
        if not np.issubdtype(a.dtype, np.floating):
            continue
        if np.isnan(a).any():
            raise FloatingPointError(
                f"NaN detected in {name} at {jax.tree_util.keystr(path)}")
        if inf and np.isinf(a).any():
            raise FloatingPointError(
                f"Inf detected in {name} at {jax.tree_util.keystr(path)}")
    return tree


@contextlib.contextmanager
def nan_panic():
    """Scoped jax_debug_nans — XLA re-runs the offending op un-jitted and
    raises at the exact primitive (the libnd4j panic-mode analog that
    actually points at the op)."""
    prev = jax.config.read("jax_debug_nans")
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


@contextlib.contextmanager
def trace(logdir: str):
    """Device-timeline trace via jax.profiler (TensorBoard/Perfetto
    viewable) — the libnd4j GraphProfile / nvprof replacement."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
