"""Profiling / tracing / numerics panic.

Reference analog (SURVEY.md §5): ND4J OpProfiler
(org.nd4j.linalg.profiler.OpProfiler with ProfilerConfig NaN/Inf panic
modes), DL4J PerformanceListener, libnd4j GraphProfile. TPU-first the
per-op timeline comes from jax.profiler (XLA's own instrumentation); this
module adds the OpProfiler-style aggregation, step timing, and the
NaN-panic mode (jax_debug_nans + an explicit check_numerics for pytrees).
"""

from deeplearning4j_tpu.profiler.profiler import (
    OpProfiler, ProfilerConfig, check_numerics, nan_panic, trace,
)

__all__ = ["OpProfiler", "ProfilerConfig", "check_numerics", "nan_panic",
           "trace"]
