"""Lazy build + load of the native library."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_ROOT = Path(__file__).resolve().parent.parent.parent
_SRC = _ROOT / "native" / "dl4jtpu_native.cpp"
# committed PORTABLE artifact: codec-free, no shared-library dependencies
# beyond libc/libstdc++ — the fallback for toolchain-less hosts
_SO = _ROOT / "native" / "build" / "libdl4jtpu.so"
# locally-built variant (preferred): includes the JPEG/PNG decode front
# when this host has the codec dev files; never committed
_SO_LOCAL = _ROOT / "native" / "build" / "libdl4jtpu_local.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build(out: Path) -> bool:
    out.parent.mkdir(parents=True, exist_ok=True)
    base = ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", "-pthread",
            "-shared", "-o", str(out), str(_SRC)]
    # preferred: with the native JPEG/PNG decode front; fall back to a
    # codec-less build on hosts without libjpeg/libpng dev files (the
    # Python layer then decodes via PIL)
    attempts = [base + ["-DDL4J_WITH_CODECS", "-ljpeg", "-lpng"], base]
    err = ""
    for cmd in attempts:
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=300)
        except (FileNotFoundError, subprocess.TimeoutExpired):
            return False
        if res.returncode == 0:
            return True
        err = res.stderr
    import warnings

    warnings.warn(f"native build failed:\n{err[-2000:]}")
    return False


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.dl4j_ws_create.restype = c.c_void_p
    lib.dl4j_ws_create.argtypes = [c.c_size_t]
    lib.dl4j_ws_alloc.restype = c.c_void_p
    lib.dl4j_ws_alloc.argtypes = [c.c_void_p, c.c_size_t, c.c_size_t]
    lib.dl4j_ws_reset.argtypes = [c.c_void_p]
    lib.dl4j_ws_used.restype = c.c_size_t
    lib.dl4j_ws_used.argtypes = [c.c_void_p]
    lib.dl4j_ws_peak.restype = c.c_size_t
    lib.dl4j_ws_peak.argtypes = [c.c_void_p]
    lib.dl4j_ws_spilled.restype = c.c_size_t
    lib.dl4j_ws_spilled.argtypes = [c.c_void_p]
    lib.dl4j_ws_destroy.argtypes = [c.c_void_p]

    lib.dl4j_pipe_create.restype = c.c_void_p
    lib.dl4j_pipe_create.argtypes = [c.c_char_p, c.c_char_p, c.c_long,
                                     c.c_long, c.c_long, c.c_long, c.c_int,
                                     c.c_uint, c.c_int, c.c_int]
    lib.dl4j_pipe_next.restype = c.c_int
    lib.dl4j_pipe_next.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                                   c.POINTER(c.c_float)]
    lib.dl4j_pipe_reset.argtypes = [c.c_void_p]
    lib.dl4j_pipe_batches_per_epoch.restype = c.c_long
    lib.dl4j_pipe_batches_per_epoch.argtypes = [c.c_void_p]
    lib.dl4j_pipe_destroy.argtypes = [c.c_void_p]

    lib.dl4j_imgpipe_create.restype = c.c_void_p
    lib.dl4j_imgpipe_create.argtypes = [c.c_char_p, c.c_char_p, c.c_long,
                                        c.c_long, c.c_long, c.c_long,
                                        c.c_long, c.c_long, c.c_long,
                                        c.c_long, c.c_int, c.c_int, c.c_uint,
                                        c.POINTER(c.c_float),
                                        c.POINTER(c.c_float), c.c_int,
                                        c.c_int, c.c_int]
    lib.dl4j_imgpipe_next.restype = c.c_int
    lib.dl4j_imgpipe_next.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                                      c.POINTER(c.c_float)]
    lib.dl4j_imgpipe_next_u8.restype = c.c_int
    lib.dl4j_imgpipe_next_u8.argtypes = [c.c_void_p, c.POINTER(c.c_uint8),
                                         c.POINTER(c.c_float)]
    lib.dl4j_imgpipe_reset.argtypes = [c.c_void_p]
    lib.dl4j_imgpipe_batches_per_epoch.restype = c.c_long
    lib.dl4j_imgpipe_batches_per_epoch.argtypes = [c.c_void_p]
    lib.dl4j_imgpipe_destroy.argtypes = [c.c_void_p]

    lib.dl4j_csv_parse.restype = c.c_void_p
    lib.dl4j_csv_parse.argtypes = [c.c_char_p, c.c_char, c.c_int, c.c_int]
    lib.dl4j_csv_rows.restype = c.c_long
    lib.dl4j_csv_rows.argtypes = [c.c_void_p]
    lib.dl4j_csv_bad_fields.restype = c.c_long
    lib.dl4j_csv_bad_fields.argtypes = [c.c_void_p]
    lib.dl4j_csv_cols.restype = c.c_long
    lib.dl4j_csv_cols.argtypes = [c.c_void_p]
    lib.dl4j_csv_copy.argtypes = [c.c_void_p, c.POINTER(c.c_float)]
    lib.dl4j_csv_free.argtypes = [c.c_void_p]

    lib.dl4j_cache_trim.restype = c.c_long
    lib.dl4j_cache_trim.argtypes = [c.c_char_p, c.c_long]

    lib.dl4j_wc_create.restype = c.c_void_p
    lib.dl4j_wc_create.argtypes = [c.c_char_p, c.c_int]
    lib.dl4j_wc_bytes.restype = c.c_long
    lib.dl4j_wc_bytes.argtypes = [c.c_void_p]
    lib.dl4j_wc_dump.argtypes = [c.c_void_p, c.c_char_p]
    lib.dl4j_wc_destroy.argtypes = [c.c_void_p]

    lib.dl4j_w2v_create.restype = c.c_void_p
    lib.dl4j_w2v_create.argtypes = [c.c_char_p, c.c_char_p, c.c_long,
                                    c.POINTER(c.c_float),
                                    c.POINTER(c.c_float), c.c_int, c.c_int,
                                    c.c_long, c.c_uint, c.c_int, c.c_int]
    lib.dl4j_w2v_next.restype = c.c_int
    lib.dl4j_w2v_next.argtypes = [c.c_void_p, c.POINTER(c.c_int32),
                                  c.POINTER(c.c_int32), c.POINTER(c.c_int32)]
    lib.dl4j_w2v_reset.argtypes = [c.c_void_p]
    lib.dl4j_w2v_words.restype = c.c_long
    lib.dl4j_w2v_words.argtypes = [c.c_void_p]
    lib.dl4j_w2v_pairs.restype = c.c_long
    lib.dl4j_w2v_pairs.argtypes = [c.c_void_p]
    lib.dl4j_w2v_destroy.argtypes = [c.c_void_p]

    if hasattr(lib, "dl4j_image_decode"):     # codec build present
        lib.dl4j_image_probe.restype = c.c_int
        lib.dl4j_image_probe.argtypes = [c.c_char_p, c.POINTER(c.c_long),
                                         c.POINTER(c.c_long)]
        lib.dl4j_image_decode.restype = c.c_int
        lib.dl4j_image_decode.argtypes = [c.c_char_p,
                                          c.POINTER(c.c_uint8), c.c_long,
                                          c.c_long, c.c_long]
        lib.dl4j_image_stage.restype = c.c_int
        lib.dl4j_image_stage.argtypes = [c.c_char_p, c.c_long, c.c_char_p,
                                         c.c_long, c.c_long, c.c_long,
                                         c.c_int]
    return lib


def native_csv_parse(path, delimiter: str = ",", skip_header: bool = False,
                     n_threads: int = 4):
    """Parse a numeric CSV into a float32 [rows, cols] array using the
    multi-threaded native parser; None if the native lib is unavailable or
    the file can't be parsed (caller falls back to Python)."""
    import numpy as np

    lib = load_native_lib()
    if lib is None:
        return None
    h = lib.dl4j_csv_parse(str(path).encode(), delimiter.encode(),
                           int(skip_header), n_threads)
    if not h:
        return None
    try:
        if lib.dl4j_csv_bad_fields(h):
            # non-numeric content: refuse rather than return silent zeros —
            # the Python fallback will raise (or parse strings) consistently
            return None
        rows, cols = lib.dl4j_csv_rows(h), lib.dl4j_csv_cols(h)
        out = np.empty((rows, cols), np.float32)
        lib.dl4j_csv_copy(h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out
    finally:
        lib.dl4j_csv_free(h)


def trim_compile_cache(cache_dir: Optional[str] = None,
                       cap_bytes: int = 2 << 30) -> int:
    """LRU-trim the persistent XLA compilation cache directory down to
    cap_bytes (PJRT executable-cache management; libnd4j GraphHolder analog).
    Returns bytes evicted (0 if under cap), -1 on error/no native lib."""
    cache_dir = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                            str(_ROOT / ".jax_cache"))
    lib = load_native_lib()
    if lib is None or not os.path.isdir(cache_dir):
        return -1
    return int(lib.dl4j_cache_trim(str(cache_dir).encode(), int(cap_bytes)))


def load_native_lib() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native library; None if unavailable.
    One attempt per process — success and failure are both cached.

    Load order: locally-built variant (rebuilt when the source is newer;
    may carry codec dependencies this host satisfies by construction) ->
    committed portable artifact (codec-free; loads anywhere a libc does).
    A failed load of one candidate falls through to the next, so a
    committed artifact with missing sonames can never disable the whole
    native layer on a toolchain-less host."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if _SRC.exists():
            stale_local = (not _SO_LOCAL.exists()
                           or _SO_LOCAL.stat().st_mtime
                           < _SRC.stat().st_mtime)
            if stale_local:
                _build(_SO_LOCAL)      # failure is fine: fall back below
        for cand in (_SO_LOCAL, _SO):
            if not cand.exists():
                continue
            try:
                _lib = _declare(ctypes.CDLL(str(cand)))
                return _lib
            except (OSError, AttributeError):
                # OSError: unsatisfied dependency on this host;
                # AttributeError: stale binary missing newer symbols —
                # dlopen caches by pathname, so retry under a unique path
                # after a rebuild when that is possible
                _lib = None
                if cand == _SO_LOCAL and _build(_SO_LOCAL):
                    import shutil
                    import tempfile

                    alt = None
                    try:
                        # same dir: /tmp may be mounted noexec
                        with tempfile.NamedTemporaryFile(
                                suffix=".so", dir=str(cand.parent),
                                delete=False) as f:
                            alt = f.name
                        shutil.copy2(cand, alt)
                        _lib = _declare(ctypes.CDLL(alt))
                        return _lib
                    except (OSError, AttributeError):
                        _lib = None
                    finally:
                        # the dlopen mapping survives the unlink on Linux
                        if alt is not None:
                            try:
                                os.unlink(alt)
                            except OSError:
                                pass
        return _lib


def native_available() -> bool:
    return load_native_lib() is not None
