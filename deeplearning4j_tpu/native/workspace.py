"""Workspace — scoped arena memory.

Reference analog: org.nd4j.linalg.api.memory.MemoryWorkspace /
libnd4j memory::Workspace — scoped bump allocation with reset, peak
tracking, and heap spill when the arena is exhausted. On TPU the DEVICE
side of workspaces is XLA buffer assignment + donation; this arena covers
the host-staging role (batch assembly, serialization buffers).
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from deeplearning4j_tpu.native.lib import load_native_lib


class Workspace:
    """Context-managed arena: numpy views into native memory.

        with Workspace(16 << 20) as ws:
            a = ws.alloc((1024, 1024), np.float32)
            ...
        # exit resets the arena (use-after-scope = reading stale data,
        # exactly the hazard the reference's debug mode traps)
    """

    def __init__(self, size_bytes: int):
        self._lib = load_native_lib()
        self.size = size_bytes
        self._handle: Optional[int] = None
        self._py_buffers = []  # python fallback
        if self._lib is not None:
            self._handle = self._lib.dl4j_ws_create(size_bytes)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def alloc(self, shape, dtype=np.float32) -> np.ndarray:
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        if self._handle is not None:
            ptr = self._lib.dl4j_ws_alloc(self._handle, nbytes, 64)
            if not ptr:
                raise MemoryError("workspace allocation failed")
            buf = (ctypes.c_char * nbytes).from_address(ptr)
            return np.frombuffer(buf, dtype=dtype).reshape(shape)
        a = np.empty(shape, dtype)
        self._py_buffers.append(a)
        return a

    def used(self) -> int:
        if self._handle is not None:
            return int(self._lib.dl4j_ws_used(self._handle))
        return sum(a.nbytes for a in self._py_buffers)

    def peak(self) -> int:
        if self._handle is not None:
            return int(self._lib.dl4j_ws_peak(self._handle))
        return self.used()

    def spilled(self) -> int:
        if self._handle is not None:
            return int(self._lib.dl4j_ws_spilled(self._handle))
        return 0

    def reset(self):
        if self._handle is not None:
            self._lib.dl4j_ws_reset(self._handle)
        self._py_buffers.clear()

    def destroy(self):
        if self._handle is not None:
            self._lib.dl4j_ws_destroy(self._handle)
            self._handle = None
        self._py_buffers.clear()

    def __enter__(self) -> "Workspace":
        return self

    def __exit__(self, *exc):
        self.reset()

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass
