"""Native runtime bindings (ctypes over native/dl4jtpu_native.cpp).

Reference analog (SURVEY.md §2.1): libnd4j's workspace allocator
(memory::Workspace) and the prefetch queues of AsyncDataSetIterator /
ParallelWrapper — the host-side runtime around the device compute path. The
library is compiled lazily with g++ on first use (no pybind11 in the image;
plain C ABI + ctypes). Every entry point has a pure-Python fallback so the
framework works where no toolchain exists.
"""

from deeplearning4j_tpu.native.lib import (
    load_native_lib, native_available, native_csv_parse, trim_compile_cache,
)
from deeplearning4j_tpu.native.workspace import Workspace
from deeplearning4j_tpu.native.pipeline import (
    NativeDataSetIterator, NativeImageDataSetIterator, decode_image_file,
    image_files_iterator, probe_image, stage_image_files,
    write_binary_dataset, write_image_dataset,
)

__all__ = ["load_native_lib", "native_available", "Workspace",
           "NativeDataSetIterator", "NativeImageDataSetIterator",
           "write_binary_dataset", "write_image_dataset",
           "decode_image_file", "image_files_iterator", "probe_image",
           "stage_image_files", "native_csv_parse", "trim_compile_cache"]
