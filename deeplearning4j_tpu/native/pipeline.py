"""Native prefetching DataSet iterator.

Reference analog: AsyncDataSetIterator + ParallelWrapper's prefetch queues
(org.deeplearning4j.datasets.iterator.AsyncDataSetIterator) — producer
threads keeping batches ahead of the training step, implemented in C++
(native/dl4jtpu_native.cpp) instead of Java threads. Falls back to a numpy
implementation when no toolchain is available.
"""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.native.lib import load_native_lib


def write_binary_dataset(directory, features: np.ndarray, labels: np.ndarray
                         ) -> Tuple[str, str]:
    """Flat-float32 export consumed by the native pipeline (the interchange
    format standing in for the reference's DataSet binary serialization)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    f = directory / "features.bin"
    l = directory / "labels.bin"
    np.ascontiguousarray(features, np.float32).tofile(f)
    np.ascontiguousarray(labels, np.float32).tofile(l)
    return str(f), str(l)


class NativeDataSetIterator:
    """Iterates (features, labels) batches assembled by native worker threads.

    features file: [n, feat_dim] float32, labels file: [n, label_dim].
    Drop-last semantics; reshuffles per epoch when shuffle=True.
    """

    def __init__(self, feat_path: str, label_path: str, n: int,
                 feat_shape, label_shape, batch_size: int,
                 shuffle: bool = True, seed: int = 0, n_threads: int = 2,
                 queue_cap: int = 4):
        self.feat_shape = tuple(feat_shape)
        self.label_shape = tuple(label_shape)
        self.feat_dim = int(np.prod(self.feat_shape))
        self.label_dim = int(np.prod(self.label_shape))
        self.batch_size = batch_size
        self.n = n
        self._lib = load_native_lib()
        self._handle = None
        self._fallback: Optional[_PyPipeline] = None
        if self._lib is not None:
            self._handle = self._lib.dl4j_pipe_create(
                feat_path.encode(), label_path.encode(), n, self.feat_dim,
                self.label_dim, batch_size, int(shuffle), seed, n_threads,
                queue_cap)
        if self._handle is None:
            self._fallback = _PyPipeline(feat_path, label_path, n,
                                         self.feat_dim, self.label_dim,
                                         batch_size, shuffle, seed)
        self._feat_buf = np.empty((batch_size, self.feat_dim), np.float32)
        self._label_buf = np.empty((batch_size, self.label_dim), np.float32)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def batches_per_epoch(self) -> int:
        if self._handle is not None:
            return int(self._lib.dl4j_pipe_batches_per_epoch(self._handle))
        return self._fallback.n_batches

    def __iter__(self):
        return self

    def __next__(self) -> DataSet:
        if self._handle is not None:
            rc = self._lib.dl4j_pipe_next(
                self._handle,
                self._feat_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._label_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            if rc == 1:
                raise StopIteration
            if rc != 0:
                raise RuntimeError("native pipeline error")
            f = self._feat_buf.reshape((self.batch_size,) + self.feat_shape).copy()
            y = self._label_buf.reshape((self.batch_size,) + self.label_shape).copy()
            return DataSet(f, y)
        return self._fallback.next(self.feat_shape, self.label_shape)

    def reset(self):
        if self._handle is not None:
            self._lib.dl4j_pipe_reset(self._handle)
        else:
            self._fallback.reset()

    def close(self):
        if self._handle is not None:
            self._lib.dl4j_pipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _PyPipeline:
    """Pure-python fallback with identical semantics."""

    def __init__(self, feat_path, label_path, n, feat_dim, label_dim,
                 batch, shuffle, seed):
        self.feats = np.fromfile(feat_path, np.float32).reshape(n, feat_dim)
        self.labels = np.fromfile(label_path, np.float32).reshape(n, label_dim)
        self.batch = batch
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.n_batches = n // batch
        self._reshuffle()

    def _reshuffle(self):
        self.order = np.arange(len(self.feats))
        if self.shuffle:
            np.random.default_rng(self.seed + self.epoch).shuffle(self.order)
        self.pos = 0

    def next(self, feat_shape, label_shape) -> DataSet:
        if self.pos >= self.n_batches:
            raise StopIteration
        idx = self.order[self.pos * self.batch:(self.pos + 1) * self.batch]
        self.pos += 1
        return DataSet(
            self.feats[idx].reshape((self.batch,) + tuple(feat_shape)).copy(),
            self.labels[idx].reshape((self.batch,) + tuple(label_shape)).copy())

    def reset(self):
        self.epoch += 1
        self._reshuffle()
