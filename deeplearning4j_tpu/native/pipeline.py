"""Native prefetching DataSet iterator.

Reference analog: AsyncDataSetIterator + ParallelWrapper's prefetch queues
(org.deeplearning4j.datasets.iterator.AsyncDataSetIterator) — producer
threads keeping batches ahead of the training step, implemented in C++
(native/dl4jtpu_native.cpp) instead of Java threads. Falls back to a numpy
implementation when no toolchain is available.
"""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.native.lib import load_native_lib


def write_binary_dataset(directory, features: np.ndarray, labels: np.ndarray
                         ) -> Tuple[str, str]:
    """Flat-float32 export consumed by the native pipeline (the interchange
    format standing in for the reference's DataSet binary serialization)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    f = directory / "features.bin"
    l = directory / "labels.bin"
    np.ascontiguousarray(features, np.float32).tofile(f)
    np.ascontiguousarray(labels, np.float32).tofile(l)
    return str(f), str(l)


class NativeDataSetIterator:
    """Iterates (features, labels) batches assembled by native worker threads.

    features file: [n, feat_dim] float32, labels file: [n, label_dim].
    Drop-last semantics; reshuffles per epoch when shuffle=True.
    """

    def __init__(self, feat_path: str, label_path: str, n: int,
                 feat_shape, label_shape, batch_size: int,
                 shuffle: bool = True, seed: int = 0, n_threads: int = 2,
                 queue_cap: int = 4):
        self.feat_shape = tuple(feat_shape)
        self.label_shape = tuple(label_shape)
        self.feat_dim = int(np.prod(self.feat_shape))
        self.label_dim = int(np.prod(self.label_shape))
        self.batch_size = batch_size
        self.n = n
        self._lib = load_native_lib()
        self._handle = None
        self._fallback: Optional[_PyPipeline] = None
        if self._lib is not None:
            self._handle = self._lib.dl4j_pipe_create(
                feat_path.encode(), label_path.encode(), n, self.feat_dim,
                self.label_dim, batch_size, int(shuffle), seed, n_threads,
                queue_cap)
        if self._handle is None:
            self._fallback = _PyPipeline(feat_path, label_path, n,
                                         self.feat_dim, self.label_dim,
                                         batch_size, shuffle, seed)
        self._feat_buf = np.empty((batch_size, self.feat_dim), np.float32)
        self._label_buf = np.empty((batch_size, self.label_dim), np.float32)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def batches_per_epoch(self) -> int:
        if self._handle is not None:
            return int(self._lib.dl4j_pipe_batches_per_epoch(self._handle))
        return self._fallback.n_batches

    def __iter__(self):
        return self

    def __next__(self) -> DataSet:
        if self._handle is not None:
            rc = self._lib.dl4j_pipe_next(
                self._handle,
                self._feat_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                self._label_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            if rc == 1:
                raise StopIteration
            if rc != 0:
                raise RuntimeError("native pipeline error")
            f = self._feat_buf.reshape((self.batch_size,) + self.feat_shape).copy()
            y = self._label_buf.reshape((self.batch_size,) + self.label_shape).copy()
            return DataSet(f, y)
        return self._fallback.next(self.feat_shape, self.label_shape)

    def reset(self):
        if self._handle is not None:
            self._lib.dl4j_pipe_reset(self._handle)
        else:
            self._fallback.reset()

    def close(self):
        if self._handle is not None:
            self._lib.dl4j_pipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _PyPipeline:
    """Pure-python fallback with identical semantics."""

    def __init__(self, feat_path, label_path, n, feat_dim, label_dim,
                 batch, shuffle, seed):
        self.feats = np.fromfile(feat_path, np.float32).reshape(n, feat_dim)
        self.labels = np.fromfile(label_path, np.float32).reshape(n, label_dim)
        self.batch = batch
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.n_batches = n // batch
        self._reshuffle()

    def _reshuffle(self):
        self.order = np.arange(len(self.feats))
        if self.shuffle:
            np.random.default_rng(self.seed + self.epoch).shuffle(self.order)
        self.pos = 0

    def next(self, feat_shape, label_shape) -> DataSet:
        if self.pos >= self.n_batches:
            raise StopIteration
        idx = self.order[self.pos * self.batch:(self.pos + 1) * self.batch]
        self.pos += 1
        return DataSet(
            self.feats[idx].reshape((self.batch,) + tuple(feat_shape)).copy(),
            self.labels[idx].reshape((self.batch,) + tuple(label_shape)).copy())

    def reset(self):
        self.epoch += 1
        self._reshuffle()


def write_image_dataset(directory, images: np.ndarray, labels: np.ndarray
                        ) -> Tuple[str, str]:
    """uint8 [n, H, W, C] image export for the native image pipeline (4x
    smaller at rest than float32; normalization happens in the C++ workers)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    f = directory / "images.u8"
    l = directory / "labels.bin"
    np.ascontiguousarray(images, np.uint8).tofile(f)
    np.ascontiguousarray(labels, np.float32).tofile(l)
    return str(f), str(l)


class NativeImageDataSetIterator:
    """ImageNet-class input path: threaded C++ decode->augment->normalize
    producing float32 NHWC batches, with optional async DEVICE prefetch.

    Reference analog: DataVec ImageRecordReader + ImagePreProcessingScaler +
    AsyncDataSetIterator stacked — random crop + horizontal flip + per-
    channel normalize run in native worker threads; ``device_prefetch``
    stages the NEXT batch onto the accelerator while the current one trains
    (the host->device overlap the reference gets from its prefetch queues).

    augment=True: random crop to (crop_h, crop_w) + random horizontal flip,
    fresh draws every epoch. augment=False: deterministic center crop (eval).
    """

    def __init__(self, img_path: str, label_path: str, n: int, image_shape,
                 label_dim: int, batch_size: int, crop=None,
                 shuffle: bool = True, augment: bool = True, seed: int = 0,
                 mean=None, std=None, n_threads: int = 4, queue_cap: int = 4,
                 device_prefetch: bool = False, output: str = "f32"):
        """``output``: "f32" — workers normalize on the host (the DataVec
        ImagePreProcessingScaler behavior); "u8" — workers only crop/flip
        and batches stay uint8 (4x less host traffic AND host->device
        transfer), with ``normalize()`` (a one-op jit XLA fuses into the
        consuming conv) applying (x/255 - mean)/std ON DEVICE — the
        TPU-first split of the same work."""
        H, W, C = image_shape
        crop_h, crop_w = crop if crop is not None else (H, W)
        if output not in ("f32", "u8"):
            raise ValueError(f"output must be 'f32' or 'u8', got {output!r}")
        self.output = output
        self.batch_size = batch_size
        self.out_shape = (batch_size, crop_h, crop_w, C)
        self.label_dim = label_dim
        self._device_prefetch = device_prefetch
        self._staged = None
        mean = np.asarray(mean if mean is not None else [0.0] * C, np.float32)
        std = np.asarray(std if std is not None else [1.0] * C, np.float32)
        if mean.size != C or std.size != C:
            raise ValueError(f"mean/std must have {C} channel entries")
        self.mean, self.std = mean, std
        self._lib = load_native_lib()
        self._handle = None
        self._py = None
        self._exhausted = False
        if self._lib is not None:
            self._handle = self._lib.dl4j_imgpipe_create(
                img_path.encode(), label_path.encode(), n, H, W, C,
                label_dim, crop_h, crop_w, batch_size, int(shuffle),
                int(augment), seed,
                mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                n_threads, queue_cap, int(output == "u8"))
        if self._handle is None:
            self._py = _PyImagePipeline(img_path, label_path, n, (H, W, C),
                                        label_dim, (crop_h, crop_w),
                                        batch_size, shuffle, augment, seed,
                                        mean, std, u8=(output == "u8"))
        self._label_buf = np.empty((batch_size, label_dim), np.float32)
        self._norm_jit = None

    def normalize(self, x):
        """Device-side (x/255 - mean)/std for output="u8" batches; XLA
        fuses it into the first conv of the consuming train step."""
        if self._norm_jit is None:
            import jax
            import jax.numpy as jnp

            a = jnp.asarray(1.0 / (255.0 * self.std), jnp.float32)
            b = jnp.asarray(-self.mean / self.std, jnp.float32)
            self._norm_jit = jax.jit(
                lambda u8: u8.astype(jnp.float32) * a + b)
        return self._norm_jit(x)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def batches_per_epoch(self) -> int:
        if self._handle is not None:
            return int(self._lib.dl4j_imgpipe_batches_per_epoch(self._handle))
        return self._py.n_batches

    def _fetch_host(self):
        """Next (features, labels) as host numpy, or None at epoch end.
        Writes into FRESH arrays (no reuse-then-copy: the consumer owns the
        buffers, and one copy per batch is one too many at model rate)."""
        if self._handle is not None:
            if self.output == "u8":
                feat = np.empty(self.out_shape, np.uint8)
                rc = self._lib.dl4j_imgpipe_next_u8(
                    self._handle,
                    feat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    self._label_buf.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)))
            else:
                feat = np.empty(self.out_shape, np.float32)
                rc = self._lib.dl4j_imgpipe_next(
                    self._handle,
                    feat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    self._label_buf.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)))
            if rc == 1:
                return None
            if rc != 0:
                raise RuntimeError("native image pipeline failed")
            return feat, self._label_buf.copy()
        return self._py.next()

    def _stage(self, host):
        if host is None:
            return None
        if not self._device_prefetch:
            return host
        import jax

        # async host->device: the transfer overlaps the consumer's compute
        return tuple(jax.device_put(a) for a in host)

    def __iter__(self):
        # a finished epoch re-iterated without an explicit reset() advances
        # the epoch ONCE here; fit() calls reset() itself between epochs, in
        # which case _exhausted is already cleared and nothing double-resets
        if self._exhausted:
            self.reset()
        if self._staged is None:  # keep an already-prefetched batch
            self._staged = self._stage(self._fetch_host())
        return self

    def __next__(self) -> DataSet:
        cur = self._staged
        if cur is None:
            self._exhausted = True
            raise StopIteration
        # stage the NEXT batch before handing the current one to the trainer
        self._staged = self._stage(self._fetch_host())
        return DataSet(cur[0], cur[1])

    def reset(self):
        if self._handle is not None:
            self._lib.dl4j_imgpipe_reset(self._handle)
        else:
            self._py.reset()
        self._staged = None
        self._exhausted = False

    def close(self):
        if self._handle is not None:
            self._lib.dl4j_imgpipe_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _PyImagePipeline:
    """Numpy fallback with the same contract (different RNG stream)."""

    def __init__(self, img_path, label_path, n, shape, label_dim, crop,
                 batch, shuffle, augment, seed, mean, std, u8=False):
        H, W, C = shape
        self.u8 = u8
        self.images = np.fromfile(img_path, np.uint8).reshape(n, H, W, C)
        self.labels = np.fromfile(label_path, np.float32).reshape(n, label_dim)
        self.crop = crop
        self.batch = batch
        self.shuffle = shuffle
        self.augment = augment
        self.seed = seed
        self.epoch = 0
        self.mean, self.std = mean, std
        self.n_batches = n // batch
        self._start()

    def _start(self):
        self._rng = np.random.default_rng(self.seed + self.epoch)
        self._order = (self._rng.permutation(len(self.images)) if self.shuffle
                       else np.arange(len(self.images)))
        self._pos = 0

    def next(self):
        if self._pos >= self.n_batches:
            return None
        ch, cw = self.crop
        H, W = self.images.shape[1:3]
        idx = self._order[self._pos * self.batch:(self._pos + 1) * self.batch]
        feats = np.empty((self.batch, ch, cw, self.images.shape[3]),
                         np.uint8 if self.u8 else np.float32)
        for r, src in enumerate(idx):
            if self.augment:
                top = self._rng.integers(0, H - ch + 1)
                left = self._rng.integers(0, W - cw + 1)
                flip = bool(self._rng.integers(0, 2))
            else:
                top, left, flip = (H - ch) // 2, (W - cw) // 2, False
            img = self.images[src, top:top + ch, left:left + cw]
            if flip:
                img = img[:, ::-1]
            if self.u8:
                feats[r] = img
            else:
                feats[r] = (img.astype(np.float32) / 255.0
                            - self.mean) / self.std
        self._pos += 1
        return feats, self.labels[idx].copy()

    def reset(self):
        self.epoch += 1
        self._start()


# --------------------------------------------------------------- image files
# Decode front for the staging format (SURVEY.md §2.3 Datasets/fetchers:
# DataVec's ImageRecordReader reads actual image FILES). JPEG/PNG entropy
# decode + bilinear resize run in the native library (libjpeg/libpng,
# threaded, order-preserving); PIL is the fallback when the native build
# has no codecs.


def probe_image(path) -> Tuple[int, int]:
    """(height, width) of an image file without a full decode."""
    lib = load_native_lib()
    if lib is not None and hasattr(lib, "dl4j_image_probe"):
        h = ctypes.c_long()
        w = ctypes.c_long()
        if lib.dl4j_image_probe(str(path).encode(), ctypes.byref(h),
                                ctypes.byref(w)) == 0:
            return int(h.value), int(w.value)
        # non-JPEG/PNG format: PIL fallback below
    from PIL import Image

    with Image.open(path) as im:
        return im.height, im.width


def decode_image_file(path, image_shape) -> np.ndarray:
    """Decode one JPEG/PNG file to uint8 [H, W, C] (C=3 RGB / C=1 gray),
    bilinear-resized to the staging shape."""
    H, W, C = image_shape
    lib = load_native_lib()
    if lib is not None and hasattr(lib, "dl4j_image_decode"):
        out = np.empty((H, W, C), np.uint8)
        rc = lib.dl4j_image_decode(
            str(path).encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), H, W, C)
        if rc == 0:
            return out
        # the native front covers JPEG/PNG; other formats (bmp/webp/...)
        # fall through to PIL so a codec build never supports FEWER
        # formats than a codec-less one
    return _pil_decode(path, image_shape)


def _pil_decode(path, image_shape) -> np.ndarray:
    from PIL import Image

    H, W, C = image_shape
    with Image.open(path) as im:
        im = im.convert("L" if C == 1 else "RGB")
        if (im.height, im.width) != (H, W):
            im = im.resize((W, H), Image.BILINEAR)
        a = np.asarray(im, np.uint8)
    return a[..., None] if C == 1 else a


def stage_image_files(paths, labels, directory, image_shape,
                      n_threads: int = 8) -> Tuple[str, str]:
    """Decode image files ONCE into the uint8 staging pair
    (images.u8 [n, H, W, C], labels.bin [n, label_dim]) consumed by
    NativeImageDataSetIterator — epochs then re-crop/flip/normalize from
    staged uint8 without touching the codecs again."""
    H, W, C = image_shape
    paths = [str(p) for p in paths]
    labels = np.ascontiguousarray(labels, np.float32)
    if len(paths) != len(labels):
        raise ValueError(f"{len(paths)} paths vs {len(labels)} labels")
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    img_path = directory / "images.u8"
    label_path = directory / "labels.bin"
    lib = load_native_lib()
    rc = -1
    if lib is not None and hasattr(lib, "dl4j_image_stage"):
        rc = lib.dl4j_image_stage("\n".join(paths).encode(), len(paths),
                                  str(img_path).encode(), H, W, C, n_threads)
    if rc != 0:
        # no codec build, or some files the native front can't decode
        # (non-JPEG/PNG in the mix): stream per-file — decode_image_file
        # still uses the native decoder for each JPEG/PNG and PIL only for
        # the odd formats; one image in memory at a time
        with open(img_path, "wb") as f:
            for p in paths:
                f.write(decode_image_file(p, image_shape).tobytes())
    labels.tofile(label_path)
    return str(img_path), str(label_path)


def image_files_iterator(paths, labels, image_shape, label_dim,
                         batch_size, directory=None, **kwargs
                         ) -> "NativeImageDataSetIterator":
    """ImageRecordReader-style entry: image FILES -> staged uint8 ->
    threaded augment/normalize iterator. ``directory`` keeps the staging
    pair for reuse across runs (defaults to a temp dir)."""
    import shutil
    import tempfile

    own_dir = directory is None
    directory = directory or tempfile.mkdtemp(prefix="dl4j_imgstage_")
    try:
        img_path, label_path = stage_image_files(paths, labels, directory,
                                                 image_shape)
        return NativeImageDataSetIterator(img_path, label_path, len(paths),
                                          image_shape, label_dim, batch_size,
                                          **kwargs)
    finally:
        # the pipeline loads the staging pair into memory at construction;
        # a temp dir WE created must not leak a dataset-sized file per call
        if own_dir:
            shutil.rmtree(directory, ignore_errors=True)
