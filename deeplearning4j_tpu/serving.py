"""Model serving over HTTP.

Reference analog: the reference's serving tier — ParallelInference behind a
REST endpoint (deeplearning4j model server / nearest-neighbors-server
pattern). Stdlib-only HTTP: POST /predict with JSON {"inputs": [[...]]}
returns {"outputs": [[...]]}; batching + async execution come from
ParallelInference underneath, so concurrent requests share device batches.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.parallel.inference import ParallelInference


class ModelServer:
    """Serve a model's output() via JSON HTTP.

        server = ModelServer(model, port=0).start()
        ... POST http://host:port/predict {"inputs": [...]}
        server.stop()
    """

    def __init__(self, model, port: int = 0, host: str = "127.0.0.1",
                 batch_limit: int = 32, queue_timeout: float = 30.0):
        self.model = model
        self._host, self._port = host, port
        self._timeout = queue_timeout
        self._pi = ParallelInference(model, batch_limit=batch_limit)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> "ModelServer":
        self._pi.start()
        pi, timeout = self._pi, self._timeout

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code, payload):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):  # noqa: N802
                if self.path.split("?")[0] != "/predict":
                    self._reply(404, {"error": "unknown endpoint"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    xs = np.asarray(body["inputs"], np.float32)
                    queues = [pi.submit(x) for x in xs]
                    outs = [np.asarray(q.get(timeout=timeout)).tolist()
                            for q in queues]
                    self._reply(200, {"outputs": outs})
                except Exception as e:  # noqa: BLE001 — serving boundary
                    self._reply(400, {"error": str(e)})

            def do_GET(self):  # noqa: N802
                if self.path.split("?")[0] == "/health":
                    self._reply(200, {"status": "ok"})
                else:
                    self._reply(404, {"error": "unknown endpoint"})

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._pi.stop()
