"""Model serving over HTTP.

Reference analog: the reference's serving tier — ParallelInference behind a
REST endpoint (deeplearning4j model server / nearest-neighbors-server
pattern). Stdlib-only HTTP: POST /predict with JSON {"inputs": [[...]]}
returns {"outputs": [[...]]}; batching + async execution come from
ParallelInference underneath, so concurrent requests share device batches.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
import numpy as np

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.parallel.inference import ParallelInference



class _HttpServerMixin:
    """Shared ephemeral-port resolution and shutdown for the HTTP servers."""

    _httpd = None
    _thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def _stop_httpd(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def _serve_json(host, port, post_routes, get_routes):
    """Shared JSON-over-HTTP scaffolding for the serving endpoints: routes
    are {path: fn(body-dict) -> payload-dict}; errors become JSON 400s.
    Every server also answers ``GET /metrics`` with the process-wide
    Prometheus exposition (text format), and — when monitoring is enabled —
    records per-route request latency and an in-flight gauge.
    Returns (httpd, thread) — call httpd.shutdown()/server_close() to stop.
    """

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code, payload):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _route(self, routes, body):
            path = self.path.split("?")[0]
            fn = routes.get(path)
            if fn is None:
                self._reply(404, {"error": "unknown endpoint"})
                return
            mon = monitoring.serving_monitor()
            if mon is None:
                try:
                    self._reply(200, fn(body))
                except Exception as e:  # noqa: BLE001 — serving boundary
                    self._reply(400, {"error": str(e)})
                return
            mon.in_flight.inc()
            t0 = time.perf_counter()
            code = 200
            try:
                payload = fn(body)
            except Exception as e:  # noqa: BLE001 — serving boundary
                code, payload = 400, {"error": str(e)}
            finally:
                mon.in_flight.dec()
            mon.request_seconds.labels(route=path, code=code).observe(
                time.perf_counter() - t0)
            self._reply(code, payload)

        def do_POST(self):  # noqa: N802
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
            except Exception as e:  # noqa: BLE001
                self._reply(400, {"error": str(e)})
                return
            self._route(post_routes, body)

        def do_GET(self):  # noqa: N802
            if self.path.split("?")[0] == "/metrics":
                data = monitoring.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._route(get_routes, {})

        def log_message(self, *args):
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread


class ModelServer(_HttpServerMixin):
    """Serve a model's output() via JSON HTTP.

        server = ModelServer(model, port=0).start()
        ... POST http://host:port/predict {"inputs": [...]}
        server.stop()
    """

    def __init__(self, model, port: int = 0, host: str = "127.0.0.1",
                 batch_limit: int = 32, queue_timeout: float = 30.0):
        self.model = model
        self._host, self._port = host, port
        self._timeout = queue_timeout
        self._pi = ParallelInference(model, batch_limit=batch_limit)

    def start(self) -> "ModelServer":
        self._pi.start()
        pi, timeout = self._pi, self._timeout

        def predict(body):
            xs = np.asarray(body["inputs"], np.float32)
            queues = [pi.submit(x) for x in xs]
            return {"outputs": [np.asarray(q.get(timeout=timeout)).tolist()
                                for q in queues]}

        self._httpd, self._thread = _serve_json(
            self._host, self._port,
            post_routes={"/predict": predict},
            get_routes={"/health": lambda _: {"status": "ok"}})
        return self

    def stop(self):
        self._stop_httpd()
        self._pi.stop()


class KNNServer(_HttpServerMixin):
    """Nearest-neighbors HTTP server.

    Reference analog: deeplearning4j-nearestneighbors-server's NearestNeighborsServer —
    a VPTree over an indexed point set behind REST. Endpoints:

        POST /knn     {"point": [...], "k": n}
                      -> {"results": [{"index": i, "distance": d}, ...]}
        POST /knnvec  {"vectors": [[...], ...], "k": n}   (batched; brute
                      MXU path — one device matmul for the whole batch)
                      -> {"results": [[{"index", "distance"}, ...], ...]}
        GET  /health

    ``backend``: "vptree" (default, the reference's structure) | "kdtree" |
    "brute" (single points also answered by the batched MXU path).
    """

    def __init__(self, points, port: int = 0, host: str = "127.0.0.1",
                 backend: str = "vptree"):
        from deeplearning4j_tpu.neighbors import KDTree, VPTree, knn_search

        self.points = np.asarray(points, np.float32)
        self._host, self._port = host, port
        self._brute = lambda qs, k: knn_search(self.points, qs, k=k)
        if backend == "vptree":
            self._tree = VPTree(self.points)
        elif backend == "kdtree":
            self._tree = KDTree(self.points)
        elif backend == "brute":
            self._tree = None
        else:
            raise ValueError("backend must be vptree|kdtree|brute")

    def _query_one(self, point, k):
        if self._tree is not None:
            idx, dist = self._tree.knn(np.asarray(point, np.float32), k=k)
            return [{"index": int(i), "distance": float(d)}
                    for i, d in zip(idx, dist)]
        return self._query_batch([point], k)[0]

    def _query_batch(self, vectors, k):
        idx, dist = self._brute(np.asarray(vectors, np.float32), k)
        idx, dist = np.asarray(idx), np.asarray(dist)
        return [[{"index": int(i), "distance": float(d)}
                 for i, d in zip(row_i, row_d)]
                for row_i, row_d in zip(idx, dist)]

    def start(self) -> "KNNServer":
        self._httpd, self._thread = _serve_json(
            self._host, self._port,
            post_routes={
                "/knn": lambda b: {"results": self._query_one(
                    b["point"], int(b.get("k", 1)))},
                "/knnvec": lambda b: {"results": self._query_batch(
                    b["vectors"], int(b.get("k", 1)))},
            },
            get_routes={"/health": lambda _: {"status": "ok",
                                              "points": len(self.points)}})
        return self

    def stop(self):
        self._stop_httpd()
