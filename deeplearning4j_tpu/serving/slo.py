"""The SLO layer: per-class latency objectives, burn rate, shed order.

An SLO here is "fraction ``target`` of a class's requests finish within
``objective_ms``". The tracker keeps a sliding window of recent latencies
per priority class and derives the **burn rate** — observed violation
fraction divided by the error budget ``(1 - target)``. Burn rate 1.0 means
the budget is being spent exactly as fast as the objective allows; above
1.0 the class is missing its SLO.

Overload policy is **shed lowest class first**: when a class is burning
(rate > ``shed_threshold``), every *strictly lower* class sheds at
admission (429, ``dl4j_serving_shed_total{reason="slo"}``) until the
burning class recovers — batch traffic is sacrificed to keep interactive
p99 inside its objective, never the reverse. A burning class itself is
NOT shed (shedding it wouldn't return its already-spent budget and would
turn a latency miss into an availability miss).

``GET /slo`` on the gateway reports the whole picture per class:
objective, window count, violation fraction, burn rate, and whether
traffic of that class is currently being shed.

Zero-overhead contract: a gateway without ``slo=`` config never builds a
tracker — no deques, no burn-rate math, no extra metrics on the request
path (spy-guarded in tests/test_serving_gateway.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight
from deeplearning4j_tpu.serving.tenancy import PRIORITY_CLASSES, class_rank


class SloTracker:
    """Sliding-window latency objectives per priority class.

    ``objectives`` maps class -> ``{"objective_ms": float, "target": float}``
    (target defaults to 0.99; a bare number is shorthand for the
    objective). Classes without an objective are tracked for /slo but never
    burn, and never cause shedding. ``window`` is the per-class sample
    count the burn rate is computed over; ``min_samples`` keeps one
    unlucky cold-start request from tripping the shed policy.
    """

    def __init__(self, objectives: Dict[str, object], *, window: int = 256,
                 min_samples: int = 8, shed_threshold: float = 1.0):
        self.objectives: Dict[str, Dict[str, float]] = {}
        for klass, obj in dict(objectives).items():
            if not isinstance(obj, dict):
                obj = {"objective_ms": float(obj)}
            if "objective_ms" not in obj:
                raise ValueError(f"SLO for class {klass!r} needs "
                                 "'objective_ms'")
            target = float(obj.get("target", 0.99))
            if not 0.0 < target < 1.0:
                raise ValueError(f"SLO target for {klass!r} must be in "
                                 f"(0, 1), got {target}")
            self.objectives[klass] = {
                "objective_s": float(obj["objective_ms"]) / 1000.0,
                "target": target}
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.shed_threshold = float(shed_threshold)
        self._lock = threading.Lock()
        self._samples: Dict[str, deque] = {}     # klass -> deque[bool ok]
        self._burning: set = set()   # classes past shed_threshold (edges)
        mon = monitoring.slo_monitor()
        if mon is not None:
            for klass, obj in self.objectives.items():
                mon.objective_seconds.labels(**{"class": klass}).set(
                    obj["objective_s"])

    # ------------------------------------------------------------- observe
    def observe(self, klass: Optional[str], seconds: float) -> None:
        """Record one served request's latency under its class."""
        klass = klass or "default"
        obj = self.objectives.get(klass)
        ok = obj is None or seconds <= obj["objective_s"]
        with self._lock:
            samples = self._samples.setdefault(klass,
                                               deque(maxlen=self.window))
            samples.append(ok)
            burn = self._burn_locked(klass)
            # edge-detect shed-threshold crossings for the flight recorder:
            # one event per transition, not one per observation
            crossed = None
            if burn is not None:
                if burn > self.shed_threshold and klass not in self._burning:
                    self._burning.add(klass)
                    crossed = "slo_burn"
                elif burn <= self.shed_threshold and klass in self._burning:
                    self._burning.discard(klass)
                    crossed = "slo_recover"
        if crossed is not None:
            rec = flight.recorder()
            if rec is not None:
                rec.record(crossed,
                           severity="warn" if crossed == "slo_burn"
                           else "info",
                           klass=klass, burn_rate=round(burn, 4),
                           threshold=self.shed_threshold)
        mon = monitoring.slo_monitor()
        if mon is not None:
            mon.latency_seconds.labels(**{"class": klass}).observe(seconds)
            if not ok:
                mon.violations_total.labels(**{"class": klass}).inc()
            if burn is not None:
                mon.burn_rate.labels(**{"class": klass}).set(burn)

    def _burn_locked(self, klass: str) -> Optional[float]:
        """Violation fraction / error budget over the window; None when the
        class has no objective or too few samples to judge."""
        obj = self.objectives.get(klass)
        samples = self._samples.get(klass)
        if obj is None or not samples or len(samples) < self.min_samples:
            return None
        bad = sum(1 for ok in samples if not ok)
        return (bad / len(samples)) / (1.0 - obj["target"])

    def burn_rate(self, klass: str) -> Optional[float]:
        with self._lock:
            return self._burn_locked(klass)

    # ---------------------------------------------------------- shed policy
    def should_shed(self, klass: Optional[str]) -> bool:
        """True when some strictly higher-priority class is burning — this
        (lower) class gives up its admission so the burning class's
        objective recovers. Lowest classes shed first by construction:
        batch sheds while default/interactive still admit."""
        rank = class_rank(klass)
        if rank == 0:
            return False        # nothing outranks the top class
        with self._lock:
            for other in self.objectives:
                if class_rank(other) >= rank:
                    continue
                burn = self._burn_locked(other)
                if burn is not None and burn > self.shed_threshold:
                    return True
        return False

    # --------------------------------------------------------------- status
    def status(self) -> dict:
        """The ``GET /slo`` payload: per-class objective/burn/shed state."""
        with self._lock:
            classes = {}
            known = set(self.objectives) | set(self._samples)
            for klass in sorted(known, key=class_rank):
                obj = self.objectives.get(klass)
                samples = self._samples.get(klass, ())
                bad = sum(1 for ok in samples if not ok)
                classes[klass] = {
                    "objective_ms": (None if obj is None
                                     else obj["objective_s"] * 1000.0),
                    "target": None if obj is None else obj["target"],
                    "window_count": len(samples),
                    "violations": bad,
                    "burn_rate": self._burn_locked(klass),
                }
        for klass, st in classes.items():
            st["shedding"] = self.should_shed(klass)
        return {"classes": classes,
                "priority_order": list(PRIORITY_CLASSES),
                "shed_threshold": self.shed_threshold}
