"""Replica autoscaling of ParallelInference workers from serving signals.

The actuator is :meth:`ParallelInference.set_replicas` (worker threads
sharing one lane pair — growth spawns immediately, shrink retires workers
at their next loop check); the sensor is the same backlog that feeds
``dl4j_serving_model_queue_depth``. Policy is deliberately boring:

- scale UP one replica when backlog-per-replica has exceeded
  ``high_backlog`` for ``scale_up_after`` consecutive ticks;
- scale DOWN one replica when it has stayed below ``low_backlog`` for
  ``scale_down_after`` consecutive ticks (down is slower than up — the
  classic hysteresis asymmetry that prevents flapping on bursty load);
- never below ``min_replicas``, never above ``max_replicas``.

Every change moves by ONE replica and resets the streak, so a spike ramps
up over a few ticks instead of slamming to the max, and the decision trail
is legible in ``dl4j_serving_autoscale_total{direction=...}`` +
``dl4j_serving_replicas``.

Drive it manually (``tick()`` from tests/bench) or start the background
thread (``start()``/``stop()``) — the gateway wires the latter into its
lifecycle when constructed with ``autoscale=``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight


class ReplicaAutoscaler:
    """Backlog-driven worker autoscaling over every model in a registry."""

    def __init__(self, registry, *, min_replicas: int = 1,
                 max_replicas: int = 4, high_backlog: float = 8.0,
                 low_backlog: float = 1.0, scale_up_after: int = 2,
                 scale_down_after: int = 5, interval_s: float = 0.25):
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self.registry = registry
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.high_backlog = float(high_backlog)
        self.low_backlog = float(low_backlog)
        self.scale_up_after = int(scale_up_after)
        self.scale_down_after = int(scale_down_after)
        self.interval_s = float(interval_s)
        self._streaks: Dict[str, int] = {}   # key -> +up / -down streak
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---------------------------------------------------------------- tick
    def tick(self) -> Dict[str, dict]:
        """One evaluation pass over every registered (name, version).
        Returns the per-model decision trail (tests and /models debugging).
        """
        decisions: Dict[str, dict] = {}
        with self.registry._lock:
            all_versions = [mv for versions in self.registry._models.values()
                            for mv in versions.values()]
        seen = set()
        mon = monitoring.serving_monitor()
        for mv in all_versions:
            key = f"{mv.name}/{mv.version}"
            seen.add(key)
            replicas = max(1, mv.pi.replicas())
            per_replica = mv.pi.backlog() / replicas
            streak = self._streaks.get(key, 0)
            if per_replica > self.high_backlog:
                streak = streak + 1 if streak > 0 else 1
            elif per_replica < self.low_backlog:
                streak = streak - 1 if streak < 0 else -1
            else:
                streak = 0
            direction = None
            if streak >= self.scale_up_after and replicas < self.max_replicas:
                mv.pi.set_replicas(replicas + 1)
                direction, streak = "up", 0
            elif (streak <= -self.scale_down_after
                    and replicas > self.min_replicas):
                mv.pi.set_replicas(replicas - 1)
                direction, streak = "down", 0
            self._streaks[key] = streak
            target = mv.pi._target
            if direction is not None:
                rec = flight.recorder()
                if rec is not None:
                    rec.record("autoscale", model=mv.name,
                               version=mv.version, direction=direction,
                               replicas=target,
                               backlog_per_replica=round(per_replica, 3))
            if mon is not None:
                mon.replicas.labels(model=mv.name,
                                    version=mv.version).set(target)
                if direction is not None:
                    mon.autoscale_total.labels(
                        model=mv.name, version=mv.version,
                        direction=direction).inc()
            decisions[key] = {"backlog_per_replica": per_replica,
                              "replicas": target, "streak": streak,
                              "scaled": direction}
        # forget models that were unloaded
        for key in list(self._streaks):
            if key not in seen:
                del self._streaks[key]
        return decisions

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "ReplicaAutoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="dl4j-autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def describe(self) -> dict:
        return {"min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "high_backlog": self.high_backlog,
                "low_backlog": self.low_backlog,
                "scale_up_after": self.scale_up_after,
                "scale_down_after": self.scale_down_after,
                "streaks": dict(self._streaks)}
