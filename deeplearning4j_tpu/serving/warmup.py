"""Warmup / AOT precompile at registered batch-shape buckets.

The first request against a cold model pays the XLA compile (hundreds of ms
to minutes) on the request path. ParallelInference already pads partial
batches to power-of-two buckets, so the set of batch shapes a model will
ever see is known *at load time*: the pow2 ladder clamped to ``batch_limit``
(plus ``batch_limit`` itself for non-pow2 limits). ``warmup_model`` runs the
model once per bucket on zeros at load, populating the per-shape jit cache
(and the persistent XLA compilation cache when configured) so no request
ever stalls behind a compile — the serving analog of PyGraph's
remove-the-launch-gap result: the device never waits on the host.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import monitoring


def pow2_buckets(batch_limit: int) -> Tuple[int, ...]:
    """The dispatchable batch sizes under pad-to-bucket batching: powers of
    two clamped to the limit, plus the limit itself (non-pow2 limits)."""
    if batch_limit < 1:
        raise ValueError("batch_limit must be >= 1")
    return tuple(sorted({min(1 << i, batch_limit)
                         for i in range(batch_limit.bit_length() + 1)}))


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest registered bucket >= n (the shape a size-n batch pads to);
    the largest bucket when n exceeds them all (the dispatcher splits)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


def warmup_model(model, example_shape: Sequence[int],
                 buckets: Sequence[int],
                 dtype=np.float32,
                 labels: Optional[Tuple[str, str]] = None) -> Dict[int, float]:
    """Run ``model.output`` once per bucket on zeros of
    ``(bucket, *example_shape)``; returns {bucket: seconds}. ``labels``:
    optional (model, version) pair for the warmup-duration histogram."""
    timings: Dict[int, float] = {}
    shape = tuple(int(d) for d in example_shape)
    for b in sorted(set(int(b) for b in buckets)):
        x = np.zeros((b,) + shape, dtype)
        t0 = time.perf_counter()
        np.asarray(model.output(x))  # np.asarray blocks until device done
        timings[b] = time.perf_counter() - t0
    mon = monitoring.serving_monitor()
    if mon is not None and labels is not None:
        for dt in timings.values():
            mon.warmup_seconds.labels(model=labels[0],
                                      version=labels[1]).observe(dt)
    return timings
