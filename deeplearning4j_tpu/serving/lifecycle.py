"""Preemption-aware serving lifecycle: SIGTERM -> drain -> journal -> exit 0.

On TPU pods the scheduler preempts with a SIGTERM and a grace window; a
process that uses the window well loses NOTHING: in-flight generation
sessions are journaled (generation/sessions.py) for resume-on-restart,
training state gets an emergency checkpoint, and the process exits 0 so the
supervisor restarts it cleanly instead of backing off a "crash".

    manager = (LifecycleManager(grace_s=20.0)
               .register_gateway(gw)
               .register_checkpoint(trainer_save_fn)
               .install())                    # SIGTERM handler
    ...
    # on SIGTERM (or faults class ``preempt``): drain, journal, checkpoint

The drain sequence inside the grace budget:

1. every registered gateway stops admitting (``/readyz`` flips to 503 so
   balancers eject the instance);
2. every generation engine is shut down with ``reason="preempted"`` —
   open streams get a terminal ``finish_reason: "preempted"`` line and
   their session journal records stay OPEN on disk;
3. session journals are fsync'd;
4. gateways finish their graceful stop with whatever budget remains;
5. emergency-checkpoint callbacks run (the trainer hook);
6. ``exit_fn(0)`` if one was configured (``sys.exit`` in production;
   tests leave it None and assert on state instead).

The whole sequence runs on a dedicated ``dl4j-preempt`` thread — the
trigger may be a signal handler or a fault injected INSIDE an engine's own
step loop (faults class ``preempt``), neither of which may block on the
drain. :func:`deliver_preemption` is that injection point's entry: with an
installed manager it starts the drain; unmanaged it raises
:class:`~deeplearning4j_tpu.faults.PreemptionFault` so the driver dies
mid-decode exactly like an unhandled SIGTERM.

Fast restart: re-create the journal, resume before traffic —
``gateway.register_generator(name, engine, sessions=path)`` replays the
journal into the fresh engine (see docs/fault_tolerance.md).
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu import faults, monitoring
from deeplearning4j_tpu.monitoring import flight


class LifecycleManager:
    """Owns the preemption grace budget and the drain choreography."""

    def __init__(self, grace_s: float = 20.0,
                 exit_fn: Optional[Callable[[int], None]] = None):
        self.grace_s = float(grace_s)
        self.exit_fn = exit_fn
        self._gateways: List = []
        self._engines: List = []
        self._journals: List = []
        self._checkpoints: List[Callable[[], None]] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.preempted = threading.Event()
        self.reason: Optional[str] = None
        self.errors: List[str] = []
        self._installed_signals: List[int] = []

    # ------------------------------------------------------- registration
    def register_gateway(self, gateway) -> "LifecycleManager":
        """Drain this gateway (admission off, engines preempted, session
        journals synced) inside the grace budget."""
        self._gateways.append(gateway)
        return self

    def register_engine(self, engine) -> "LifecycleManager":
        """A bare GenerationEngine (no gateway in front of it)."""
        self._engines.append(engine)
        return self

    def register_journal(self, journal) -> "LifecycleManager":
        self._journals.append(journal)
        return self

    def register_checkpoint(self, fn: Callable[[], None]
                            ) -> "LifecycleManager":
        """Emergency-checkpoint callback (e.g. a trainer save); runs after
        the serving drain, still inside the grace budget."""
        self._checkpoints.append(fn)
        return self

    # ------------------------------------------------------------ install
    def install(self, signals=(signal.SIGTERM,)) -> "LifecycleManager":
        """Install as the process preemption handler: the given signals
        (and the faults ``preempt`` class via :func:`deliver_preemption`)
        trigger :meth:`preempt`. No-op for the signal part when not on the
        main thread (tests installing from workers still get the faults
        path)."""
        global _MANAGER
        for s in signals:
            try:
                signal.signal(s, self._on_signal)
                self._installed_signals.append(int(s))
            except ValueError:
                pass  # not the main thread: faults delivery still works
        _MANAGER = self
        return self

    def uninstall(self) -> None:
        global _MANAGER
        for s in self._installed_signals:
            try:
                signal.signal(s, signal.SIG_DFL)
            except ValueError:
                pass
        self._installed_signals = []
        if _MANAGER is self:
            _MANAGER = None

    def _on_signal(self, signum, frame) -> None:
        del frame
        self.preempt(reason=f"signal:{signum}")

    # ------------------------------------------------------------ preempt
    def preempt(self, reason: str = "preempt", wait: bool = False,
                **ctx) -> "LifecycleManager":
        """Begin (or join) the grace-budgeted drain. Idempotent: a second
        trigger while draining just observes the first. ``wait=True``
        blocks until the drain completes (tests; signal handlers and
        injection points leave it False)."""
        with self._lock:
            if self._thread is None:
                self.reason = reason
                rec = flight.recorder()
                if rec is not None:
                    rec.record("preempt", severity="warn", reason=reason,
                               grace_s=self.grace_s,
                               **{k: v for k, v in ctx.items()
                                  if isinstance(v, (int, float, str))})
                mon = monitoring.recovery_monitor()
                if mon is not None:
                    mon.recovery_total.labels(component="lifecycle",
                                              outcome="preempted").inc()
                self._thread = threading.Thread(
                    target=self._drain, name="dl4j-preempt", daemon=True)
                self._thread.start()
        if wait:
            self.preempted.wait()
        return self

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.preempted.wait(timeout)

    def _note(self, err: BaseException) -> None:
        self.errors.append(f"{type(err).__name__}: {err}")

    def _drain(self) -> None:
        deadline = time.monotonic() + self.grace_s

        def remaining() -> float:
            return max(0.0, deadline - time.monotonic())

        # 1. stop admitting everywhere first — the budget pays down
        #    in-flight work, not new arrivals
        for gw in self._gateways:
            gw._draining = True
        # 2. preempt every engine: open streams end "preempted", session
        #    journal records stay open for the restart to resume
        engines = list(self._engines)
        for gw in self._gateways:
            engines.extend(gw._generators.values())
        for eng in engines:
            try:
                eng.shutdown(timeout=remaining(), reason="preempted")
            except Exception as e:  # keep draining the rest of the fleet
                self._note(e)
        # 3. everything journaled so far becomes durable
        journals = list(self._journals)
        for gw in self._gateways:
            journals.extend(getattr(gw, "_sessions", {}).values())
        for eng in engines:
            if getattr(eng, "journal", None) is not None:
                journals.append(eng.journal)
        seen = set()
        for j in journals:
            if id(j) in seen:
                continue
            seen.add(id(j))
            try:
                j.sync()
            except Exception as e:
                self._note(e)
        # 4. finish the gateway stop with whatever budget remains
        for gw in self._gateways:
            try:
                gw.stop(drain=True, timeout=remaining())
            except Exception as e:
                self._note(e)
        # 5. emergency checkpoints (trainer hook)
        for fn in self._checkpoints:
            try:
                fn()
            except Exception as e:
                self._note(e)
        rec = flight.recorder()
        if rec is not None:
            rec.record("preempt_drained", reason=self.reason,
                       errors=len(self.errors))
        self.preempted.set()
        # 6. exit 0: a preemption is not a crash
        if self.exit_fn is not None:
            self.exit_fn(0)

    def describe(self) -> dict:
        return {"grace_s": self.grace_s,
                "preempted": self.preempted.is_set(),
                "reason": self.reason,
                "gateways": len(self._gateways),
                "engines": len(self._engines),
                "checkpoints": len(self._checkpoints),
                "errors": list(self.errors)}


_MANAGER: Optional[LifecycleManager] = None


def manager() -> Optional[LifecycleManager]:
    """The installed manager, or None — injection points do exactly one
    None check (the zero-overhead contract's lifecycle edition)."""
    return _MANAGER


def deliver_preemption(source: str = "", **ctx):
    """The faults ``preempt`` class lands here (engine step loop, trainer
    fit loop). With a manager installed the grace-budgeted drain starts on
    its own thread and the caller keeps stepping until the drain cancels
    it; unmanaged, raise — the driver dies mid-decode like a process that
    never handled SIGTERM."""
    mgr = _MANAGER
    if mgr is None:
        rec = flight.recorder()
        if rec is not None:
            rec.record("preempt", severity="warn", source=source,
                       reason="injected:unmanaged",
                       **{k: v for k, v in ctx.items()
                          if isinstance(v, (int, float, str))})
        raise faults.PreemptionFault(
            f"injected preemption at {source or 'unknown'} "
            f"({', '.join(f'{k}={v}' for k, v in ctx.items())})")
    return mgr.preempt(reason=f"injected:{source or 'fault'}", **ctx)


def reset() -> None:
    """Drop the installed manager (test isolation hook)."""
    global _MANAGER
    if _MANAGER is not None:
        _MANAGER.uninstall()
    _MANAGER = None


__all__ = ["LifecycleManager", "deliver_preemption", "manager", "reset"]
