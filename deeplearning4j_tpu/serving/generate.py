"""The gateway's streaming text-generation tier.

    POST /v1/<name>/generate   {"prompt": "..." | "prompt_ids": [...],
                                "max_new_tokens": 64, "temperature": 0.8,
                                "top_k": 40, "top_p": 0.95, "seed": 7,
                                "eos_id": 3, "stream": true,
                                "timeout_ms": 30000}

Streaming mode (default) answers ndjson — one ``{"token": id, "text":
"..."}`` line per emitted token as it is produced, then a terminal
``{"done": true, "finish_reason": ..., "n_tokens": N}`` line (see
serving/http.py's StreamingResponse for the wire contract). ``"stream":
false`` collects the whole completion and answers one JSON body, bounded by
the admission deadline (504 on expiry, partial work cancelled).

Admission mirrors the predict tier: 503 while draining or after engine
shutdown, 429 + Retry-After when the engine's backlog exceeds the queue
bound (counted in ``dl4j_serving_shed_total{reason="queue_full"}`` and
``dl4j_generate_requests_total{outcome="shed"}``), 404 for an unknown
generator, 400 for a bad prompt. A client that disconnects mid-stream
cancels its generation at the engine's next step — slots are never held by
dead connections.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight
from deeplearning4j_tpu.serving.http import HttpError, StreamingResponse


def match_generate(path: str) -> Optional[dict]:
    """/v1/<name>/generate -> {"name": name} (None = no match)."""
    parts = path.strip("/").split("/")
    if len(parts) == 3 and parts[0] == "v1" and parts[2] == "generate":
        return {"name": parts[1]}
    return None


def _prompt_from(body: dict, engine):
    if "prompt_ids" in body:
        ids = body["prompt_ids"]
        if not isinstance(ids, (list, tuple)):
            raise HttpError(400, "prompt_ids must be a list of token ids")
        return [int(t) for t in ids]
    prompt = body.get("prompt")
    if isinstance(prompt, str):
        if engine.codec is None:
            raise HttpError(400, "this generator has no codec; send "
                                 "prompt_ids")
        return prompt
    raise HttpError(400, "need prompt (string) or prompt_ids (list)")


def handle_generate(gateway, engine, name: str, body: dict,
                    klass: Optional[str] = None, trace=None):
    """The /v1/<name>/generate handler body, shared by the gateway.

    Returns either a plain dict (one-shot) or a StreamingResponse whose
    ``on_finish`` releases the gateway in-flight slot — which is what makes
    ``ServingGateway.stop()`` drain streams, not just one-shot requests.
    ``klass`` is the caller's priority class (multi-tenant gateways):
    ``batch`` requests wait in the engine's low-priority pending lane, so
    interactive submissions claim freed slots first. ``trace`` (traced
    gateways) rides into the engine stream for slot-lifetime spans; a
    streaming response closes it in ``on_finish`` — at last-token (or
    disconnect) time, not at headers-out time.
    """
    mon = monitoring.serving_monitor()
    gmon = monitoring.generate_monitor()
    if engine.pending_count() >= gateway.generate_max_queue:
        if mon is not None:
            mon.shed_total.labels(model=name, reason="queue_full",
                                  **{"class": klass or "default"}).inc()
        if gmon is not None:
            gmon.requests_total.labels(outcome="shed").inc()
        rec = flight.recorder()
        if rec is not None:
            rec.record("shed", severity="warn", model=name,
                       reason="queue_full", klass=klass or "default",
                       trace=trace)
        if trace is not None:
            trace.event("shed", reason="queue_full", model=name)
        raise HttpError(429, "generation queue is full",
                        headers=gateway.admission._retry_headers(
                            engine.pending_count()))
    prompt = _prompt_from(body, engine)
    try:
        stream = engine.submit(
            prompt,
            max_new_tokens=int(body.get("max_new_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            seed=int(body.get("seed", 0)),
            eos_id=body.get("eos_id"),
            klass=klass, trace=trace)
    except RuntimeError as e:  # engine shut down
        raise HttpError(503, str(e),
                        headers=gateway.admission._retry_headers()) from None
    except ValueError as e:
        raise HttpError(400, str(e)) from None
    codec = engine.codec

    if not body.get("stream", True):
        timeout = gateway.admission.timeout_for(body)
        if not stream.wait(timeout):
            stream.cancel()
            raise HttpError(504, "deadline exceeded")
        out = {"tokens": stream.tokens, "n_tokens": len(stream.tokens),
               "finish_reason": stream.finish_reason, "model": name}
        if codec is not None:
            out["text"] = codec.decode(stream.tokens)
        return out

    gateway._track(+1)

    def finish():
        if not stream.done:
            stream.cancel()  # client went away: free the slot
        if trace is not None:
            gateway.tracer.finish(trace, "served", code=200,
                                  reason=stream.finish_reason)
        gateway._track(-1)

    def lines():
        for tok in stream:
            d = {"token": tok}
            if codec is not None:
                d["text"] = codec.decode([tok])
            yield d
        yield {"done": True, "finish_reason": stream.finish_reason,
               "n_tokens": len(stream.tokens), "model": name}

    return StreamingResponse(lines(), on_finish=finish)


def read_ndjson_stream(resp):
    """Client-side helper: iterate the parsed ndjson lines of a streaming
    ``/generate`` response (an ``http.client``/``urllib`` response object)."""
    import json

    for raw in resp:
        raw = raw.strip()
        if raw:
            yield json.loads(raw)
