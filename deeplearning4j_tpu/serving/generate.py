"""The gateway's streaming text-generation tier.

    POST /v1/<name>/generate   {"prompt": "..." | "prompt_ids": [...],
                                "max_new_tokens": 64, "temperature": 0.8,
                                "top_k": 40, "top_p": 0.95, "seed": 7,
                                "eos_id": 3, "stream": true,
                                "timeout_ms": 30000}

Streaming mode (default) answers ndjson — one ``{"token": id, "text":
"..."}`` line per emitted token as it is produced, then a terminal
``{"done": true, "finish_reason": ..., "n_tokens": N}`` line (see
serving/http.py's StreamingResponse for the wire contract). ``"stream":
false`` collects the whole completion and answers one JSON body, bounded by
the admission deadline (504 on expiry, partial work cancelled).

Admission mirrors the predict tier: 503 while draining or after engine
shutdown, 429 + Retry-After when the engine's backlog exceeds the queue
bound (counted in ``dl4j_serving_shed_total{reason="queue_full"}`` and
``dl4j_generate_requests_total{outcome="shed"}``), 404 for an unknown
generator, 400 for a bad prompt. A client that disconnects mid-stream
cancels its generation at the engine's next step — slots are never held by
dead connections.

Durable sessions (generators registered with ``sessions=``): a request
carrying ``X-Request-Id`` (header) or ``request_id`` (body) is journaled,
its ndjson lines gain 1-based ``"seq"`` numbers, and a disconnect does NOT
cancel it — the engine keeps generating into the journal. The client
reconnects by POSTing the same ``X-Request-Id`` with ``last_seq`` (body,
or ``X-Last-Seq`` header) and receives exactly the not-yet-seen tokens:
the journaled prefix replays, then the live stream is followed. After a
preemption + restart the journal resumes the session bit-identically
(generation/sessions.py), so the reconnect contract spans process deaths.
Corrupt/lost sessions answer a clean 503; unknown ids start a NEW durable
session under that id. See docs/fault_tolerance.md for curl examples.
"""

from __future__ import annotations

from typing import Optional, Tuple

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight
from deeplearning4j_tpu.serving.http import HttpError, StreamingResponse


def match_generate(path: str) -> Optional[dict]:
    """/v1/<name>/generate -> {"name": name} (None = no match)."""
    parts = path.strip("/").split("/")
    if len(parts) == 3 and parts[0] == "v1" and parts[2] == "generate":
        return {"name": parts[1]}
    return None


def _prompt_from(body: dict, engine):
    if "prompt_ids" in body:
        ids = body["prompt_ids"]
        if not isinstance(ids, (list, tuple)):
            raise HttpError(400, "prompt_ids must be a list of token ids")
        return [int(t) for t in ids]
    prompt = body.get("prompt")
    if isinstance(prompt, str):
        if engine.codec is None:
            raise HttpError(400, "this generator has no codec; send "
                                 "prompt_ids")
        return prompt
    raise HttpError(400, "need prompt (string) or prompt_ids (list)")


def _session_identity(body: dict, headers) -> Tuple[Optional[str], int]:
    """(request_id, last_seq) from the request, headers winning over body
    fields (a reconnecting proxy sets headers without reparsing the body).
    """
    rid = None
    if headers is not None:
        rid = headers.get("X-Request-Id")
    if not rid:
        rid = body.get("request_id")
    raw = body.get("last_seq")
    if raw is None and headers is not None:
        raw = headers.get("X-Last-Seq")
    try:
        last_seq = max(0, int(raw or 0))
    except (TypeError, ValueError):
        raise HttpError(400, "last_seq must be an integer") from None
    return (str(rid) if rid else None), last_seq


def handle_generate(gateway, engine, name: str, body: dict,
                    klass: Optional[str] = None, trace=None, headers=None):
    """The /v1/<name>/generate handler body, shared by the gateway.

    Returns either a plain dict (one-shot) or a StreamingResponse whose
    ``on_finish`` releases the gateway in-flight slot — which is what makes
    ``ServingGateway.stop()`` drain streams, not just one-shot requests.
    ``klass`` is the caller's priority class (multi-tenant gateways):
    ``batch`` requests wait in the engine's low-priority pending lane, so
    interactive submissions claim freed slots first. ``trace`` (traced
    gateways) rides into the engine stream for slot-lifetime spans; a
    streaming response closes it in ``on_finish`` — at last-token (or
    disconnect) time, not at headers-out time.
    """
    mon = monitoring.serving_monitor()
    gmon = monitoring.generate_monitor()
    journal = gateway._sessions.get(name) if gateway._sessions else None
    request_id = None
    if journal is not None:
        request_id, last_seq = _session_identity(body, headers)
        if request_id is not None:
            rec = journal.get(request_id)
            if rec is not None:  # a reconnect, not a new submission
                return _reconnect(gateway, engine, name, rec, body,
                                  last_seq, trace)
    if engine.pending_count() >= gateway.generate_max_queue:
        if mon is not None:
            mon.shed_total.labels(model=name, reason="queue_full",
                                  **{"class": klass or "default"}).inc()
        if gmon is not None:
            gmon.requests_total.labels(outcome="shed").inc()
        rec = flight.recorder()
        if rec is not None:
            rec.record("shed", severity="warn", model=name,
                       reason="queue_full", klass=klass or "default",
                       trace=trace)
        if trace is not None:
            trace.event("shed", reason="queue_full", model=name)
        raise HttpError(429, "generation queue is full",
                        headers=gateway.admission._retry_headers(
                            engine.pending_count()))
    prompt = _prompt_from(body, engine)
    try:
        stream = engine.submit(
            prompt,
            max_new_tokens=int(body.get("max_new_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            seed=int(body.get("seed", 0)),
            eos_id=body.get("eos_id"),
            klass=klass, trace=trace, request_id=request_id)
    except RuntimeError as e:  # engine shut down
        raise HttpError(503, str(e),
                        headers=gateway.admission._retry_headers()) from None
    except ValueError as e:
        raise HttpError(400, str(e)) from None
    codec = engine.codec
    durable = request_id is not None  # journaled: survives disconnects

    if not body.get("stream", True):
        timeout = gateway.admission.timeout_for(body)
        if not stream.wait(timeout):
            if not durable:  # a durable session keeps generating
                stream.cancel()
            raise HttpError(504, "deadline exceeded")
        out = {"tokens": stream.tokens, "n_tokens": len(stream.tokens),
               "finish_reason": stream.finish_reason, "model": name}
        if durable:
            out["request_id"] = request_id
        if codec is not None:
            out["text"] = codec.decode(stream.tokens)
        return out

    gateway._track(+1)

    def finish():
        # a durable session outlives its connection: the engine keeps
        # generating into the journal and the client reconnects by id
        if not stream.done and not durable:
            stream.cancel()  # client went away: free the slot
        if trace is not None:
            gateway.tracer.finish(trace, "served", code=200,
                                  reason=stream.finish_reason)
        gateway._track(-1)

    def lines():
        seq = 0
        for tok in stream:
            seq += 1
            d = {"token": tok}
            if durable:
                d["seq"] = seq
                d["request_id"] = request_id
            if codec is not None:
                d["text"] = codec.decode([tok])
            yield d
        term = {"done": True, "finish_reason": stream.finish_reason,
                "n_tokens": len(stream.tokens), "model": name}
        if durable:
            term["request_id"] = request_id
        yield term

    return StreamingResponse(lines(), on_finish=finish)


def _reconnect(gateway, engine, name: str, rec, body: dict, last_seq: int,
               trace=None):
    """A request whose id is already in the session journal: replay the
    journaled tokens past ``last_seq`` (exactly-once by sequence number),
    then follow the live stream if the session is still generating.

    Reconnects never submit work — they observe the existing session — so
    they skip the queue-full shed and never fail with 429. The failure
    modes are all clean errors: a corrupt/lost journal record answers 503
    immediately (never a hang), and an interrupted session that has not
    yet been resumed into an engine answers 503 + Retry-After.
    """
    rid = rec.request_id
    if rec.corrupt or rec.lost:
        raise HttpError(
            503, f"session {rid!r} cannot be recovered: "
                 + ("journal corrupt" if rec.corrupt else "resume failed"))
    stream = rec.stream
    live = stream is not None and not stream.done
    if not live and rec.finish_reason is None:
        # interrupted (crash/preempt) and not resumed here yet: the
        # restart path resumes before traffic, so tell the client to retry
        raise HttpError(503, f"session {rid!r} is being recovered",
                        headers=gateway.admission._retry_headers())
    if trace is not None:
        trace.event("session_reconnect", request_id=rid, last_seq=last_seq,
                    live=live)
    frec = flight.recorder()
    if frec is not None:
        frec.record("session_reconnect", model=name, request_id=rid,
                    last_seq=last_seq, live=live, trace=trace)
    codec = engine.codec

    def _finish_reason():
        if rec.finish_reason is not None:
            return rec.finish_reason
        return stream.finish_reason if stream is not None else None

    if not body.get("stream", True):
        if live:
            timeout = gateway.admission.timeout_for(body)
            if not stream.wait(timeout):  # session stays alive: no cancel
                raise HttpError(504, "deadline exceeded")
        toks = list(rec.tokens[last_seq:])
        out = {"tokens": toks, "n_tokens": len(rec.tokens),
               "finish_reason": _finish_reason(), "model": name,
               "request_id": rid, "last_seq": last_seq}
        if codec is not None:
            out["text"] = codec.decode(toks)
        return out

    gateway._track(+1)

    def finish():
        if trace is not None:
            gateway.tracer.finish(trace, "served", code=200,
                                  reason=_finish_reason())
        gateway._track(-1)

    def lines():
        # 1. the journaled prefix — durable, ordered, exactly-once: every
        #    line the client already consumed (seq <= last_seq) is skipped
        i = last_seq
        stable = stream.seq0 if live else len(rec.tokens)
        while i < stable:
            d = {"seq": i + 1, "token": rec.tokens[i], "request_id": rid}
            if codec is not None:
                d["text"] = codec.decode([rec.tokens[i]])
            yield d
            i += 1
        # 2. the live tail (seq numbers continue where the prefix ended)
        if live:
            for seq, tok in stream.follow(last_seq=i):
                d = {"seq": seq, "token": tok, "request_id": rid}
                if codec is not None:
                    d["text"] = codec.decode([tok])
                yield d
        yield {"done": True, "finish_reason": _finish_reason(),
               "n_tokens": len(rec.tokens), "model": name,
               "request_id": rid, "resumes": rec.resumes}

    return StreamingResponse(lines(), on_finish=finish)


def read_ndjson_stream(resp):
    """Client-side helper: iterate the parsed ndjson lines of a streaming
    ``/generate`` response (an ``http.client``/``urllib`` response object)."""
    import json

    for raw in resp:
        raw = raw.strip()
        if raw:
            yield json.loads(raw)
