"""Multi-tenant admission: API keys, priority classes, sliding-window quotas.

The scenario this kills: one abusive (or merely enthusiastic) tenant fills
the queues and every other tenant's latency degrades equally. Here each
tenant authenticates with an API key (``X-Api-Key`` header or ``api_key``
body field), carries a priority class (``interactive`` > ``batch``) that the
queues and slot pools honor, and is metered against sliding-window request
and token quotas — a request over quota is rejected NOW with 429 and a
``Retry-After`` computed from when the window actually frees up, instead of
degrading everyone.

Zero-overhead contract: a gateway constructed without ``tenants=`` never
builds a :class:`TenantTable` and the request path performs none of this —
no key lookup, no window pruning, no per-tenant metrics (spy-guarded in
tests/test_serving_gateway.py).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Dict, Iterable, Optional, Union

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.serving.http import HttpError

#: priority classes, highest first — shed order is the reverse
PRIORITY_CLASSES = ("interactive", "default", "batch")


def class_rank(klass: Optional[str]) -> int:
    """Smaller = higher priority; unknown classes rank with ``default``."""
    try:
        return PRIORITY_CLASSES.index(klass or "default")
    except ValueError:
        return PRIORITY_CLASSES.index("default")


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One API-key principal: identity, priority class, and quota bounds.

    ``requests_per_window`` / ``tokens_per_window`` of None means unmetered
    for that resource; ``window_s`` is the sliding accounting window. A
    predict request costs its batch-row count in tokens; a generate request
    costs its requested ``max_new_tokens``.
    """

    key: str
    name: str
    klass: str = "interactive"
    requests_per_window: Optional[int] = None
    tokens_per_window: Optional[int] = None
    window_s: float = 60.0

    def __post_init__(self):
        if self.klass not in PRIORITY_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: unknown priority class "
                f"{self.klass!r} (known: {', '.join(PRIORITY_CLASSES)})")


class QuotaExceeded(HttpError):
    """429 with a drain-aware Retry-After; ``resource`` says which quota
    (requests/tokens) bit."""

    def __init__(self, tenant: str, resource: str, retry_after_s: float):
        retry = min(max(int(math.ceil(retry_after_s)), 1), 30)
        super().__init__(
            429, f"tenant {tenant!r} {resource} quota exceeded; retry later",
            headers={"Retry-After": str(retry)})
        self.resource = resource


class TenantTable:
    """API-key -> Tenant resolution plus sliding-window usage accounting.

    Thread-safe: the gateway's handler threads authorize/admit concurrently.
    Usage is a per-tenant deque of ``(t, tokens)`` events pruned lazily at
    admit time — O(evicted) per call, no background thread.
    """

    def __init__(self, tenants: Iterable[Union[Tenant, dict]]):
        self._tenants: Dict[str, Tenant] = {}
        for t in tenants:
            if isinstance(t, dict):
                t = Tenant(**t)
            if t.key in self._tenants:
                raise ValueError(f"duplicate tenant API key for {t.name!r}")
            self._tenants[t.key] = t
        self._usage: Dict[str, deque] = {t.name: deque()
                                         for t in self._tenants.values()}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._tenants)

    def tenants(self):
        return list(self._tenants.values())

    # -------------------------------------------------------------- authn
    def authorize(self, body: dict, headers=None) -> Tenant:
        """Resolve the request's tenant from ``X-Api-Key`` (header) or
        ``api_key`` (body). 401 on missing/unknown key — multi-tenant
        gateways serve no anonymous traffic."""
        key = None
        if headers is not None:
            key = headers.get("X-Api-Key")
        if key is None:
            key = body.get("api_key")
        if key is None:
            self._count_anon("missing_key")
            raise HttpError(401, "missing API key (X-Api-Key header or "
                                 "api_key body field)")
        tenant = self._tenants.get(key)
        if tenant is None:
            self._count_anon("unknown_key")
            raise HttpError(401, "unknown API key")
        return tenant

    def _count_anon(self, outcome: str):
        mon = monitoring.tenant_monitor()
        if mon is not None:
            mon.requests_total.labels(tenant="<unauthorized>",
                                      outcome=outcome).inc()

    # -------------------------------------------------------------- quota
    def _prune(self, events: deque, now: float, window: float):
        cutoff = now - window
        while events and events[0][0] <= cutoff:
            events.popleft()

    def admit(self, tenant: Tenant, tokens: int = 1) -> None:
        """Charge one request of ``tokens`` cost against the tenant's
        sliding window, or raise :class:`QuotaExceeded` (429) with a
        Retry-After saying when the window will have drained enough."""
        now = time.monotonic()
        with self._lock:
            events = self._usage[tenant.name]
            self._prune(events, now, tenant.window_s)
            n_req = len(events)
            n_tok = sum(e[1] for e in events)
            resource = None
            if (tenant.requests_per_window is not None
                    and n_req + 1 > tenant.requests_per_window):
                resource = "requests"
            elif (tenant.tokens_per_window is not None
                    and n_tok + tokens > tenant.tokens_per_window):
                resource = "tokens"
            if resource is not None:
                # the window frees up when its oldest event ages out
                retry = (events[0][0] + tenant.window_s - now) if events \
                    else tenant.window_s
                self._record(tenant, f"quota_{resource}", 0, n_req, n_tok)
                raise QuotaExceeded(tenant.name, resource, retry)
            events.append((now, tokens))
            n_req, n_tok = n_req + 1, n_tok + tokens
        self._record(tenant, "admitted", tokens, n_req, n_tok)

    def usage(self, tenant: Tenant) -> Dict[str, int]:
        """Current in-window usage (requests, tokens) for status surfaces."""
        now = time.monotonic()
        with self._lock:
            events = self._usage[tenant.name]
            self._prune(events, now, tenant.window_s)
            return {"requests": len(events),
                    "tokens": sum(e[1] for e in events)}

    def _record(self, tenant: Tenant, outcome: str, tokens: int,
                n_req: int, n_tok: int):
        mon = monitoring.tenant_monitor()
        if mon is None:
            return
        mon.requests_total.labels(tenant=tenant.name, outcome=outcome).inc()
        if tokens:
            mon.tokens_total.labels(tenant=tenant.name).inc(tokens)
        if tenant.requests_per_window is not None:
            mon.quota_remaining.labels(tenant=tenant.name,
                                       resource="requests").set(
                max(0, tenant.requests_per_window - n_req))
        if tenant.tokens_per_window is not None:
            mon.quota_remaining.labels(tenant=tenant.name,
                                       resource="tokens").set(
                max(0, tenant.tokens_per_window - n_tok))
