"""Gateway failover: per-replica circuit breakers + idempotency-keyed retry.

A replica (one registered (model, version)) that starts failing its
forwards should stop receiving traffic BEFORE clients notice; a request
that hit the failing replica should be retried once on a healthy sibling
— without ever executing twice from the client's point of view.

Circuit breaker (closed -> open -> half-open, per replica):

- ``closed``   normal; errors are counted over a sliding outcome window.
  Trips open on ``consecutive_errors`` in a row OR a windowed error rate
  >= ``error_rate`` (with at least ``window`` outcomes observed).
- ``open``     the router excludes the replica; after ``cooldown_s`` one
  probe request is let through (half-open).
- ``half_open`` the probe's outcome decides: success -> closed (fresh
  window), failure -> open again (new cooldown).

Transitions land in ``dl4j_recovery_total{component="gateway",
outcome="breaker_open"|"breaker_closed"}`` and the flight recorder
(``breaker_open`` events), so a postmortem shows exactly when a replica
was ejected and readmitted.

Idempotency: a non-streaming predict carrying ``Idempotency-Key`` (header)
or ``idempotency_key`` (body) has its successful response cached for
``ttl_s``; a client retry with the same key replays the stored response
byte-for-byte instead of re-running the forward — the retry loop in
``ServingGateway._predict_inner`` (driven by the shared
:class:`~deeplearning4j_tpu.faults.retry.RetryPolicy`) is therefore safe
to be aggressive.

Configured via ``ServingGateway(failover={...})``; an unconfigured gateway
holds ``failover=None`` and the request path does ZERO breaker/cache work
(the spy-guarded zero-overhead contract, same as tenancy/SLO/tracing).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.faults.retry import RetryPolicy
from deeplearning4j_tpu.monitoring import flight


class CircuitBreaker:
    """One replica's health automaton. Thread-safe; time injectable."""

    def __init__(self, consecutive_errors: int = 3, error_rate: float = 0.5,
                 window: int = 16, cooldown_s: float = 5.0,
                 clock=time.monotonic):
        self.consecutive_errors = int(consecutive_errors)
        self.error_rate = float(error_rate)
        self.window = int(window)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self._outcomes: "deque[bool]" = deque(maxlen=self.window)
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        self.opened_total = 0

    def allow(self) -> bool:
        """May a request be routed to this replica right now? An open
        breaker admits exactly one probe once the cooldown elapses."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self.state = "half_open"
                    self._probing = True
                    return True
                return False
            # half_open: one probe in flight at a time
            if not self._probing:
                self._probing = True
                return True
            return False

    def _trip(self) -> bool:
        self.state = "open"
        self._opened_at = self._clock()
        self._consecutive = 0
        self._outcomes.clear()
        self.opened_total += 1
        return True

    def record(self, ok: bool) -> Optional[str]:
        """Feed one outcome; returns "opened"/"closed" on a state change
        (the caller emits metrics/flight events — the breaker stays pure).
        """
        with self._lock:
            if self.state == "half_open":
                self._probing = False
                if ok:
                    self.state = "closed"
                    self._outcomes.clear()
                    self._consecutive = 0
                    return "closed"
                self._trip()
                return "opened"
            if self.state == "open":
                return None  # late result from before the trip
            self._outcomes.append(ok)
            self._consecutive = 0 if ok else self._consecutive + 1
            if not ok:
                errs = sum(1 for o in self._outcomes if not o)
                if (self._consecutive >= self.consecutive_errors
                        or (len(self._outcomes) >= self.window
                            and errs / len(self._outcomes)
                            >= self.error_rate)):
                    self._trip()
                    return "opened"
            return None

    def describe(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "consecutive_errors": self._consecutive,
                    "window": list(self._outcomes),
                    "opened_total": self.opened_total}


class IdempotencyCache:
    """Bounded TTL map: idempotency key -> stored response payload."""

    def __init__(self, ttl_s: float = 120.0, capacity: int = 1024,
                 clock=time.monotonic):
        self.ttl_s = float(ttl_s)
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        self._d: "OrderedDict[str, Tuple[float, dict]]" = OrderedDict()
        self.replays = 0

    def get(self, key: str) -> Optional[dict]:
        now = self._clock()
        with self._lock:
            hit = self._d.get(key)
            if hit is None:
                return None
            at, payload = hit
            if now - at > self.ttl_s:
                del self._d[key]
                return None
            self.replays += 1
            return payload

    def put(self, key: str, payload: dict) -> None:
        with self._lock:
            self._d[key] = (self._clock(), payload)
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)


class GatewayFailover:
    """The gateway's failover brain: breakers per replica, the idempotency
    cache, and the retry policy the predict path runs failed attempts
    under. Built only when ``ServingGateway(failover=...)`` is configured.
    """

    def __init__(self, consecutive_errors: int = 3, error_rate: float = 0.5,
                 window: int = 16, cooldown_s: float = 5.0,
                 retries: int = 1, retry_base_delay_s: float = 0.01,
                 idempotency_ttl_s: float = 120.0,
                 idempotency_capacity: int = 1024,
                 clock=time.monotonic):
        self._breaker_kw = dict(consecutive_errors=consecutive_errors,
                                error_rate=error_rate, window=window,
                                cooldown_s=cooldown_s, clock=clock)
        self.retries = int(retries)
        self.idempotency = IdempotencyCache(ttl_s=idempotency_ttl_s,
                                            capacity=idempotency_capacity,
                                            clock=clock)
        self._lock = threading.Lock()
        self._breakers: Dict[Tuple[str, str], CircuitBreaker] = {}
        # the shared RetryPolicy drives the cross-replica retry: attempts
        # land in dl4j_retry_attempts_total{component="gateway"} and the
        # eventual outcome in dl4j_recovery_total{component="gateway"}
        self.retry_policy = RetryPolicy(
            max_attempts=self.retries + 1, base_delay_s=retry_base_delay_s,
            max_delay_s=0.25, deadline_s=30.0, retry_on=(ReplicaFailed,),
            seed=0)

    def breaker(self, name: str, version: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get((name, version))
            if b is None:
                b = self._breakers[(name, version)] = CircuitBreaker(
                    **self._breaker_kw)
            return b

    def excluded(self, name: str) -> set:
        """Versions of ``name`` the router should avoid right now (their
        breaker is open and still cooling down)."""
        with self._lock:
            items = [(k[1], b) for k, b in self._breakers.items()
                     if k[0] == name]
        return {v for v, b in items if not b.allow()}

    def record(self, name: str, version: str, ok: bool, trace=None) -> None:
        """Feed a replica outcome; emits the transition's metric + flight
        event when the breaker changes state."""
        change = self.breaker(name, version).record(ok)
        if change is None:
            return
        mon = monitoring.recovery_monitor()
        if mon is not None:
            mon.recovery_total.labels(
                component="gateway",
                outcome=f"breaker_{change}").inc()
        rec = flight.recorder()
        if rec is not None:
            rec.record(f"breaker_{change}",
                       severity="warn" if change == "opened" else "info",
                       model=name, version=version, trace=trace)
        if trace is not None:
            trace.event(f"breaker_{change}", model=name, version=version)

    def idempotency_key(self, body: dict, headers=None) -> Optional[str]:
        key = None
        if headers is not None:
            key = headers.get("Idempotency-Key")
        if key is None:
            key = body.get("idempotency_key")
        return key

    def describe(self) -> dict:
        with self._lock:
            breakers = {f"{n}/{v}": b.describe()
                        for (n, v), b in self._breakers.items()}
        return {"breakers": breakers,
                "idempotency_replays": self.idempotency.replays,
                "retries": self.retries}


class ReplicaFailed(Exception):
    """Retryable wrapper: a routed replica 500'd and a sibling is worth
    trying. ``error`` carries the original HttpError for the case where
    every attempt fails."""

    def __init__(self, error):
        super().__init__(str(error))
        self.error = error


__all__ = ["CircuitBreaker", "GatewayFailover", "IdempotencyCache",
           "ReplicaFailed"]
