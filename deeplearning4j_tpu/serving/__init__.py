"""Production serving subsystem.

Grew out of the single-model ``serving.py`` (kept importable here unchanged:
``ModelServer``, ``KNNServer``) into a real serving tier:

- :mod:`~deeplearning4j_tpu.serving.gateway` — :class:`ServingGateway`, the
  multi-model HTTP front: per-model ``POST /v1/<name>/predict``, admin
  ``POST /models/*`` routes, ``/healthz`` / ``/readyz``, graceful drain;
- :mod:`~deeplearning4j_tpu.serving.registry` — named + versioned models,
  hot load/unload/reload, weighted canary traffic splits;
- :mod:`~deeplearning4j_tpu.serving.admission` — bounded queues, per-request
  deadlines, 429/503/504 backpressure, load-shed counters;
- :mod:`~deeplearning4j_tpu.serving.warmup` — pad-to-bucket batch shapes
  precompiled at model load, so no request pays a cold XLA compile;
- :mod:`~deeplearning4j_tpu.serving.http` — stdlib JSON-over-HTTP
  scaffolding (+ ``GET /metrics`` Prometheus exposition on every server);
- :mod:`~deeplearning4j_tpu.serving.tenancy` — API keys, priority classes
  (``interactive`` > ``default`` > ``batch``), sliding-window quotas;
- :mod:`~deeplearning4j_tpu.serving.slo` — per-class latency objectives,
  burn rate, shed-lowest-class-first overload policy, ``GET /slo``;
- :mod:`~deeplearning4j_tpu.serving.autoscale` — backlog-driven replica
  autoscaling of each model's ParallelInference worker pool;
- :mod:`~deeplearning4j_tpu.serving.lifecycle` — preemption-aware drain:
  SIGTERM -> journal sessions -> emergency checkpoint -> exit 0;
- :mod:`~deeplearning4j_tpu.serving.failover` — per-replica circuit
  breakers + idempotency-keyed cross-replica retry of failed predicts.

See ``docs/serving.md`` for routes, admission knobs, and a canary example;
``docs/slo.md`` for the multi-tenant/SLO runbook; ``docs/fault_tolerance.md``
for preemption + session recovery.
"""

# Lazy re-exports (PEP 562): the generation engine imports
# serving.warmup's bucket helpers, and eagerly importing the whole HTTP
# gateway stack alongside them would drag threading servers into every
# `import deeplearning4j_tpu.generation` (guarded by
# tests/test_generation.py's import-graph test).
_EXPORTS = {
    "AdmissionController": "deeplearning4j_tpu.serving.admission",
    "ServingGateway": "deeplearning4j_tpu.serving.gateway",
    "Tenant": "deeplearning4j_tpu.serving.tenancy",
    "TenantTable": "deeplearning4j_tpu.serving.tenancy",
    "QuotaExceeded": "deeplearning4j_tpu.serving.tenancy",
    "PRIORITY_CLASSES": "deeplearning4j_tpu.serving.tenancy",
    "SloTracker": "deeplearning4j_tpu.serving.slo",
    "ReplicaAutoscaler": "deeplearning4j_tpu.serving.autoscale",
    "HttpError": "deeplearning4j_tpu.serving.http",
    "serve_json": "deeplearning4j_tpu.serving.http",
    "_serve_json": "deeplearning4j_tpu.serving.http",
    "_HttpServerMixin": "deeplearning4j_tpu.serving.http",
    "KNNServer": "deeplearning4j_tpu.serving.legacy",
    "ModelServer": "deeplearning4j_tpu.serving.legacy",
    "ModelRegistry": "deeplearning4j_tpu.serving.registry",
    "ModelVersion": "deeplearning4j_tpu.serving.registry",
    "bucket_for": "deeplearning4j_tpu.serving.warmup",
    "pow2_buckets": "deeplearning4j_tpu.serving.warmup",
    "warmup_model": "deeplearning4j_tpu.serving.warmup",
    "LifecycleManager": "deeplearning4j_tpu.serving.lifecycle",
    "CircuitBreaker": "deeplearning4j_tpu.serving.failover",
    "GatewayFailover": "deeplearning4j_tpu.serving.failover",
    "IdempotencyCache": "deeplearning4j_tpu.serving.failover",
    "ReplicaFailed": "deeplearning4j_tpu.serving.failover",
}

__all__ = [
    "ServingGateway", "ModelRegistry", "ModelVersion",
    "AdmissionController", "HttpError", "serve_json",
    "Tenant", "TenantTable", "QuotaExceeded", "PRIORITY_CLASSES",
    "SloTracker", "ReplicaAutoscaler",
    "ModelServer", "KNNServer",
    "pow2_buckets", "bucket_for", "warmup_model",
    "LifecycleManager", "CircuitBreaker", "GatewayFailover",
    "IdempotencyCache", "ReplicaFailed",
]


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
