"""Production serving subsystem.

Grew out of the single-model ``serving.py`` (kept importable here unchanged:
``ModelServer``, ``KNNServer``) into a real serving tier:

- :mod:`~deeplearning4j_tpu.serving.gateway` — :class:`ServingGateway`, the
  multi-model HTTP front: per-model ``POST /v1/<name>/predict``, admin
  ``POST /models/*`` routes, ``/healthz`` / ``/readyz``, graceful drain;
- :mod:`~deeplearning4j_tpu.serving.registry` — named + versioned models,
  hot load/unload/reload, weighted canary traffic splits;
- :mod:`~deeplearning4j_tpu.serving.admission` — bounded queues, per-request
  deadlines, 429/503/504 backpressure, load-shed counters;
- :mod:`~deeplearning4j_tpu.serving.warmup` — pad-to-bucket batch shapes
  precompiled at model load, so no request pays a cold XLA compile;
- :mod:`~deeplearning4j_tpu.serving.http` — stdlib JSON-over-HTTP
  scaffolding (+ ``GET /metrics`` Prometheus exposition on every server).

See ``docs/serving.md`` for routes, admission knobs, and a canary example.
"""

from deeplearning4j_tpu.serving.admission import AdmissionController
from deeplearning4j_tpu.serving.gateway import ServingGateway
from deeplearning4j_tpu.serving.http import HttpError, serve_json, _serve_json, _HttpServerMixin
from deeplearning4j_tpu.serving.legacy import KNNServer, ModelServer
from deeplearning4j_tpu.serving.registry import ModelRegistry, ModelVersion
from deeplearning4j_tpu.serving.warmup import (bucket_for, pow2_buckets,
                                               warmup_model)

__all__ = [
    "ServingGateway", "ModelRegistry", "ModelVersion",
    "AdmissionController", "HttpError", "serve_json",
    "ModelServer", "KNNServer",
    "pow2_buckets", "bucket_for", "warmup_model",
]
