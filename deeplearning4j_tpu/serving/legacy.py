"""Single-model servers predating the gateway (kept as the simple tier).

Reference analog: the reference's serving tier — ParallelInference behind a
REST endpoint (deeplearning4j model server / nearest-neighbors-server
pattern). Stdlib-only HTTP: POST /predict with JSON {"inputs": [[...]]}
returns {"outputs": [[...]]}; batching + async execution come from
ParallelInference underneath, so concurrent requests share device batches.

For multi-model registry / canary splits / admission control / warmup, use
:class:`deeplearning4j_tpu.serving.ServingGateway`.
"""

from __future__ import annotations

import queue
import time
import numpy as np

from deeplearning4j_tpu.parallel.inference import (DeadlineExceeded,
                                                   ParallelInference)
from deeplearning4j_tpu.serving.http import (HttpError, _HttpServerMixin,
                                             serve_json)


class ModelServer(_HttpServerMixin):
    """Serve a model's output() via JSON HTTP.

        server = ModelServer(model, port=0).start()
        ... POST http://host:port/predict {"inputs": [...]}
        server.stop()
    """

    def __init__(self, model, port: int = 0, host: str = "127.0.0.1",
                 batch_limit: int = 32, queue_timeout: float = 30.0):
        self.model = model
        self._host, self._port = host, port
        self._timeout = queue_timeout
        self._pi = ParallelInference(model, batch_limit=batch_limit)

    def start(self) -> "ModelServer":
        self._pi.start()
        pi, timeout = self._pi, self._timeout

        def predict(body):
            xs = np.asarray(body["inputs"], np.float32)
            # one shared deadline for the whole request: when the first
            # result times out, the worker sheds the expired siblings too
            # instead of computing for (and orphaning) a gone client
            deadline = time.monotonic() + timeout
            queues = [pi.submit(x, deadline=deadline) for x in xs]
            outs = []
            for q in queues:
                try:
                    r = q.get(timeout=max(deadline - time.monotonic(), 0.001))
                except queue.Empty:
                    raise HttpError(504, "prediction timed out") from None
                if isinstance(r, DeadlineExceeded):
                    raise HttpError(504, "prediction timed out") from None
                if isinstance(r, BaseException):
                    raise HttpError(500, f"forward pass failed: {r}") from None
                outs.append(np.asarray(r).tolist())
            return {"outputs": outs}

        self._httpd, self._thread = serve_json(
            self._host, self._port,
            post_routes={"/predict": predict},
            get_routes={"/health": lambda _: {"status": "ok"}})
        return self

    def stop(self):
        self._stop_httpd()
        self._pi.drain()


class KNNServer(_HttpServerMixin):
    """Nearest-neighbors HTTP server.

    Reference analog: deeplearning4j-nearestneighbors-server's NearestNeighborsServer —
    a VPTree over an indexed point set behind REST. Endpoints:

        POST /knn     {"point": [...], "k": n}
                      -> {"results": [{"index": i, "distance": d}, ...]}
        POST /knnvec  {"vectors": [[...], ...], "k": n}   (batched; brute
                      MXU path — one device matmul for the whole batch)
                      -> {"results": [[{"index", "distance"}, ...], ...]}
        GET  /health

    ``backend``: "vptree" (default, the reference's structure) | "kdtree" |
    "brute" (single points also answered by the batched MXU path).
    """

    def __init__(self, points, port: int = 0, host: str = "127.0.0.1",
                 backend: str = "vptree"):
        from deeplearning4j_tpu.neighbors import KDTree, VPTree, knn_search

        self.points = np.asarray(points, np.float32)
        self._host, self._port = host, port
        self._brute = lambda qs, k: knn_search(self.points, qs, k=k)
        if backend == "vptree":
            self._tree = VPTree(self.points)
        elif backend == "kdtree":
            self._tree = KDTree(self.points)
        elif backend == "brute":
            self._tree = None
        else:
            raise ValueError("backend must be vptree|kdtree|brute")

    def _query_one(self, point, k):
        if self._tree is not None:
            idx, dist = self._tree.knn(np.asarray(point, np.float32), k=k)
            return [{"index": int(i), "distance": float(d)}
                    for i, d in zip(idx, dist)]
        return self._query_batch([point], k)[0]

    def _query_batch(self, vectors, k):
        idx, dist = self._brute(np.asarray(vectors, np.float32), k)
        idx, dist = np.asarray(idx), np.asarray(dist)
        return [[{"index": int(i), "distance": float(d)}
                 for i, d in zip(row_i, row_d)]
                for row_i, row_d in zip(idx, dist)]

    def start(self) -> "KNNServer":
        self._httpd, self._thread = serve_json(
            self._host, self._port,
            post_routes={
                "/knn": lambda b: {"results": self._query_one(
                    b["point"], int(b.get("k", 1)))},
                "/knnvec": lambda b: {"results": self._query_batch(
                    b["vectors"], int(b.get("k", 1)))},
            },
            get_routes={"/health": lambda _: {"status": "ok",
                                              "points": len(self.points)}})
        return self

    def stop(self):
        self._stop_httpd()
