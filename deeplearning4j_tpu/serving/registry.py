"""Model registry: named, versioned models with hot load/unload/reload and
weighted traffic splitting.

Reference analog: the reference's model-server tier keeps one model per
process; a production gateway multiplexes — each (name, version) gets its
own ParallelInference worker (bounded queue, pad-to-bucket batching) and is
warmed at its batch-shape buckets before it takes traffic. Traffic within a
name is split by per-version weights (the canary pattern: 90/10 between
stable and candidate), and a reload builds + warms the replacement fully
off the request path before an atomic swap, then drains the old worker so
already-admitted requests still complete — zero-drop hot swap.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving.warmup import pow2_buckets, warmup_model


class ModelVersion:
    """One servable (name, version): the model, its batching worker, and
    its warmed bucket set."""

    def __init__(self, name: str, version: str, model,
                 pi: ParallelInference, buckets: Tuple[int, ...],
                 warmup_timings: Optional[Dict[int, float]] = None):
        self.name = name
        self.version = version
        self.model = model
        self.pi = pi
        self.buckets = buckets
        self.warmup_timings = dict(warmup_timings or {})
        self.loaded_at = time.time()

    def describe(self) -> dict:
        return {"name": self.name, "version": self.version,
                "buckets": list(self.buckets),
                "warmed": sorted(self.warmup_timings),
                "backlog": self.pi.backlog(),
                "healthy": self.pi.healthy(),
                "worker_restarts": self.pi.restarts,
                "quantized": bool(getattr(self.model, "_quantized", False)),
                "loaded_at": self.loaded_at}


class ModelRegistry:
    """Thread-safe name -> {version -> ModelVersion} map with per-name
    traffic splits. ``seed`` pins the weighted-routing RNG (tests)."""

    def __init__(self, batch_limit: int = 32, max_queue: int = 128,
                 queue_timeout_s: float = 0.005,
                 seed: Optional[int] = None):
        self.batch_limit = batch_limit
        self.max_queue = max_queue
        self.queue_timeout_s = queue_timeout_s
        self._lock = threading.RLock()
        self._models: Dict[str, Dict[str, ModelVersion]] = {}
        self._splits: Dict[str, Dict[str, float]] = {}
        self._rng = random.Random(seed)

    # ------------------------------------------------------------- loading
    def _build(self, name: str, version: str, model, warmup_shape,
               warmup: bool, batch_limit: Optional[int],
               max_queue: Optional[int]) -> ModelVersion:
        """Construct + warm a ModelVersion WITHOUT touching the routing
        table — all compile cost happens off the request path."""
        limit = batch_limit or self.batch_limit
        mon = monitoring.serving_monitor()

        def on_shed(n, klass=None):
            m = monitoring.serving_monitor()
            if m is not None:
                m.shed_total.labels(model=name, reason="deadline",
                                    **{"class": klass or "default"}).inc(n)

        def on_depth(backlog):
            # fires on EVERY dequeue path — normal dispatch and deadline
            # sheds alike — so the per-model queue-depth gauge decays when
            # expired requests are dropped instead of freezing at its last
            # submit-time value (the gauge-leak fix)
            m = monitoring.serving_monitor()
            if m is not None:
                m.model_queue_depth.labels(model=name,
                                           version=version).set(backlog)

        pi = ParallelInference(
            model, batch_limit=limit, queue_timeout_s=self.queue_timeout_s,
            max_queue=self.max_queue if max_queue is None else max_queue,
            on_shed=on_shed, on_depth=on_depth,
            name=f"pi-{name}-{version}").start()
        buckets = pow2_buckets(limit)
        timings: Dict[int, float] = {}
        if warmup and warmup_shape is not None:
            timings = warmup_model(model, warmup_shape, buckets,
                                   labels=(name, version))
        if mon is not None:
            mon.model_loaded.labels(model=name, version=version).set(1)
            mon.replicas.labels(model=name, version=version).set(
                pi.replicas())
        return ModelVersion(name, version, model, pi, buckets, timings)

    def load(self, name: str, version: str, model, *,
             weight: Optional[float] = None,
             warmup_shape: Optional[Sequence[int]] = None,
             warmup: bool = True, batch_limit: Optional[int] = None,
             max_queue: Optional[int] = None) -> ModelVersion:
        """Register (or hot-reload) a version. New names/versions default to
        weight 1.0 when first for the name, else 0.0 (explicit canary
        opt-in via ``weight`` or ``set_split``). Re-loading an existing
        (name, version) is a hot swap: the replacement is warmed first,
        swapped atomically, and the old worker drained."""
        mv = self._build(name, version, model, warmup_shape, warmup,
                         batch_limit, max_queue)
        with self._lock:
            versions = self._models.setdefault(name, {})
            old = versions.get(version)
            versions[version] = mv
            split = self._splits.setdefault(name, {})
            if weight is not None:
                split[version] = float(weight)
            elif version not in split:
                split[version] = 1.0 if len(versions) == 1 else 0.0
        if old is not None:
            old.pi.drain()
        return mv

    def reload(self, name: str, version: str, model, **kw) -> ModelVersion:
        """Alias of :meth:`load` for an existing (name, version): build +
        warm the replacement off-path, atomic swap, drain the old worker —
        in-flight requests against the old instance still complete."""
        return self.load(name, version, model, **kw)

    def unload(self, name: str, version: Optional[str] = None,
               drain: bool = True) -> List[ModelVersion]:
        """Remove one version (or every version of a name). Removed workers
        are drained by default: already-admitted requests complete."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(f"model {name!r} is not registered")
            if version is None:
                removed = list(versions.values())
                del self._models[name]
                self._splits.pop(name, None)
            else:
                if version not in versions:
                    raise KeyError(f"model {name!r} has no version "
                                   f"{version!r}")
                removed = [versions.pop(version)]
                self._splits.get(name, {}).pop(version, None)
                if not versions:
                    del self._models[name]
                    self._splits.pop(name, None)
        mon = monitoring.serving_monitor()
        for mv in removed:
            if mon is not None:
                mon.model_loaded.labels(model=mv.name,
                                        version=mv.version).set(0)
            if drain:
                mv.pi.drain()
            else:
                mv.pi.stop()
        return removed

    # ------------------------------------------------------------- routing
    def set_split(self, name: str, weights: Dict[str, float]) -> Dict[str, float]:
        """Replace the name's traffic split; weights need not sum to 1
        (normalized at routing time) but must be >= 0, and every keyed
        version must exist."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(f"model {name!r} is not registered")
            unknown = set(weights) - set(versions)
            if unknown:
                raise KeyError(f"model {name!r} has no version(s) "
                               f"{sorted(unknown)}")
            if any(w < 0 for w in weights.values()):
                raise ValueError("split weights must be >= 0")
            if not any(w > 0 for w in weights.values()):
                raise ValueError("at least one split weight must be > 0")
            self._splits[name] = {v: float(w) for v, w in weights.items()}
            return dict(self._splits[name])

    def route(self, name: str, exclude=()) -> ModelVersion:
        """Pick a version by weighted random choice over the name's split.
        ``exclude`` (circuit-broken replicas, already-failed attempts)
        filters the candidates; when it would empty the set it is ignored
        — routing somewhere honest beats fabricating a 404."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise KeyError(f"model {name!r} is not registered")
            split = self._splits.get(name, {})
            weighted = [(versions[v], w) for v, w in split.items()
                        if w > 0 and v in versions]
            if not weighted:
                weighted = [(mv, 1.0) for mv in versions.values()]
            if exclude:
                kept = [(mv, w) for mv, w in weighted
                        if mv.version not in exclude]
                if kept:
                    weighted = kept
            total = sum(w for _, w in weighted)
            r = self._rng.random() * total
            for mv, w in weighted:
                r -= w
                if r <= 0:
                    return mv
            return weighted[-1][0]

    def get(self, name: str, version: str) -> Optional[ModelVersion]:
        with self._lock:
            return self._models.get(name, {}).get(version)

    def versions(self, name: str) -> List[str]:
        """Registered version ids for a name (empty when unknown)."""
        with self._lock:
            return sorted(self._models.get(name, {}))

    # -------------------------------------------------------------- status
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def ready(self) -> bool:
        """At least one servable version registered."""
        with self._lock:
            return any(self._models.values())

    def health(self) -> dict:
        """Per-(name, version) worker health: ``healthy`` is False only in
        the window between a worker-thread death and its revival;
        ``worker_restarts`` counts every self-healing event so far."""
        with self._lock:
            all_versions = [mv for versions in self._models.values()
                            for mv in versions.values()]
        return {
            f"{mv.name}/{mv.version}": {
                "healthy": mv.pi.healthy(),
                "worker_restarts": mv.pi.restarts,
                "backlog": mv.pi.backlog(),
            }
            for mv in all_versions
        }

    def describe(self) -> dict:
        with self._lock:
            return {
                name: {
                    "versions": {v: mv.describe()
                                 for v, mv in versions.items()},
                    "split": dict(self._splits.get(name, {})),
                }
                for name, versions in self._models.items()
            }

    def shutdown(self, drain: bool = True):
        """Drain (or hard-stop) every registered worker."""
        with self._lock:
            all_versions = [mv for versions in self._models.values()
                            for mv in versions.values()]
            self._models.clear()
            self._splits.clear()
        for mv in all_versions:
            if drain:
                mv.pi.drain()
            else:
                mv.pi.stop()
