"""Admission control: bounded queues, deadlines, and load shedding.

The failure mode this kills: an overloaded single-queue server accepts every
request, the queue grows without bound, every response is late, and nothing
in /metrics says why. Here admission is explicit — each model's worker queue
is bounded, a request that can't be admitted is REJECTED NOW (HTTP 429 with
``Retry-After``) instead of piling up, every admitted request carries a
deadline (expired ones are shed at dispatch and answered 504), and every
shed increments a per-model, per-reason counter so overload is visible the
moment it starts.
"""

from __future__ import annotations

import math
import queue
import time
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.parallel.inference import DeadlineExceeded
from deeplearning4j_tpu.serving.http import HttpError
from deeplearning4j_tpu.serving.registry import ModelVersion


class AdmissionController:
    """Per-request admission policy for the gateway.

    default_timeout_s / max_timeout_s: request deadline bounds (requests may
    pass ``timeout_ms`` in the body, clamped to the max);
    retry_after_s: the backpressure hint on 429 responses.
    """

    def __init__(self, default_timeout_s: float = 30.0,
                 max_timeout_s: float = 300.0,
                 retry_after_s: float = 1.0):
        self.default_timeout_s = default_timeout_s
        self.max_timeout_s = max_timeout_s
        self.retry_after_s = retry_after_s

    # ------------------------------------------------------------ deadline
    def timeout_for(self, body: dict) -> float:
        """The request's timeout budget in seconds (body ``timeout_ms``
        overrides the default, clamped to [1 ms, max])."""
        ms = body.get("timeout_ms")
        if ms is None:
            return self.default_timeout_s
        return min(max(float(ms) / 1000.0, 0.001), self.max_timeout_s)

    def _shed(self, model: str, reason: str, n: int = 1):
        mon = monitoring.serving_monitor()
        if mon is not None:
            mon.shed_total.labels(model=model, reason=reason).inc(n)

    def _retry_headers(self) -> dict:
        return {"Retry-After": str(max(1, math.ceil(self.retry_after_s)))}

    # -------------------------------------------------------------- submit
    def submit(self, mv: ModelVersion, xs: np.ndarray,
               deadline: float) -> List["queue.Queue"]:
        """Admit every row of ``xs`` to ``mv``'s worker, or reject with a
        429 (queue full) / 503 (worker draining). Capacity for the WHOLE
        request is checked up front so a rejected multi-row request does
        not half-admit; rows that slip through the precheck race keep
        their deadline, so the worker eventually sheds them rather than
        holding them forever."""
        cap = mv.pi.max_queue
        if cap and mv.pi.backlog() + len(xs) > cap:
            self._shed(mv.name, "queue_full")
            raise HttpError(
                429, f"model {mv.name!r} queue is full ({cap} pending); "
                "retry later", headers=self._retry_headers())
        queues = []
        for x in xs:
            try:
                queues.append(mv.pi.submit(x, deadline=deadline))
            except queue.Full:
                self._shed(mv.name, "queue_full")
                raise HttpError(
                    429, f"model {mv.name!r} queue is full "
                    f"({mv.pi.max_queue} pending); retry later",
                    headers=self._retry_headers()) from None
            except RuntimeError:
                # worker draining (hot reload / shutdown race)
                self._shed(mv.name, "draining")
                raise HttpError(
                    503, f"model {mv.name!r} version {mv.version!r} is "
                    "draining; retry", headers=self._retry_headers()) from None
        mon = monitoring.serving_monitor()
        if mon is not None:
            mon.model_queue_depth.labels(
                model=mv.name, version=mv.version).set(mv.pi.backlog())
        return queues

    # -------------------------------------------------------------- gather
    def gather(self, mv: ModelVersion, queues: List["queue.Queue"],
               deadline: float) -> List[np.ndarray]:
        """Collect every result before the deadline; a timeout or a
        deadline-shed result is a 504 (the remaining siblings carry the
        same deadline — the worker cancels them, nothing is orphaned)."""
        outs = []
        for q in queues:
            remaining = deadline - time.monotonic()
            try:
                r = q.get(timeout=max(remaining, 0.001))
            except queue.Empty:
                self._shed(mv.name, "deadline")
                raise HttpError(
                    504, f"model {mv.name!r} deadline exceeded "
                    "waiting for result") from None
            if isinstance(r, DeadlineExceeded):
                # worker-side shed already counted via on_shed
                raise HttpError(
                    504, f"model {mv.name!r} deadline exceeded "
                    "before dispatch") from None
            if isinstance(r, BaseException):
                raise HttpError(500, f"model {mv.name!r} forward pass "
                                f"failed: {r}") from None
            outs.append(np.asarray(r))
        return outs
