"""Admission control: bounded queues, deadlines, and load shedding.

The failure mode this kills: an overloaded single-queue server accepts every
request, the queue grows without bound, every response is late, and nothing
in /metrics says why. Here admission is explicit — each model's worker queue
is bounded, a request that can't be admitted is REJECTED NOW (HTTP 429 with
``Retry-After``) instead of piling up, every admitted request carries a
deadline (expired ones are shed at dispatch and answered 504), and every
shed increments a per-model, per-reason, per-priority-class counter so
overload is visible — and attributable — the moment it starts.

``Retry-After`` is drain-aware: the controller keeps an EWMA of observed
per-request service time, and a 429's hint is ``EWMA × queue position``
clamped to [1, 30]s — a client behind a deep queue on a slow model backs
off longer than one behind a shallow queue on a fast one, instead of every
rejected client hammering back after the same constant second.

Priority classes ride through ``submit(..., klass=...)`` into the worker's
two-lane queue: ``batch`` requests wait in the low-priority lane that only
drains when no interactive/default work is queued.
"""

from __future__ import annotations

import math
import queue
import threading
import time
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight
from deeplearning4j_tpu.parallel.inference import DeadlineExceeded
from deeplearning4j_tpu.serving.http import HttpError
from deeplearning4j_tpu.serving.registry import ModelVersion


class AdmissionController:
    """Per-request admission policy for the gateway.

    default_timeout_s / max_timeout_s: request deadline bounds (requests may
    pass ``timeout_ms`` in the body, clamped to the max);
    retry_after_s: the backpressure hint on 429 responses before any
    service-time observations exist (the EWMA takes over after warmup).
    """

    #: EWMA smoothing for observed per-request service time
    EWMA_ALPHA = 0.2

    def __init__(self, default_timeout_s: float = 30.0,
                 max_timeout_s: float = 300.0,
                 retry_after_s: float = 1.0):
        self.default_timeout_s = default_timeout_s
        self.max_timeout_s = max_timeout_s
        self.retry_after_s = retry_after_s
        self._ewma_service_s: Optional[float] = None
        self._ewma_lock = threading.Lock()

    # ------------------------------------------------------------ deadline
    def timeout_for(self, body: dict) -> float:
        """The request's timeout budget in seconds (body ``timeout_ms``
        overrides the default, clamped to [1 ms, max])."""
        ms = body.get("timeout_ms")
        if ms is None:
            return self.default_timeout_s
        return min(max(float(ms) / 1000.0, 0.001), self.max_timeout_s)

    def _shed(self, model: str, reason: str, n: int = 1,
              klass: Optional[str] = None, trace=None):
        mon = monitoring.serving_monitor()
        if mon is not None:
            mon.shed_total.labels(model=model, reason=reason,
                                  **{"class": klass or "default"}).inc(n)
        rec = flight.recorder()
        if rec is not None:
            # SLO-driven sheds are a trigger kind: the recorder dumps a
            # postmortem bundle carrying this request's trace
            rec.record("slo_shed" if reason == "slo" else "shed",
                       severity="warn", model=model, reason=reason,
                       klass=klass or "default", n=n, trace=trace)
        if trace is not None:
            trace.event("shed", reason=reason, model=model)

    # ---------------------------------------------------------- backoff hint
    def observe_service(self, seconds_per_request: float) -> None:
        """Feed one observed per-request service time into the EWMA the
        Retry-After hint is computed from."""
        with self._ewma_lock:
            if self._ewma_service_s is None:
                self._ewma_service_s = seconds_per_request
            else:
                self._ewma_service_s += self.EWMA_ALPHA * (
                    seconds_per_request - self._ewma_service_s)

    def retry_after_for(self, position: Optional[int] = None) -> int:
        """Seconds a rejected client should back off: EWMA service time ×
        its queue position, clamped to [1, 30]. Falls back to the
        configured constant before any service time has been observed."""
        with self._ewma_lock:
            ewma = self._ewma_service_s
        if position is None or ewma is None:
            return max(1, math.ceil(self.retry_after_s))
        return min(max(math.ceil(ewma * max(position, 1)), 1), 30)

    def _retry_headers(self, position: Optional[int] = None) -> dict:
        return {"Retry-After": str(self.retry_after_for(position))}

    # -------------------------------------------------------------- submit
    def submit(self, mv: ModelVersion, xs: np.ndarray, deadline: float,
               klass: Optional[str] = None, trace=None) -> List["queue.Queue"]:
        """Admit every row of ``xs`` to ``mv``'s worker, or reject with a
        429 (queue full) / 503 (worker draining). Capacity for the WHOLE
        request is checked up front so a rejected multi-row request does
        not half-admit; rows that slip through the precheck race keep
        their deadline, so the worker eventually sheds them rather than
        holding them forever. ``klass`` routes ``batch`` to the worker's
        low-priority lane; ``trace`` rides into the lane so the worker
        records this request's queue-wait and dispatch spans."""
        cap = mv.pi.max_queue
        if cap and mv.pi.lane_backlog(klass) + len(xs) > cap:
            # per-LANE capacity: a saturated batch lane must not starve
            # interactive admission
            self._shed(mv.name, "queue_full", klass=klass, trace=trace)
            raise HttpError(
                429, f"model {mv.name!r} queue is full ({cap} pending); "
                "retry later",
                headers=self._retry_headers(mv.pi.backlog()))
        queues = []
        for x in xs:
            try:
                queues.append(mv.pi.submit(x, deadline=deadline, klass=klass,
                                           trace=trace))
            except queue.Full:
                self._shed(mv.name, "queue_full", klass=klass, trace=trace)
                raise HttpError(
                    429, f"model {mv.name!r} queue is full "
                    f"({mv.pi.max_queue} pending); retry later",
                    headers=self._retry_headers(mv.pi.backlog())) from None
            except RuntimeError:
                # worker draining (hot reload / shutdown race)
                self._shed(mv.name, "draining", klass=klass, trace=trace)
                raise HttpError(
                    503, f"model {mv.name!r} version {mv.version!r} is "
                    "draining; retry", headers=self._retry_headers()) from None
        mon = monitoring.serving_monitor()
        if mon is not None:
            mon.model_queue_depth.labels(
                model=mv.name, version=mv.version).set(mv.pi.backlog())
        return queues

    # -------------------------------------------------------------- gather
    def gather(self, mv: ModelVersion, queues: List["queue.Queue"],
               deadline: float, klass: Optional[str] = None, trace=None
               ) -> List[np.ndarray]:
        """Collect every result before the deadline; a timeout or a
        deadline-shed result is a 504 (the remaining siblings carry the
        same deadline — the worker cancels them, nothing is orphaned).
        Completed gathers feed the service-time EWMA behind Retry-After."""
        outs = []
        t0 = time.monotonic()
        for q in queues:
            remaining = deadline - time.monotonic()
            try:
                r = q.get(timeout=max(remaining, 0.001))
            except queue.Empty:
                self._shed(mv.name, "deadline", klass=klass, trace=trace)
                raise HttpError(
                    504, f"model {mv.name!r} deadline exceeded "
                    "waiting for result") from None
            if isinstance(r, DeadlineExceeded):
                # worker-side shed already counted via on_shed
                raise HttpError(
                    504, f"model {mv.name!r} deadline exceeded "
                    "before dispatch") from None
            if isinstance(r, BaseException):
                raise HttpError(500, f"model {mv.name!r} forward pass "
                                f"failed: {r}") from None
            outs.append(np.asarray(r))
        self.observe_service((time.monotonic() - t0) / max(len(queues), 1))
        return outs
