"""The production serving gateway: registry + admission + warmup + lifecycle.

One HTTP server multiplexing many named, versioned models:

    POST /v1/<name>/predict   {"inputs": [[...]], "timeout_ms": 250}
    POST /v1/<name>/generate  {"prompt"|"prompt_ids", sampling knobs,
                               "stream": true} — ndjson token streaming
                              from a continuous-batching GenerationEngine
                              (serving/generate.py)
    POST /models/load         {"name", "version", "path", "weight",
                               "warmup_shape", "batch_limit"}
    POST /models/reload       (same body — hot swap, zero dropped requests)
    POST /models/unload       {"name", "version"?}
    POST /models/split        {"name", "split": {"v1": 0.9, "v2": 0.1}}
    GET  /models              registry + splits + backlogs
    GET  /healthz             process liveness (200 once the server is up;
                              body reports "degraded" + the affected
                              model workers when any inference worker
                              died/was self-heal restarted)
    GET  /readyz              traffic readiness (503 until a model is
                              loaded, and again once draining)
    GET  /slo                 per-class SLO status: objective, burn rate,
                              and whether the class is currently shedding
                              ({"enabled": false} without SLO config)
    GET  /metrics             Prometheus exposition (process-wide registry;
                              ``?exemplars=1`` upgrades to OpenMetrics with
                              trace-id exemplars on latency buckets)
    GET  /debug/requests      request-tracer table: in-flight + recently
                              completed traces with per-stage timing
                              ({"enabled": false} without ``trace=``)
    GET  /debug/trace/<id>    ONE request as Chrome trace-event JSON
                              (load in Perfetto / chrome://tracing)
    GET  /debug/flight        flight-recorder tail: recent structured
                              incidents and where bundles were dumped

Admission outcomes a client sees: 200 (served), 429 + ``Retry-After``
(queue full, over quota, or shed for a burning higher class — back off),
503 (no servable model, or draining), 504 (deadline exceeded), 500 (model
forward failed), 404 (unknown model), 401 (multi-tenant mode, bad/missing
API key).

Multi-tenant mode (all opt-in; see docs/slo.md):

- ``tenants=[Tenant(...)]`` — API-key auth, priority classes
  (``interactive`` > ``default`` > ``batch``; batch rides the workers'
  low-priority lane), sliding-window request/token quotas (429 with a
  drain-aware ``Retry-After``);
- ``slo={"interactive": {"objective_ms": 250, "target": 0.95}, ...}`` —
  per-class latency objectives with shed-lowest-class-first overload
  behavior and the ``GET /slo`` burn-rate surface;
- ``autoscale={"max_replicas": 4, ...}`` — backlog-driven replica
  autoscaling of every model's worker pool, started/stopped with the
  gateway lifecycle.

None of the three configured = none of the machinery built: the request
path does zero tenancy/SLO/priority bookkeeping (spy-guarded contract).

Lifecycle: ``stop()`` is a graceful drain — stop admitting (``/readyz``
goes 503 so balancers eject the instance), wait for in-flight requests,
flush every model's worker queue, then join. Nothing admitted is dropped.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.common.env import Environment, _flag
from deeplearning4j_tpu.monitoring import context, flight
from deeplearning4j_tpu.serving.admission import AdmissionController
from deeplearning4j_tpu.serving.generate import handle_generate, match_generate
from deeplearning4j_tpu.serving.http import (HttpError, StreamingResponse,
                                             _HttpServerMixin, serve_json)
from deeplearning4j_tpu.serving.registry import ModelRegistry


def _match_predict(path: str):
    """/v1/<name>/predict -> {"name": name} (None = no match)."""
    parts = path.strip("/").split("/")
    if len(parts) == 3 and parts[0] == "v1" and parts[2] == "predict":
        return {"name": parts[1]}
    return None


def _match_debug_trace(path: str):
    """/debug/trace/<id> -> {"trace_id": id} (None = no match)."""
    parts = path.strip("/").split("/")
    if (len(parts) == 3 and parts[0] == "debug" and parts[1] == "trace"
            and parts[2]):
        return {"trace_id": parts[2]}
    return None


def _sp(trace, name: str, **args):
    """``trace.span(name)`` or a no-op — the tracing None-gate inline, so
    traced and untraced requests share one code path."""
    if trace is None:
        return contextlib.nullcontext()
    return trace.span(name, **args)


class ServingGateway(_HttpServerMixin):
    """Multi-model serving gateway.

        gw = ServingGateway(port=0).start()
        gw.register_model("mnist", "v1", model, warmup_shape=(28, 28, 1))
        ... POST http://host:port/v1/mnist/predict {"inputs": [...]}
        gw.stop()          # graceful drain

    ``admin=False`` disables the mutating /models/* routes (predict-only
    data plane); the Python API (register_model/unload_model/set_split)
    always works.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 batch_limit: int = 32, max_queue: int = 128,
                 queue_timeout_s: float = 0.005,
                 default_timeout_s: float = 30.0,
                 retry_after_s: float = 1.0,
                 seed: Optional[int] = None, admin: bool = True,
                 generate_max_queue: int = 64,
                 tenants=None, slo=None, autoscale=None,
                 trace: Optional[bool] = None, failover=None):
        self._host, self._port = host, port
        self.admin = admin
        self.registry = ModelRegistry(
            batch_limit=batch_limit, max_queue=max_queue,
            queue_timeout_s=queue_timeout_s, seed=seed)
        self.admission = AdmissionController(
            default_timeout_s=default_timeout_s,
            retry_after_s=retry_after_s)
        self.generate_max_queue = generate_max_queue
        # multi-tenant tier: all three stay None unless configured, and
        # every request-path touch point is a single None check — the
        # zero-overhead contract
        self.tenancy = None
        if tenants is not None:
            from deeplearning4j_tpu.serving.tenancy import TenantTable

            self.tenancy = (tenants if isinstance(tenants, TenantTable)
                            else TenantTable(tenants))
        self.slo = None
        if slo is not None:
            from deeplearning4j_tpu.serving.slo import SloTracker

            self.slo = slo if isinstance(slo, SloTracker) else SloTracker(slo)
        self.autoscaler = None
        if autoscale is not None:
            from deeplearning4j_tpu.serving.autoscale import ReplicaAutoscaler

            self.autoscaler = (autoscale
                               if isinstance(autoscale, ReplicaAutoscaler)
                               else ReplicaAutoscaler(self.registry,
                                                      **autoscale))
        # request tracing follows the same opt-in pattern: built only for
        # trace=True (or DL4J_TPU_TRACING in the environment, read live so
        # tests can monkeypatch it); otherwise ``tracer is None`` and the
        # request path performs zero tracer calls
        self.tracer = None
        if trace or (trace is None and _flag(Environment.TRACING)):
            self.tracer = monitoring.RequestTracer()
        # failover tier (opt-in, same contract): per-replica circuit
        # breakers + idempotency-keyed cross-replica retry of non-streaming
        # predicts. None = the predict path does zero breaker/cache work.
        self.failover = None
        if failover is not None:
            from deeplearning4j_tpu.serving.failover import GatewayFailover

            self.failover = (failover
                             if isinstance(failover, GatewayFailover)
                             else GatewayFailover(**failover))
        self._generators: dict = {}
        # per-generator session journals (crash-recoverable generation);
        # empty dict on an unconfigured gateway — the generate path checks
        # truthiness once and performs zero journal calls
        self._sessions: dict = {}
        self._draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Condition(self._inflight_lock)

    # ------------------------------------------------------- python API
    def register_model(self, name: str, version: str, model, *,
                       weight: Optional[float] = None,
                       warmup_shape: Optional[Sequence[int]] = None,
                       warmup: bool = True,
                       batch_limit: Optional[int] = None,
                       max_queue: Optional[int] = None):
        """Load (or hot-reload) a servable version; warmed before it takes
        traffic. See :meth:`ModelRegistry.load`."""
        return self.registry.load(
            name, version, model, weight=weight, warmup_shape=warmup_shape,
            warmup=warmup, batch_limit=batch_limit, max_queue=max_queue)

    def unload_model(self, name: str, version: Optional[str] = None):
        return self.registry.unload(name, version)

    def set_split(self, name: str, weights):
        return self.registry.set_split(name, weights)

    def register_generator(self, name: str, engine, *, sessions=None,
                           resume: bool = True):
        """Attach a started :class:`GenerationEngine` under
        ``POST /v1/<name>/generate`` (streaming). The engine's background
        step loop is started here if it isn't running yet.

        ``sessions`` (a journal path or a
        :class:`~deeplearning4j_tpu.generation.sessions.SessionJournal`)
        arms crash-recoverable sessions: requests carrying an
        ``X-Request-Id`` become durable, clients reconnect with
        ``last_seq``, and — with ``resume=True`` — sessions interrupted by
        a previous process's preemption are re-submitted into this engine
        BEFORE it takes new traffic (register, then ``start()`` the
        gateway)."""
        if sessions is not None:
            from deeplearning4j_tpu.generation.sessions import SessionJournal

            journal = (sessions if isinstance(sessions, SessionJournal)
                       else SessionJournal(sessions))
            engine.attach_journal(journal)
            self._sessions[name] = journal
        self._generators[name] = engine.start()
        if sessions is not None and resume:
            self._sessions[name].resume_into(engine)
        return engine

    def unregister_generator(self, name: str, *, timeout: float = 10.0):
        eng = self._generators.pop(name)
        eng.shutdown(timeout=timeout)
        return eng

    # --------------------------------------------------------- handlers
    def _track(self, delta: int):
        with self._inflight_lock:
            self._inflight += delta
            if self._inflight == 0:
                self._idle.notify_all()

    def _admit_tenant(self, name: str, body: dict, headers, cost: int,
                      trace=None):
        """The multi-tenant admission prelude shared by predict and
        generate: authorize the API key, shed if a higher-priority class
        is burning its SLO budget, then charge the quota. Returns the
        tenant's priority class (None when tenancy is off — the
        zero-overhead path does none of this)."""
        tenant = klass = None
        if self.tenancy is not None:
            tenant = self.tenancy.authorize(body, headers)
            klass = tenant.klass
        if self.slo is not None and self.slo.should_shed(klass):
            self.admission._shed(name, "slo", klass=klass, trace=trace)
            raise HttpError(
                429, f"shedding {klass or 'default'} traffic: a higher-"
                "priority class is over its latency objective",
                headers=self.admission._retry_headers())
        if tenant is not None:
            try:
                self.tenancy.admit(tenant, tokens=cost)
            except HttpError:
                self.admission._shed(name, "quota", klass=klass, trace=trace)
                raise
        return klass

    def _begin_trace(self, route: str, params, model: str):
        """Mint a trace (tracer configured) and flight-record the admit
        (recorder armed); both are None-gated no-ops otherwise."""
        trace = None
        if self.tracer is not None:
            trace = self.tracer.begin(route, headers=params.get("_headers"),
                                      model=model)
        rec = flight.recorder()
        if rec is not None:
            rec.record("admit", route=route, model=model, trace=trace)
        return trace

    def _finish_trace(self, trace, exc: Optional[BaseException]) -> None:
        """Close a trace with the request's disposition: backpressure codes
        are ``shed`` (the reason says why), everything else that raised is
        ``error``, a clean return is ``served``."""
        if trace is None:
            return
        if exc is None:
            self.tracer.finish(trace, "served", code=200)
        elif isinstance(exc, HttpError):
            disp = "shed" if exc.code in (429, 503, 504) else "error"
            self.tracer.finish(trace, disp, code=exc.code,
                               reason=exc.message)
        else:
            self.tracer.finish(trace, "error", code=400, reason=str(exc))

    def _predict(self, params, body):
        if self._draining:
            raise HttpError(503, "gateway is draining",
                            headers=self.admission._retry_headers())
        name = params["name"]
        trace = self._begin_trace("/v1/*/predict", params, name)
        self._track(+1)
        try:
            with context.bind(trace):
                payload = self._predict_inner(name, body,
                                              params.get("_headers"),
                                              trace=trace)
            self._finish_trace(trace, None)
            return payload
        except BaseException as e:
            self._finish_trace(trace, e)
            raise
        finally:
            self._track(-1)

    def _generate(self, params, body):
        if self._draining:
            raise HttpError(503, "gateway is draining",
                            headers=self.admission._retry_headers())
        name = params["name"]
        engine = self._generators.get(name)
        if engine is None:
            raise HttpError(404, f"generator {name!r} is not registered")
        trace = self._begin_trace("/v1/*/generate", params, name)
        try:
            with context.bind(trace):
                with _sp(trace, "quota_check"):
                    klass = self._admit_tenant(
                        name, body, params.get("_headers"),
                        cost=int(body.get("max_new_tokens", 64)),
                        trace=trace)
                payload = handle_generate(self, engine, name, body,
                                          klass=klass, trace=trace,
                                          headers=params.get("_headers"))
        except BaseException as e:
            self._finish_trace(trace, e)
            raise
        if not isinstance(payload, StreamingResponse):
            # streams finish their trace in on_finish, at last-token time
            self._finish_trace(trace, None)
        return payload

    def _predict_inner(self, name: str, body: dict, headers=None,
                       trace=None):
        fo = self.failover
        if fo is None:
            return self._predict_attempt(name, body, headers, trace)
        from deeplearning4j_tpu.serving.failover import ReplicaFailed

        idem = fo.idempotency_key(body, headers)
        if idem is not None:
            cached = fo.idempotency.get(idem)
            if cached is not None:
                # exactly-once from the client's view: replay the stored
                # response instead of re-running the forward
                if trace is not None:
                    trace.event("idempotent_replay")
                return cached
        failed: set = set()

        def attempt():
            payload = self._predict_attempt(
                name, body, headers, trace,
                exclude=fo.excluded(name) | failed, failover=fo,
                failed=failed)
            if idem is not None:
                fo.idempotency.put(idem, payload)
            return payload

        try:
            # the shared RetryPolicy owns backoff + attempt accounting:
            # dl4j_retry_attempts_total{component="gateway"} and
            # dl4j_recovery_total{component="gateway",outcome="retried_ok"}
            return fo.retry_policy.call(attempt, component="gateway")
        except ReplicaFailed as e:
            raise e.error

    def _predict_attempt(self, name: str, body: dict, headers=None,
                         trace=None, exclude=(), failover=None,
                         failed=None):
        try:
            mv = self.registry.route(name, exclude=exclude)
        except KeyError:
            raise HttpError(404, f"model {name!r} is not registered") from None
        xs = np.asarray(body["inputs"], np.float32)
        if xs.ndim < 1 or xs.shape[0] == 0:
            raise HttpError(400, "inputs must be a non-empty batch")
        with _sp(trace, "quota_check"):
            klass = self._admit_tenant(name, body, headers, cost=len(xs),
                                       trace=trace)
        timeout = self.admission.timeout_for(body)
        deadline = time.monotonic() + timeout
        t0 = time.perf_counter()
        code = 200
        try:
            with _sp(trace, "submit", rows=len(xs)):
                try:
                    queues = self.admission.submit(mv, xs, deadline,
                                                   klass=klass, trace=trace)
                except HttpError as e:
                    if e.code != 503:
                        raise
                    # the routed version started draining under us (hot
                    # reload / unload race): re-route once — the registry
                    # swap is atomic, so the retry sees the replacement.
                    # This is what makes hot reload zero-drop.
                    mv = self.registry.route(name, exclude=exclude)
                    queues = self.admission.submit(mv, xs, deadline,
                                                   klass=klass, trace=trace)
            with _sp(trace, "gather"):
                outs = self.admission.gather(mv, queues, deadline,
                                             klass=klass, trace=trace)
            if failover is not None:
                failover.record(name, mv.version, ok=True, trace=trace)
            with _sp(trace, "serialize"):
                return {"outputs": [y.tolist() for y in outs],
                        "model": mv.name, "version": mv.version}
        except HttpError as e:
            code = e.code
            if e.code == 500 and failover is not None:
                # the replica's forward failed: feed its breaker, and if a
                # healthy sibling exists hand the request to it via the
                # retry policy (ReplicaFailed is the retryable wrapper)
                failover.record(name, mv.version, ok=False, trace=trace)
                if failed is not None:
                    failed.add(mv.version)
                siblings = [v for v in self.registry.versions(name)
                            if failed is None or v not in failed]
                if siblings:
                    from deeplearning4j_tpu.serving.failover import (
                        ReplicaFailed)

                    if trace is not None:
                        trace.event("failover", model=name,
                                    version=mv.version)
                    raise ReplicaFailed(e) from e
            raise
        except Exception:
            code = 400
            raise
        finally:
            elapsed = time.perf_counter() - t0
            mon = monitoring.serving_monitor()
            if mon is not None:
                mon.model_request_seconds.labels(
                    model=name, version=mv.version, code=code).observe(
                    elapsed,
                    exemplar=({"trace_id": trace.trace_id}
                              if trace is not None else None))
            if self.slo is not None and code != 429:
                # sheds don't spend latency budget; served outcomes —
                # including 504s, which ARE objective misses — do
                self.slo.observe(klass, elapsed)

    # ----------------------------------------------------- admin routes
    def _require(self, body: dict, *keys):
        missing = [k for k in keys if not body.get(k)]
        if missing:
            raise HttpError(400, f"missing field(s): {', '.join(missing)}")

    def _load_route(self, body: dict):
        self._require(body, "name", "version", "path")
        from deeplearning4j_tpu.util.serialization import restore_model

        model = restore_model(body["path"], load_updater=False)
        q = body.get("quantize")
        if q is not None:
            if q != "int8":
                raise HttpError(400, f"unsupported quantize dtype {q!r} "
                                     "(only 'int8')")
            model = model.quantize(q)
        shape = body.get("warmup_shape")
        mv = self.registry.load(
            body["name"], body["version"], model,
            weight=body.get("weight"),
            warmup_shape=None if shape is None else tuple(shape),
            warmup=bool(body.get("warmup", True)),
            batch_limit=body.get("batch_limit"),
            max_queue=body.get("max_queue"))
        return {"loaded": mv.describe()}

    def _unload_route(self, body: dict):
        self._require(body, "name")
        try:
            removed = self.registry.unload(body["name"], body.get("version"))
        except KeyError as e:
            raise HttpError(404, str(e)) from None
        return {"unloaded": [mv.describe() for mv in removed]}

    def _split_route(self, body: dict):
        self._require(body, "name", "split")
        try:
            split = self.registry.set_split(body["name"], body["split"])
        except KeyError as e:
            raise HttpError(404, str(e)) from None
        return {"split": split}

    def _readyz(self, _body):
        if self._draining:
            raise HttpError(503, "draining")
        if not self.registry.ready():
            raise HttpError(503, "no model loaded")
        return {"ready": True, "models": self.registry.names()}

    def _slo_route(self, _body):
        """Per-class SLO status: objective, burn rate, shed state — the
        operator's 'is batch being sacrificed right now, and why' view."""
        if self.slo is None:
            return {"enabled": False}
        return dict(self.slo.status(), enabled=True)

    def _failover_route(self, _body):
        """Per-replica breaker states + idempotency stats, or
        ``{"enabled": false}`` on a gateway without failover config."""
        if self.failover is None:
            return {"enabled": False}
        return dict(self.failover.describe(), enabled=True)

    def _debug_requests(self, _body):
        """In-flight + recently completed request traces (the tracer's
        table), or ``{"enabled": false}`` on an untraced gateway."""
        if self.tracer is None:
            return {"enabled": False}
        return dict(self.tracer.describe(), enabled=True)

    def _debug_flight(self, _body):
        """The flight recorder's recent-incident tail (process-wide), or
        ``{"enabled": false}`` when no recorder is armed."""
        rec = flight.recorder()
        if rec is None:
            return {"enabled": False}
        return dict(rec.describe(), enabled=True)

    def _debug_trace(self, params, _body):
        """One request's Chrome trace-event JSON by trace id."""
        if self.tracer is None:
            raise HttpError(404, "tracing is not enabled on this gateway")
        trace = self.tracer.get(params["trace_id"])
        if trace is None:
            raise HttpError(
                404, f"unknown trace id {params['trace_id']!r} (in-flight "
                "table and completed ring were checked)")
        return trace.to_chrome()

    def _healthz(self, _body):
        """Liveness stays 200 (the process is up — restart-level health is
        the balancer's /readyz call), but the body surfaces self-healing
        state: any model worker currently dead, or revived since load, is
        listed so operators see degradation before it becomes an outage."""
        health = self.registry.health()
        degraded = sorted(k for k, h in health.items()
                          if not h["healthy"] or h["worker_restarts"] > 0)
        return {"status": "degraded" if degraded else "alive",
                "degraded": degraded, "workers": health}

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServingGateway":
        self._draining = False
        post_routes = {}
        if self.admin:
            post_routes.update({
                "/models/load": self._load_route,
                "/models/reload": self._load_route,
                "/models/unload": self._unload_route,
                "/models/split": self._split_route,
            })
        self._httpd, self._thread = serve_json(
            self._host, self._port,
            post_routes=post_routes,
            get_routes={
                "/healthz": self._healthz,
                "/readyz": self._readyz,
                "/slo": self._slo_route,
                "/failover": self._failover_route,
                "/models": lambda _: {"models": self.registry.describe()},
                "/debug/requests": self._debug_requests,
                "/debug/flight": self._debug_flight,
            },
            dynamic_post=[
                ("/v1/*/predict", _match_predict, self._predict),
                ("/v1/*/generate", match_generate, self._generate),
            ],
            dynamic_get=[
                ("/debug/trace/*", _match_debug_trace, self._debug_trace),
            ])
        if self.autoscaler is not None:
            self.autoscaler.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Graceful drain: stop admitting (new predicts AND generates get
        503, /readyz flips), wait for in-flight work — one-shot requests
        and open generate streams alike, since a stream holds its in-flight
        slot until its last token is written — then shut down. Streams
        still open at the deadline are cancelled at their engine (the
        terminal ndjson line says ``finish_reason: "cancelled"``), never
        left to run headless. ``drain=False`` hard-stops."""
        self._draining = True
        if self.autoscaler is not None:
            # no replica churn while the workers are flushing their lanes
            self.autoscaler.stop()
        end = time.monotonic() + timeout
        if drain:
            with self._inflight_lock:
                while self._inflight > 0:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._idle.wait(timeout=remaining)
        for eng in self._generators.values():
            # drain already waited on open streams; this stops the step
            # loop and cancels anything past the deadline
            eng.shutdown(timeout=max(0.0, end - time.monotonic())
                         if drain else 0.0)
        if drain:
            # cancelled streams flush their terminal line before the
            # listener goes away
            with self._inflight_lock:
                while self._inflight > 0:
                    remaining = end + 1.0 - time.monotonic()
                    if remaining <= 0:
                        break
                    self._idle.wait(timeout=remaining)
        self._stop_httpd()
        self.registry.shutdown(drain=drain)
