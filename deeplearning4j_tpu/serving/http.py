"""JSON-over-HTTP scaffolding shared by every serving endpoint.

Stdlib-only (ThreadingHTTPServer): routes are ``{path: fn(body) -> payload}``
plus *dynamic* routes — ``(label, match_fn, handler)`` triples for
parameterized paths like ``/v1/<model>/predict`` — so the gateway can route
per-model without registering a handler per model. Handlers signal
non-200 outcomes by raising :class:`HttpError` (status code + optional
response headers, e.g. ``Retry-After`` on 429 backpressure); any other
exception is a 400 at the serving boundary.

Every server also answers ``GET /metrics`` with the process-wide Prometheus
exposition, and — when monitoring is enabled — records per-route request
latency and an in-flight gauge. Dynamic routes are observed under their
*label* (``/v1/*/predict``), not the raw path, so metric cardinality stays
bounded no matter how many models are registered.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu import monitoring


class HttpError(Exception):
    """A handler-raised HTTP outcome: status code, JSON error payload, and
    optional extra response headers (e.g. ``{"Retry-After": "1"}``)."""

    def __init__(self, code: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.code = int(code)
        self.message = message
        self.headers = dict(headers or {})


class _HttpServerMixin:
    """Shared ephemeral-port resolution and shutdown for the HTTP servers."""

    _httpd = None
    _thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def _stop_httpd(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


# (label-for-metrics, path -> params-or-None, handler(params, body))
DynamicRoute = Tuple[str, Callable[[str], Optional[dict]],
                     Callable[[dict, dict], dict]]


def serve_json(host, port, post_routes, get_routes,
               dynamic_post: Optional[List[DynamicRoute]] = None,
               dynamic_get: Optional[List[DynamicRoute]] = None):
    """Start a threaded JSON HTTP server; returns (httpd, thread) — call
    httpd.shutdown()/server_close() to stop."""
    dynamic_post = dynamic_post or []
    dynamic_get = dynamic_get or []

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code, payload, headers=None):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _match(self, routes, dynamic):
            path = self.path.split("?")[0]
            fn = routes.get(path)
            if fn is not None:
                return path, fn
            for label, match, handler in dynamic:
                params = match(path)
                if params is not None:
                    return label, (lambda body, h=handler, p=params: h(p, body))
            return path, None

        def _route(self, routes, dynamic, body):
            label, fn = self._match(routes, dynamic)
            if fn is None:
                self._reply(404, {"error": "unknown endpoint"})
                return
            mon = monitoring.serving_monitor()
            if mon is None:
                try:
                    self._reply(200, fn(body))
                except HttpError as e:
                    self._reply(e.code, {"error": e.message}, e.headers)
                except Exception as e:  # noqa: BLE001 — serving boundary
                    self._reply(400, {"error": str(e)})
                return
            mon.in_flight.inc()
            t0 = time.perf_counter()
            code, headers = 200, None
            try:
                payload = fn(body)
            except HttpError as e:
                code, payload, headers = e.code, {"error": e.message}, e.headers
            except Exception as e:  # noqa: BLE001 — serving boundary
                code, payload = 400, {"error": str(e)}
            finally:
                mon.in_flight.dec()
            mon.request_seconds.labels(route=label, code=code).observe(
                time.perf_counter() - t0)
            self._reply(code, payload, headers)

        def do_POST(self):  # noqa: N802
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
            except Exception as e:  # noqa: BLE001
                self._reply(400, {"error": str(e)})
                return
            self._route(post_routes, dynamic_post, body)

        def do_GET(self):  # noqa: N802
            if self.path.split("?")[0] == "/metrics":
                data = monitoring.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._route(get_routes, dynamic_get, {})

        def handle_one_request(self):
            # a client that times out / resets mid-write is business as
            # usual at the serving boundary, not a stack trace
            try:
                super().handle_one_request()
            except (ConnectionResetError, BrokenPipeError):
                self.close_connection = True

        def log_message(self, *args):
            pass

    class Server(ThreadingHTTPServer):
        # socketserver's default listen backlog of 5 resets connections
        # under bursty client fleets before admission control ever sees
        # them; backpressure must come from 429s, not TCP RSTs
        request_queue_size = 128
        daemon_threads = True

    httpd = Server((host, port), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread


# Back-compat alias (pre-gateway name, used by external callers of the old
# deeplearning4j_tpu.serving module).
_serve_json = serve_json
