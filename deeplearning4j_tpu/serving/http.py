"""JSON-over-HTTP scaffolding shared by every serving endpoint.

Stdlib-only (ThreadingHTTPServer): routes are ``{path: fn(body) -> payload}``
plus *dynamic* routes — ``(label, match_fn, handler)`` triples for
parameterized paths like ``/v1/<model>/predict`` — so the gateway can route
per-model without registering a handler per model. Handlers signal
non-200 outcomes by raising :class:`HttpError` (status code + optional
response headers, e.g. ``Retry-After`` on 429 backpressure); any other
exception is a 400 at the serving boundary.

Every server also answers ``GET /metrics`` with the process-wide Prometheus
exposition, and — when monitoring is enabled — records per-route request
latency and an in-flight gauge. Dynamic routes are observed under their
*label* (``/v1/*/predict``), not the raw path, so metric cardinality stays
bounded no matter how many models are registered.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight


def _record_gateway_error(route: str, exc: BaseException) -> None:
    """Flight-record an UNHANDLED handler exception (HttpErrors are
    intentional outcomes, not incidents) — a dump-trigger kind."""
    rec = flight.recorder()
    if rec is not None:
        rec.record("gateway_error", severity="error", route=route,
                   error=f"{type(exc).__name__}: {exc}")


class HttpError(Exception):
    """A handler-raised HTTP outcome: status code, JSON error payload, and
    optional extra response headers (e.g. ``{"Retry-After": "1"}``)."""

    def __init__(self, code: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.code = int(code)
        self.message = message
        self.headers = dict(headers or {})


class StreamingResponse:
    """Marker return type for handlers that stream their response.

    ``lines`` is an iterable of JSON-able dicts, written as newline-
    delimited JSON (ndjson) with a flush per line — the client sees tokens
    as they are produced. Delimiting is connection-close (HTTP/1.0 style):
    no Content-Length, ``Connection: close`` — which stdlib http.client,
    curl, and every load balancer understand without chunked-encoding
    machinery.

    ``on_finish`` runs EXACTLY once when the stream ends for any reason —
    fully written, client disconnect, or handler error. It is where the
    gateway releases its in-flight slot and cancels an abandoned upstream
    generation, so graceful drain can count streams, not just one-shot
    requests.
    """

    def __init__(self, lines, on_finish: Optional[Callable[[], None]] = None,
                 content_type: str = "application/x-ndjson"):
        self._lines = lines
        self._on_finish = on_finish
        self.content_type = content_type
        self._finished = False

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        if self._on_finish is not None:
            self._on_finish()

    def __iter__(self):
        try:
            for d in self._lines:
                yield (json.dumps(d) + "\n").encode()
        finally:
            self.finish()


class _HttpServerMixin:
    """Shared ephemeral-port resolution and shutdown for the HTTP servers."""

    _httpd = None
    _thread = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def _stop_httpd(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


# (label-for-metrics, path -> params-or-None, handler(params, body))
DynamicRoute = Tuple[str, Callable[[str], Optional[dict]],
                     Callable[[dict, dict], dict]]


def serve_json(host, port, post_routes, get_routes,
               dynamic_post: Optional[List[DynamicRoute]] = None,
               dynamic_get: Optional[List[DynamicRoute]] = None):
    """Start a threaded JSON HTTP server; returns (httpd, thread) — call
    httpd.shutdown()/server_close() to stop."""
    dynamic_post = dynamic_post or []
    dynamic_get = dynamic_get or []

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code, payload, headers=None):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)

        def _stream_reply(self, resp: StreamingResponse):
            self.send_response(200)
            self.send_header("Content-Type", resp.content_type)
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.close_connection = True
            self.end_headers()
            try:
                for chunk in resp:
                    self.wfile.write(chunk)
                    self.wfile.flush()
            finally:
                # client aborts surface as write errors above; either way
                # the stream's on_finish must run (drain accounting/cancel)
                resp.finish()

        def _match(self, routes, dynamic):
            path = self.path.split("?")[0]
            fn = routes.get(path)
            if fn is not None:
                return path, fn
            for label, match, handler in dynamic:
                params = match(path)
                if params is not None:
                    # dynamic handlers get the request headers under
                    # "_headers" (case-insensitive Message mapping) — the
                    # tenancy layer reads X-Api-Key from here
                    return label, (lambda body, h=handler, p=params,
                                   hd=self.headers:
                                   h(dict(p, _headers=hd), body))
            return path, None

        def _route(self, routes, dynamic, body):
            label, fn = self._match(routes, dynamic)
            if fn is None:
                self._reply(404, {"error": "unknown endpoint"})
                return
            mon = monitoring.serving_monitor()
            if mon is None:
                try:
                    payload = fn(body)
                except HttpError as e:
                    self._reply(e.code, {"error": e.message}, e.headers)
                    return
                except Exception as e:  # noqa: BLE001 — serving boundary
                    _record_gateway_error(label, e)
                    self._reply(400, {"error": str(e)})
                    return
                if isinstance(payload, StreamingResponse):
                    self._stream_reply(payload)
                else:
                    self._reply(200, payload)
                return
            mon.in_flight.inc()
            t0 = time.perf_counter()
            code, headers = 200, None
            try:
                payload = fn(body)
            except HttpError as e:
                code, payload, headers = e.code, {"error": e.message}, e.headers
            except Exception as e:  # noqa: BLE001 — serving boundary
                _record_gateway_error(label, e)
                code, payload = 400, {"error": str(e)}
            finally:
                mon.in_flight.dec()
            if isinstance(payload, StreamingResponse):
                # latency for a stream is time-to-last-token, observed after
                # the stream is fully written (or the client went away)
                self._stream_reply(payload)
                mon.request_seconds.labels(route=label, code=code).observe(
                    time.perf_counter() - t0)
                return
            mon.request_seconds.labels(route=label, code=code).observe(
                time.perf_counter() - t0)
            self._reply(code, payload, headers)

        def do_POST(self):  # noqa: N802
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
            except Exception as e:  # noqa: BLE001
                self._reply(400, {"error": str(e)})
                return
            self._route(post_routes, dynamic_post, body)

        def do_GET(self):  # noqa: N802
            path, _, query = self.path.partition("?")
            if path == "/metrics":
                # ?exemplars=1 upgrades the scrape to OpenMetrics with
                # exemplars on histogram buckets (trace-id backlinks); the
                # default scrape stays plain text format 0.0.4
                want_ex = parse_qs(query).get("exemplars", ["0"])[0].lower() \
                    not in ("", "0", "false", "off", "no")
                data = monitoring.metrics_text(exemplars=want_ex).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "application/openmetrics-text; version=1.0.0; "
                    "charset=utf-8" if want_ex
                    else "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            self._route(get_routes, dynamic_get, {})

        def handle_one_request(self):
            # a client that times out / resets mid-write is business as
            # usual at the serving boundary, not a stack trace
            try:
                super().handle_one_request()
            except (ConnectionResetError, BrokenPipeError):
                self.close_connection = True

        def log_message(self, *args):
            pass

    class Server(ThreadingHTTPServer):
        # socketserver's default listen backlog of 5 resets connections
        # under bursty client fleets before admission control ever sees
        # them; backpressure must come from 429s, not TCP RSTs
        request_queue_size = 128
        daemon_threads = True

    httpd = Server((host, port), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread


# Back-compat alias (pre-gateway name, used by external callers of the old
# deeplearning4j_tpu.serving module).
_serve_json = serve_json
