"""Cross-cutting runtime configuration (dtype policy, env flags).

Reference analog: ND4J's runtime-flag tier — org.nd4j.config.ND4JSystemProperties /
ND4JEnvironmentVars and libnd4j's Environment singleton.
"""

from deeplearning4j_tpu.common.dtypes import DtypePolicy, get_policy, set_policy
from deeplearning4j_tpu.common.env import Environment, env

__all__ = ["DtypePolicy", "get_policy", "set_policy", "Environment", "env"]
