"""Global dtype policy: params in f32, compute in bf16 on the MXU.

Reference analog: ND4J's global data-type setting
(org.nd4j.linalg.factory.Nd4j#setDefaultDataTypes, DataType.HALF on GPU) and
libnd4j Environment::allowHalfPrecision. On TPU the idiomatic split is
mixed precision: keep master params + optimizer state in float32, run
matmul/conv compute in bfloat16 (native MXU dtype, no loss-scaling needed
unlike fp16), and accumulate in float32.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """What dtype each tensor class uses.

    param_dtype:   master copy of trainable parameters (and optimizer state).
    compute_dtype: activations / matmul inputs inside the jitted step.
    output_dtype:  dtype returned to the user from ``output()`` etc.
    """

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32

    @property
    def mixed(self) -> bool:
        return self.compute_dtype != self.param_dtype

    def cast_to_compute(self, tree):
        import jax

        return jax.tree_util.tree_map(
            lambda x: x.astype(self.compute_dtype)
            if hasattr(x, "astype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            tree,
        )


FLOAT32 = DtypePolicy()
BF16 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16)

_policy: DtypePolicy = FLOAT32


def set_policy(policy: DtypePolicy | str) -> DtypePolicy:
    """Set the process-wide dtype policy ("float32", "bf16", or a DtypePolicy)."""
    global _policy
    if isinstance(policy, str):
        policy = {"float32": FLOAT32, "f32": FLOAT32, "bf16": BF16, "bfloat16": BF16}[
            policy.lower()
        ]
    _policy = policy
    return _policy


def get_policy() -> DtypePolicy:
    return _policy
