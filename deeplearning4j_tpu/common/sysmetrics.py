"""Host + device system metrics for the monitoring path.

Reference analog: the system/memory section of the reference UI
(StatsListener collects JVM/off-heap memory and GC counts via
SystemInfoCollection) and PerformanceListener's GC/memory reporting. The
TPU-native equivalents are host RSS (the JVM-heap analog) and PJRT device
memory stats (the device-memory analog, from
jax.local_devices()[0].memory_stats() when the backend exposes it).
"""

from __future__ import annotations

from typing import Dict


def host_rss_mb() -> float:
    """Resident set size of this process in MiB (from /proc/self/statm;
    falls back to resource.getrusage off-Linux)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os

        return pages * os.sysconf("SC_PAGE_SIZE") / (1 << 20)
    except Exception:
        try:
            import resource
            import sys

            # peak (not current) RSS; ru_maxrss is KiB on Linux, bytes on
            # macOS — and this branch only runs where /proc is absent
            div = (1 << 20) if sys.platform == "darwin" else 1024
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / div
        except Exception:
            return 0.0


def device_memory_mb(device=None) -> Dict[str, float]:
    """{'device_mem_in_use_mb', 'device_mem_limit_mb'} when the PJRT
    backend exposes memory_stats(); {} otherwise (CPU backend, interpret)."""
    try:
        import jax

        dev = device or jax.local_devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return {}
        out = {}
        if "bytes_in_use" in stats:
            out["device_mem_in_use_mb"] = stats["bytes_in_use"] / (1 << 20)
        if "bytes_limit" in stats:
            out["device_mem_limit_mb"] = stats["bytes_limit"] / (1 << 20)
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            out["device_mem_peak_mb"] = peak / (1 << 20)
        return out
    except Exception:
        return {}


def system_metrics() -> Dict[str, float]:
    """All system scalar series for the listener/UI path."""
    out = {"host_rss_mb": host_rss_mb()}
    out.update(device_memory_mb())
    return out
