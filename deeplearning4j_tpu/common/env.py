"""Runtime environment flags, read from process env vars.

Reference analog: org.nd4j.config.ND4JEnvironmentVars (backend selection,
workspace debug, OMP threads) and libnd4j's Environment singleton
(verbose/debug toggles over JNI). Here the flags steer op-impl selection
(Pallas vs plain XLA), debug checks, and profiling — the things that still
exist in an XLA world.
"""

from __future__ import annotations

import os


def _flag(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("", "0", "false", "off", "no")


def _int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or not v.strip():
        return default
    try:
        return int(v.strip())
    except ValueError:
        return default


class Environment:
    """Process-wide runtime switches (singleton, like libnd4j Environment)."""

    # Disable all Pallas kernels: every op uses its plain-XLA lowering.
    # Analog of removing deeplearning4j-cuda from the classpath (no cuDNN helpers).
    DISABLE_PALLAS = "DL4J_TPU_DISABLE_PALLAS"
    # Force Pallas kernels even where the predicate would pick XLA (testing).
    FORCE_PALLAS = "DL4J_TPU_FORCE_PALLAS"
    # Panic on NaN/Inf produced by ops (OpProfiler ANY_PANIC analog).
    NAN_PANIC = "DL4J_TPU_NAN_PANIC"
    # Verbose op-dispatch logging (libnd4j Environment::setVerbose analog).
    VERBOSE = "DL4J_TPU_VERBOSE"
    # Per-op timing profiler (org.nd4j.linalg.profiler.OpProfiler analog).
    PROFILING = "DL4J_TPU_PROFILING"
    # Unified monitoring layer (metrics registry + fit-loop instrumentation,
    # deeplearning4j_tpu/monitoring). Default OFF: the fit hot path then
    # performs no registry/tracer calls (tests enforce zero overhead).
    MONITORING = "DL4J_TPU_MONITORING"
    # Force the fused LSTM to take the scan-recompute backward instead of
    # the Pallas backward kernel (A/B measurement + escape hatch).
    LSTM_SCAN_BWD = "DL4J_TPU_LSTM_SCAN_BWD"
    # Same escape hatch for the fused GRU backward.
    GRU_SCAN_BWD = "DL4J_TPU_GRU_SCAN_BWD"
    # Import-graph optimizer (modelimport/optimizer.py): constant folding,
    # layout-op elimination, attention fusion over TF/ONNX/Keras imports.
    # Default ON; DL4J_TPU_IMPORT_OPT=0 restores the raw parsed graph.
    IMPORT_OPT = "DL4J_TPU_IMPORT_OPT"
    # Deterministic fault injection (deeplearning4j_tpu.faults): spec
    # grammar "cls:rate[@cond]" plus its seed and simulated straggler
    # delay. Parsed by faults.configure()/reset() (not cached here);
    # unset = no plan installed = zero-overhead injection points.
    FAULTS = "DL4J_TPU_FAULTS"
    FAULTS_SEED = "DL4J_TPU_FAULTS_SEED"
    FAULTS_DELAY_S = "DL4J_TPU_FAULTS_DELAY_S"
    # Async training dispatch (optimize/async_dispatch.py): how many train
    # steps may be in flight before fit_batch drains the oldest loss.
    # Default 2 (double-buffered dispatch); 0 restores the per-step
    # host-sync behavior (fit_batch returns an eager float).
    ASYNC_STEPS = "DL4J_TPU_ASYNC_STEPS"
    # Tail-batch padding: pad partial epoch-tail batches up to the pow2
    # bucket of the largest batch seen (label-mask zeroed — loss-exact) so
    # ragged tails stop compiling one XLA program per shape. Default ON;
    # =0 feeds batches through at their raw shapes.
    PAD_TAIL = "DL4J_TPU_PAD_TAIL"
    # Persistent XLA compilation cache directory (monitoring/compile.py
    # wires it plus the dl4j_compile_* metrics tier). Unset = no cache.
    COMPILE_CACHE = "DL4J_TPU_COMPILE_CACHE"
    # SpanTracer ring-buffer capacity: oldest events are dropped (and
    # counted in dl4j_trace_events_dropped_total) past this many, so a
    # long-running gateway with tracing armed holds memory flat.
    TRACE_MAX_EVENTS = "DL4J_TPU_TRACE_MAX_EVENTS"
    # Request tracing on serving gateways built without an explicit
    # ``trace=`` argument (monitoring/context.py). Unset/0 = the request
    # path performs zero tracer calls (spy-guarded contract).
    TRACING = "DL4J_TPU_TRACING"
    # Black-box flight recorder (monitoring/flight.py): =1 arms the
    # process-wide ring buffer of serving/training incidents; the DIR
    # variant also sets where trigger conditions dump postmortem bundles.
    FLIGHT = "DL4J_TPU_FLIGHT"
    FLIGHT_DIR = "DL4J_TPU_FLIGHT_DIR"
    FLIGHT_CAP = "DL4J_TPU_FLIGHT_CAP"
    # Training guardrails (deeplearning4j_tpu.guardrails): =1 arms the
    # numeric sentinel + policy ladder on every model's fit loop; the DIR
    # variant gives the ladder a rollback checkpoint directory (without
    # it, the ladder ends at clip-retry). Unset = zero-overhead unarmed
    # fit path (spy-guarded, like MONITORING/FAULTS).
    GUARDRAILS = "DL4J_TPU_GUARDRAILS"
    GUARDRAILS_DIR = "DL4J_TPU_GUARDRAILS_DIR"

    def __init__(self) -> None:
        self.reload()

    def reload(self) -> None:
        self.disable_pallas = _flag(self.DISABLE_PALLAS)
        self.force_pallas = _flag(self.FORCE_PALLAS)
        self.nan_panic = _flag(self.NAN_PANIC)
        self.verbose = _flag(self.VERBOSE)
        self.profiling = _flag(self.PROFILING)
        self.monitoring = _flag(self.MONITORING)
        self.lstm_scan_bwd = _flag(self.LSTM_SCAN_BWD)
        self.gru_scan_bwd = _flag(self.GRU_SCAN_BWD)
        self.import_opt = _flag(self.IMPORT_OPT, True)
        self.async_steps = max(0, _int(self.ASYNC_STEPS, 2))
        self.pad_tail = _flag(self.PAD_TAIL, True)
        self.compile_cache_dir = (os.environ.get(self.COMPILE_CACHE)
                                  or "").strip() or None
        self.trace_max_events = max(1, _int(self.TRACE_MAX_EVENTS, 100_000))
        self.tracing = _flag(self.TRACING)
        self.flight = _flag(self.FLIGHT)
        self.flight_dir = (os.environ.get(self.FLIGHT_DIR)
                           or "").strip() or None
        self.flight_cap = max(1, _int(self.FLIGHT_CAP, 512))
        self.guardrails = _flag(self.GUARDRAILS)
        self.guardrails_dir = (os.environ.get(self.GUARDRAILS_DIR)
                               or "").strip() or None


env = Environment()
