"""MNIST dataset iterator.

Reference analog: deeplearning4j-data :: org.deeplearning4j.datasets.iterator.
impl.MnistDataSetIterator + the MnistFetcher that downloads/caches idx files.

This environment has no network egress, so the fetcher resolves in order:
1. IDX files (train-images-idx3-ubyte etc., optionally .gz) under
   $DL4J_TPU_DATA_DIR/mnist, ~/.dl4j_tpu/mnist, or ./data/mnist;
2. a deterministic synthetic stand-in: 28x28 procedurally-rendered digit
   glyphs with random shift/scale/noise. Same shapes/dtypes/label layout as
   real MNIST, fully learnable (a LeNet reaches >95% on it), clearly flagged
   via ``MnistDataSetIterator.synthetic``.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

_SEARCH_DIRS = [
    os.environ.get("DL4J_TPU_DATA_DIR", "") + "/mnist",
    os.path.expanduser("~/.dl4j_tpu/mnist"),
    "./data/mnist",
]

# 7-segment-style glyph masks per digit, on a 4x3 grid scaled up to 28x28.
_GLYPHS = {
    0: ["###", "#.#", "#.#", "###"],
    1: ["..#", "..#", "..#", "..#"],
    2: ["###", "..#", "#..", "###"],
    3: ["###", ".##", "..#", "###"],
    4: ["#.#", "#.#", "###", "..#"],
    5: ["###", "#..", "..#", "###"],
    6: ["###", "#..", "#.#", "###"],
    7: ["###", "..#", ".#.", ".#."],
    8: ["###", "#.#", "#.#", "##."],
    9: ["###", "#.#", "###", "..#"],
}


def _read_idx(path: str) -> np.ndarray:
    """IDX file read under the shared RetryPolicy: real corpora live on
    network filesystems where a transient EIO on one read is routine —
    retrying with backoff beats failing the whole import (``data_io``
    injects exactly that error)."""
    from deeplearning4j_tpu import faults

    def read():
        plan = faults.active()
        if plan is not None and plan.fires("data_io"):
            raise faults.DataReadFault(f"injected read failure for {path}")
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, = struct.unpack(">I", f.read(4))
            ndim = magic & 0xFF
            dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)

    if faults.active() is None:
        return read()
    return faults.RetryPolicy(max_attempts=4, base_delay_s=0.01,
                              max_delay_s=0.2).call(read, component="data")


def _find_idx(train: bool):
    img = "train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte"
    lab = "train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte"
    for d in _SEARCH_DIRS:
        for suffix in ("", ".gz"):
            ip, lp = os.path.join(d, img + suffix), os.path.join(d, lab + suffix)
            if os.path.exists(ip) and os.path.exists(lp):
                return ip, lp
    return None


def _synthetic_mnist(n: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
    """Render n random digit glyphs at random positions/scales with noise."""
    rng = np.random.default_rng(seed)
    digits = rng.integers(0, 10, n)
    imgs = np.zeros((n, 28, 28), dtype=np.float32)
    cell_opts = (4, 5, 6)
    for i, d in enumerate(digits):
        cell = cell_opts[rng.integers(0, len(cell_opts))]
        gw, gh = 3 * cell, 4 * cell
        ox = rng.integers(1, 28 - gw - 1)
        oy = rng.integers(1, 28 - gh - 1)
        glyph = _GLYPHS[int(d)]
        for r, row in enumerate(glyph):
            for c, ch in enumerate(row):
                if ch == "#":
                    imgs[i, oy + r * cell : oy + (r + 1) * cell,
                         ox + c * cell : ox + (c + 1) * cell] = 1.0
    imgs += rng.normal(0, 0.08, imgs.shape).astype(np.float32)
    imgs = np.clip(imgs, 0.0, 1.0)
    labels = np.eye(10, dtype=np.float32)[digits]
    return imgs[..., None], labels  # NHWC with C=1, already in [0,1]


class MnistDataSetIterator(ArrayDataSetIterator):
    """MNIST batches: features [B,28,28,1] float32 in [0,1], labels one-hot [B,10]."""

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 n_examples: int | None = None, shuffle: bool = True):
        found = _find_idx(train)
        if found is not None:
            imgs = _read_idx(found[0]).astype(np.float32) / 255.0
            labs = _read_idx(found[1])
            features = imgs[..., None]
            labels = np.eye(10, dtype=np.float32)[labs]
            self.synthetic = False
        else:
            n = n_examples or (60000 if train else 10000)
            # cap default synthetic size to keep tests fast unless asked
            if n_examples is None:
                n = min(n, 8192 if train else 2048)
            features, labels = _synthetic_mnist(n, seed + (0 if train else 1))
            self.synthetic = True
        if n_examples is not None:
            features, labels = features[:n_examples], labels[:n_examples]
        super().__init__(features, labels, batch_size, shuffle=shuffle, seed=seed)


class EmnistDataSetIterator(MnistDataSetIterator):
    """EMNIST analog — real data only (no synthetic glyph set for letters);
    falls back to MNIST digits when EMNIST idx files are absent."""
