"""Real-data iterators from datasets bundled in the environment.

Reference analog: the deeplearning4j-data fetchers (MnistDataSetIterator
etc. download real corpora). This sandbox has no network egress, so the
MNIST/CIFAR iterators fall back to synthetic stand-ins when no local files
exist — but scikit-learn SHIPS real datasets inside its wheel, so actual
measured data can cross the framework end to end: the UCI Optical
Recognition of Handwritten Digits corpus (1797 genuine 8x8 scans) and the
UCI tabular sets (iris, wine, breast cancer).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator


def _require_sklearn():
    try:
        import sklearn.datasets as skd
    except ImportError as e:            # pragma: no cover
        raise ImportError(
            "real-data iterators need scikit-learn (bundles the UCI "
            "corpora); install it or use the synthetic iterators") from e
    return skd


class DigitsDataSetIterator(ArrayDataSetIterator):
    """REAL handwritten digits (UCI optdigits via sklearn): features
    [B, 8, 8, 1] float32 scaled to [0, 1], labels one-hot [B, 10].

    train=True takes the first 80% (1437 samples), train=False the held-out
    20% (360) — a fixed split so train/eval never overlap."""

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 shuffle: bool = True):
        skd = _require_sklearn()
        dig = skd.load_digits()
        images = dig.images.astype(np.float32) / 16.0   # pixel range 0..16
        labels = np.eye(10, dtype=np.float32)[dig.target]
        split = int(0.8 * len(images))
        sl = slice(0, split) if train else slice(split, None)
        super().__init__(images[sl][..., None], labels[sl], batch_size,
                         shuffle=shuffle, seed=seed)
        self.synthetic = False


class TabularDataSetIterator(ArrayDataSetIterator):
    """Real UCI tabular classification sets: "iris", "wine",
    "breast_cancer". Labels one-hot; features standardized with
    NormalizerStandardize statistics FIT ON THE TRAIN SPLIT only (the
    normalizer must never see held-out rows). train=True serves a fixed
    interleaved 80% (every 5th row held out), train=False the other 20%
    — interleaved because the UCI files are grouped by class, so a prefix
    split would drop whole classes from one side."""

    def __init__(self, name: str, batch_size: int, train: bool = True,
                 seed: int = 123, shuffle: bool = True):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.normalizers import (
            NormalizerStandardize,
        )

        skd = _require_sklearn()
        loaders = {"iris": skd.load_iris, "wine": skd.load_wine,
                   "breast_cancer": skd.load_breast_cancer}
        if name not in loaders:
            raise ValueError(f"unknown dataset {name!r}; "
                             f"options: {sorted(loaders)}")
        raw = loaders[name]()
        x = raw.data.astype(np.float32)
        n_classes = int(raw.target.max()) + 1
        y = np.eye(n_classes, dtype=np.float32)[raw.target]
        test_mask = np.arange(len(x)) % 5 == 4
        sel = ~test_mask if train else test_mask
        norm = NormalizerStandardize().fit(
            ArrayDataSetIterator(x[~test_mask], y[~test_mask],
                                 batch_size=256))
        split = norm.transform(DataSet(x[sel].copy(), y[sel]))
        super().__init__(split.features, split.labels, batch_size,
                         shuffle=shuffle, seed=seed)
        self.normalizer = norm
        self.n_classes = n_classes
        self.n_features = x.shape[1]
        self.synthetic = False
