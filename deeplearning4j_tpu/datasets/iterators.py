"""DataSetIterator contract + implementations.

Reference analog: org.nd4j.linalg.dataset.api.iterator.DataSetIterator
(next/hasNext/reset/batch/totalExamples/setPreProcessor) and DL4J's
AsyncDataSetIterator (prefetch thread feeding a queue). The async analog here
double-buffers host->device transfer on a background thread so the TPU never
waits on input — the DL4J prefetch idea with jax.device_put instead of
workspace pinning.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterable+resettable; subclasses implement _produce().

    Batch reads are a fault-injection point (``data_io``) and run under a
    shared RetryPolicy: a transient storage error on one batch is retried
    with backoff instead of killing the epoch (the reference's
    RecordReader retry story, owned here by the iterator base so every
    subclass inherits it). With no fault plan installed this is a single
    None check per batch — the zero-overhead contract."""

    def __init__(self, batch_size: int):
        self.batch = batch_size
        self.preprocessor = None
        self._retry = None          # built lazily on first injected fault

    def _read_batch(self, it):
        """One guarded pull: the injected ``data_io`` fault fires BEFORE
        the generator advances, so a retry re-attempts the SAME batch."""
        from deeplearning4j_tpu import faults

        plan = faults.active()
        if plan is None:
            return next(it)
        if self._retry is None:
            self._retry = faults.RetryPolicy(
                max_attempts=4, base_delay_s=0.01, max_delay_s=0.2,
                deadline_s=10.0)

        def pull():
            if plan.fires("data_io"):
                raise faults.DataReadFault("injected dataset read failure")
            return next(it)

        return self._retry.call(pull, component="data")

    def __iter__(self) -> Iterator[DataSet]:
        it = iter(self._produce())
        while True:
            try:
                ds = self._read_batch(it)
            except StopIteration:
                return
            if self.preprocessor is not None:
                self.preprocessor.transform(ds)
            yield ds

    def _produce(self):
        raise NotImplementedError

    def reset(self):
        pass

    def set_preprocessor(self, pre):
        self.preprocessor = pre
        return self


class ListDataSetIterator(DataSetIterator):
    """Iterate over pre-built DataSet batches (ListDataSetIterator)."""

    def __init__(self, datasets: list[DataSet], batch_size: int = 0):
        super().__init__(batch_size or (datasets[0].num_examples() if datasets else 0))
        self.datasets = datasets

    def _produce(self):
        yield from self.datasets


class ArrayDataSetIterator(DataSetIterator):
    """Batch a (features, labels) array pair, optional shuffle each epoch."""

    def __init__(self, features, labels, batch_size: int, shuffle: bool = False,
                 seed: int = 0, drop_last: bool = False):
        super().__init__(batch_size)
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)

    def _produce(self):
        n = self.features.shape[0]
        idx = self._rng.permutation(n) if self.shuffle else np.arange(n)
        for i in range(0, n, self.batch):
            sl = idx[i : i + self.batch]
            if self.drop_last and len(sl) < self.batch:
                break
            yield DataSet(self.features[sl], self.labels[sl])

    def total_examples(self) -> int:
        return int(self.features.shape[0])


class AsyncPrefetchIterator(DataSetIterator):
    """Wrap any iterator with a background prefetch thread (AsyncDataSetIterator).

    queue_size=2 gives double buffering: batch N+1 is staged while the device
    runs batch N. With ``device_put`` the staging includes the H2D transfer,
    so it overlaps the previous step's compute instead of serializing after
    it; ``sharder`` (a ``batch -> sharded batch`` callable, e.g.
    ``DeviceMesh.shard_batch`` under ParallelWrapper) replaces the plain
    single-device put so batches arrive already laid out for the mesh.
    """

    def __init__(self, inner: DataSetIterator, queue_size: int = 2,
                 device_put: bool = True, sharder=None):
        super().__init__(getattr(inner, "batch", 0))
        self.inner = inner
        self.queue_size = queue_size
        self.device_put = device_put
        self.sharder = sharder
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    def _stage(self, ds: DataSet) -> DataSet:
        """Move one batch to device (sharded when a sharder is set) on the
        prefetch thread."""
        if self.sharder is not None:
            put = self.sharder
        else:
            import jax

            put = jax.device_put
        return DataSet(
            put(ds.features), put(ds.labels),
            None if ds.features_mask is None else put(ds.features_mask),
            None if ds.labels_mask is None else put(ds.labels_mask),
        )

    def _produce(self):
        q: queue.Queue = queue.Queue(maxsize=self.queue_size)
        stop = threading.Event()
        _END = object()
        error: list = []

        def worker():
            try:
                for ds in self.inner:
                    if stop.is_set():
                        return
                    if self.device_put or self.sharder is not None:
                        ds = self._stage(ds)
                    # bounded put, re-checking stop: a consumer that
                    # abandons the generator mid-epoch would otherwise
                    # leave this thread blocked on a full queue forever
                    # (thread + pinned device batches leaked)
                    while not stop.is_set():
                        try:
                            q.put(ds, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
                # a source failure (e.g. an exhausted data_io fault retry)
                # must surface in the training thread, not silently
                # truncate the epoch
                error.append(e)
            finally:
                # deliver _END unless the consumer already hung up (stop):
                # a live-but-slow consumer must still see the sentinel
                while not stop.is_set():
                    try:
                        q.put(_END, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True)
        self._stop, self._thread = stop, t
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                yield item
            t.join()
            if error:
                raise error[0]
        finally:
            # normal exhaustion, consumer abandonment (GeneratorExit), or
            # an exception downstream: stop the producer and unblock any
            # pending put so the thread exits
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)

    def close(self):
        """Stop the prefetch thread without consuming the iterator (the
        explicit form of abandoning the generator)."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def reset(self):
        self.inner.reset()
