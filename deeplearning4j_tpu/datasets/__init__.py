"""Data pipeline: DataSet, iterators, normalizers, fetchers.

Reference analog: org.nd4j.linalg.dataset (DataSet, normalizers,
DataSetIterator contract), deeplearning4j-data (MnistDataSetIterator etc.),
datavec ETL. Host-side numpy with async device prefetch — the TPU analog of
DL4J's AsyncDataSetIterator prefetch thread.
"""

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator, ListDataSetIterator, ArrayDataSetIterator, AsyncPrefetchIterator,
)
from deeplearning4j_tpu.datasets.normalizers import (
    NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler,
)
from deeplearning4j_tpu.datasets.mnist import EmnistDataSetIterator, MnistDataSetIterator
from deeplearning4j_tpu.datasets.cifar import Cifar10DataSetIterator, SvhnDataSetIterator
from deeplearning4j_tpu.datasets.real import (DigitsDataSetIterator,
                                              TabularDataSetIterator)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ListDataSetIterator", "ArrayDataSetIterator",
    "AsyncPrefetchIterator", "NormalizerStandardize", "NormalizerMinMaxScaler",
    "ImagePreProcessingScaler", "MnistDataSetIterator",
    "EmnistDataSetIterator", "Cifar10DataSetIterator", "SvhnDataSetIterator",
    "DigitsDataSetIterator", "TabularDataSetIterator",
]
