"""CIFAR-10 / SVHN dataset iterators.

Reference analog: org.deeplearning4j.datasets.iterator.impl.
{Cifar10DataSetIterator, SvhnDataSetIterator} + their fetchers. No egress,
so resolution order mirrors MnistDataSetIterator:
1. real files — CIFAR-10 binary batches (data_batch_*.bin / test_batch.bin)
   under $DL4J_TPU_DATA_DIR/cifar10, ~/.dl4j_tpu/cifar10 or ./data/cifar10;
   SVHN as cropped-digit .npz {X: [N,32,32,3], y: [N]} under .../svhn;
2. deterministic synthetic stand-ins (class-colored textured patches),
   flagged via ``.synthetic``, learnable by a small CNN.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator


def _search_dirs(name: str):
    return [Path(os.environ.get("DL4J_TPU_DATA_DIR", "")) / name,
            Path(os.path.expanduser("~/.dl4j_tpu")) / name,
            Path("./data") / name]


def _synthetic_images(n: int, n_classes: int, seed: int,
                      size: int = 32) -> Tuple[np.ndarray, np.ndarray]:
    """Class-dependent color + stripe frequency + noise; separable but not
    trivial (same role as the MNIST glyph generator)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, n)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    feats = np.empty((n, size, size, 3), np.float32)
    for i, c in enumerate(labels):
        hue = c / n_classes
        base = np.stack([
            0.5 + 0.5 * np.sin(2 * np.pi * (hue + xx * (1 + c % 3))),
            0.5 + 0.5 * np.cos(2 * np.pi * (hue + yy * (1 + c % 2))),
            np.full_like(xx, hue),
        ], axis=-1)
        shift = rng.uniform(-0.04, 0.04)
        noise = rng.normal(0, 0.15, base.shape)
        feats[i] = np.clip(base + shift + noise, 0, 1)
    onehot = np.eye(n_classes, dtype=np.float32)[labels]
    return feats, onehot


def _load_cifar_binaries(train: bool) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    for d in _search_dirs("cifar10"):
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [d / n for n in names]
        if not all(p.exists() for p in paths):
            # also accept the cifar-10-batches-bin subdir layout
            paths = [d / "cifar-10-batches-bin" / n for n in names]
            if not all(p.exists() for p in paths):
                continue
        xs, ys = [], []
        for p in paths:
            raw = np.frombuffer(p.read_bytes(), np.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0])
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        x = np.concatenate(xs).astype(np.float32) / 255.0
        y = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
        return x, y
    return None


class Cifar10DataSetIterator(ArrayDataSetIterator):
    """NHWC float32 in [0,1], one-hot 10-class labels."""

    n_classes = 10

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 n_examples: Optional[int] = None, shuffle: bool = True):
        loaded = _load_cifar_binaries(train)
        if loaded is not None:
            feats, labels = loaded
            self.synthetic = False
        else:
            n = n_examples or (4096 if train else 1024)
            feats, labels = _synthetic_images(n, 10, seed + (0 if train else 1))
            self.synthetic = True
        if n_examples is not None:
            feats, labels = feats[:n_examples], labels[:n_examples]
        super().__init__(feats, labels, batch_size, shuffle=shuffle, seed=seed)


def _load_svhn_npz(train: bool) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    for d in _search_dirs("svhn"):
        p = d / ("train_32x32.npz" if train else "test_32x32.npz")
        if not p.exists():
            continue
        data = np.load(p)
        x = np.asarray(data["X"], np.float32)
        if x.max() > 1.5:
            x = x / 255.0
        if x.shape[-1] != 3 and x.shape[0] == 32:  # matlab [32,32,3,N] layout
            x = x.transpose(3, 0, 1, 2)
        y = np.asarray(data["y"]).ravel() % 10  # SVHN labels digit 10 == 0
        return x, np.eye(10, dtype=np.float32)[y]
    return None


class SvhnDataSetIterator(ArrayDataSetIterator):
    """Street View House Numbers, cropped-digit format."""

    n_classes = 10

    def __init__(self, batch_size: int, train: bool = True, seed: int = 123,
                 n_examples: Optional[int] = None, shuffle: bool = True):
        loaded = _load_svhn_npz(train)
        if loaded is not None:
            feats, labels = loaded
            self.synthetic = False
        else:
            n = n_examples or (4096 if train else 1024)
            feats, labels = _synthetic_images(n, 10, seed + 77 + (0 if train else 1))
            self.synthetic = True
        if n_examples is not None:
            feats, labels = feats[:n_examples], labels[:n_examples]
        super().__init__(feats, labels, batch_size, shuffle=shuffle, seed=seed)
