"""DataSet — a (features, labels, masks) batch.

Reference analog: org.nd4j.linalg.dataset.DataSet (features, labels,
featuresMaskArray, labelsMaskArray; save/load, shuffle, splitTestAndTrain,
batchBy). Host-side numpy; conversion to device arrays happens at the jit
boundary.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx],
        )

    def split_test_and_train(self, n_train: int):
        """Returns (train, test) (DataSet.splitTestAndTrain)."""
        tr = DataSet(
            self.features[:n_train], self.labels[:n_train],
            None if self.features_mask is None else self.features_mask[:n_train],
            None if self.labels_mask is None else self.labels_mask[:n_train],
        )
        te = DataSet(
            self.features[n_train:], self.labels[n_train:],
            None if self.features_mask is None else self.features_mask[n_train:],
            None if self.labels_mask is None else self.labels_mask[n_train:],
        )
        return tr, te

    def batch_by(self, batch_size: int) -> list["DataSet"]:
        out = []
        for i in range(0, self.num_examples(), batch_size):
            out.append(DataSet(
                self.features[i : i + batch_size], self.labels[i : i + batch_size],
                None if self.features_mask is None else self.features_mask[i : i + batch_size],
                None if self.labels_mask is None else self.labels_mask[i : i + batch_size],
            ))
        return out

    def save(self, path: str):
        arrays = {"features": self.features, "labels": self.labels}
        if self.features_mask is not None:
            arrays["features_mask"] = self.features_mask
        if self.labels_mask is not None:
            arrays["labels_mask"] = self.labels_mask
        np.savez_compressed(path, **arrays)

    @staticmethod
    def load(path: str) -> "DataSet":
        d = np.load(path)
        return DataSet(d["features"], d["labels"],
                       d.get("features_mask"), d.get("labels_mask"))

    @staticmethod
    def merge(datasets: list["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets]),
        )


@dataclasses.dataclass
class MultiDataSet:
    """Multi-input / multi-output batch for ComputationGraph training.

    Reference analog: org.nd4j.linalg.dataset.MultiDataSet (features[],
    labels[], per-array masks). ``features``/``labels`` are lists ordered
    like the graph's network_inputs/network_outputs (or dicts keyed by
    name). Sequence masks: the graph threads ONE shared [B, T] features
    mask through every vertex (the common case — all sequence inputs share
    timing). ``labels_mask`` may be a single [B, T] array (applied to every
    output's loss), or a per-output list/dict (r5) — the graph routes each
    output's loss through its own labels mask while the forward sees the
    features mask (DL4J's labelsMaskArrays semantics).
    """

    features: "list | dict"
    labels: "list | dict"
    features_mask: Optional[np.ndarray] = None
    labels_mask: "Optional[np.ndarray | list | dict]" = None

    def _arrays(self, x):
        return list(x.values()) if isinstance(x, dict) else list(x)

    def num_examples(self) -> int:
        return int(self._arrays(self.features)[0].shape[0])

    @staticmethod
    def _take_mask(m, idx):
        if m is None:
            return None
        if isinstance(m, dict):
            return {k: (None if v is None else v[idx]) for k, v in m.items()}
        if isinstance(m, (list, tuple)):
            return [None if v is None else v[idx] for v in m]
        return m[idx]

    def shuffle(self, seed: Optional[int] = None) -> "MultiDataSet":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())

        def take(x):
            if isinstance(x, dict):
                return {k: v[idx] for k, v in x.items()}
            return [v[idx] for v in x]

        return MultiDataSet(
            take(self.features), take(self.labels),
            None if self.features_mask is None else self.features_mask[idx],
            self._take_mask(self.labels_mask, idx))

    def batches(self, batch_size: int):
        """Iterate MultiDataSet minibatches (MultiDataSetIterator analog)."""
        n = self.num_examples()
        for i in range(0, n, batch_size):
            sl = slice(i, i + batch_size)

            def take(x):
                if isinstance(x, dict):
                    return {k: v[sl] for k, v in x.items()}
                return [v[sl] for v in x]

            yield MultiDataSet(
                take(self.features), take(self.labels),
                None if self.features_mask is None else self.features_mask[sl],
                self._take_mask(self.labels_mask, sl))
