"""Data normalizers.

Reference analog: org.nd4j.linalg.dataset.api.preprocessor —
NormalizerStandardize (fit mean/std then transform), NormalizerMinMaxScaler,
ImagePreProcessingScaler (0..255 -> [0,1]), with revert support.
"""

from __future__ import annotations

import numpy as np


class Normalizer:
    def fit(self, iterator):
        raise NotImplementedError

    def transform(self, ds):
        raise NotImplementedError

    def revert(self, ds):
        raise NotImplementedError


class NormalizerStandardize(Normalizer):
    def __init__(self):
        self.mean = None
        self.std = None

    def fit(self, iterator):
        n, s, s2 = 0, 0.0, 0.0
        for ds in iterator:
            f = ds.features.reshape(ds.features.shape[0], -1).astype(np.float64)
            n += f.shape[0]
            s = s + f.sum(axis=0)
            s2 = s2 + (f * f).sum(axis=0)
        if hasattr(iterator, "reset"):
            iterator.reset()
        self.mean = (s / n).astype(np.float32)
        var = s2 / n - (s / n) ** 2
        self.std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)
        return self

    def transform(self, ds):
        shape = ds.features.shape
        f = ds.features.reshape(shape[0], -1)
        ds.features = ((f - self.mean) / self.std).reshape(shape).astype(np.float32)
        return ds

    def revert(self, ds):
        shape = ds.features.shape
        f = ds.features.reshape(shape[0], -1)
        ds.features = (f * self.std + self.mean).reshape(shape)
        return ds


class NormalizerMinMaxScaler(Normalizer):
    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min = None
        self.data_max = None

    def fit(self, iterator):
        lo, hi = None, None
        for ds in iterator:
            f = ds.features.reshape(ds.features.shape[0], -1)
            bmin, bmax = f.min(axis=0), f.max(axis=0)
            lo = bmin if lo is None else np.minimum(lo, bmin)
            hi = bmax if hi is None else np.maximum(hi, bmax)
        if hasattr(iterator, "reset"):
            iterator.reset()
        self.data_min, self.data_max = lo, hi
        return self

    def transform(self, ds):
        shape = ds.features.shape
        f = ds.features.reshape(shape[0], -1)
        rng = np.maximum(self.data_max - self.data_min, 1e-12)
        scaled = (f - self.data_min) / rng
        ds.features = (scaled * (self.max_range - self.min_range) + self.min_range).reshape(
            shape).astype(np.float32)
        return ds

    def revert(self, ds):
        shape = ds.features.shape
        f = (ds.features.reshape(shape[0], -1) - self.min_range) / (
            self.max_range - self.min_range)
        ds.features = (f * (self.data_max - self.data_min) + self.data_min).reshape(shape)
        return ds


class ImagePreProcessingScaler(Normalizer):
    """0..255 pixels -> [min, max] (org.nd4j...ImagePreProcessingScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range

    def fit(self, iterator):
        return self

    def transform(self, ds):
        ds.features = (ds.features.astype(np.float32) / 255.0) * (
            self.max_range - self.min_range) + self.min_range
        return ds

    def revert(self, ds):
        ds.features = (ds.features - self.min_range) / (
            self.max_range - self.min_range) * 255.0
        return ds
