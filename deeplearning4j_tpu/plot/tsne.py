"""t-SNE.

Reference analog: org.deeplearning4j.plot.BarnesHutTsne — the reference
approximates the O(N^2) repulsive forces with a Barnes-Hut quadtree (theta)
because per-pair CPU work is expensive. TPU-first the *exact* N^2 gradient is
a handful of [N, N] matmul/elementwise ops that map straight onto the
MXU/VPU, so for the N this class is used at (thousands of points) exact
beats tree-walking; ``theta`` is accepted for API parity and ignored
(exact = theta 0). The full optimization loop (early exaggeration, momentum,
gain adaptation) runs inside one jitted ``lax.fori_loop``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _conditional_probs(X: np.ndarray, perplexity: float) -> np.ndarray:
    """Per-point sigma binary search to hit the target perplexity (host-side,
    matches the reference's computeGaussianPerplexity)."""
    n = X.shape[0]
    d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    target = np.log(perplexity)
    P = np.zeros((n, n))
    for i in range(n):
        lo, hi = 1e-20, 1e20
        beta = 1.0
        for _ in range(64):
            p = np.exp(-d2[i] * beta)
            s = p.sum()
            if s <= 0:
                H = 0.0
            else:
                p = p / s
                H = -(p[p > 0] * np.log(p[p > 0])).sum()
            if abs(H - target) < 1e-5:
                break
            if H > target:
                lo = beta
                beta = beta * 2 if hi >= 1e20 else (beta + hi) / 2
            else:
                hi = beta
                beta = beta / 2 if lo <= 1e-20 else (beta + lo) / 2
        P[i] = np.exp(-d2[i] * beta)
        P[i, i] = 0.0
        P[i] /= max(P[i].sum(), 1e-12)
    P = (P + P.T) / (2.0 * n)
    return np.maximum(P, 1e-12)


@functools.partial(jax.jit, static_argnames=("n_iter", "exaggeration_iters"))
def _tsne_optimize(P, Y0, n_iter, exaggeration_iters, learning_rate,
                   momentum_init, momentum_final, exaggeration):
    n = Y0.shape[0]

    def grad_kl(Y, Pm):
        d2 = ((Y[:, None, :] - Y[None, :, :]) ** 2).sum(-1)
        num = 1.0 / (1.0 + d2)
        num = num * (1.0 - jnp.eye(n))
        Q = num / jnp.maximum(num.sum(), 1e-12)
        Q = jnp.maximum(Q, 1e-12)
        PQ = (Pm - Q) * num
        g = 4.0 * ((PQ.sum(1)[:, None] * Y) - PQ @ Y)
        kl = (Pm * jnp.log(Pm / Q)).sum()
        return g, kl

    def body(i, carry):
        Y, vel, gains = carry
        Pm = jnp.where(i < exaggeration_iters, P * exaggeration, P)
        g, _ = grad_kl(Y, Pm)
        mom = jnp.where(i < exaggeration_iters, momentum_init, momentum_final)
        same_sign = jnp.sign(g) == jnp.sign(vel)
        gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2),
                         0.01, None)
        vel = mom * vel - learning_rate * gains * g
        Y = Y + vel
        Y = Y - Y.mean(0)
        return Y, vel, gains

    Y, _, _ = lax.fori_loop(0, n_iter, body,
                            (Y0, jnp.zeros_like(Y0), jnp.ones_like(Y0)))
    _, kl = grad_kl(Y, P)
    return Y, kl


class BarnesHutTsne:
    """t-SNE with the reference's builder-ish surface.

        tsne = BarnesHutTsne(n_components=2, perplexity=30.0, max_iter=1000)
        Y = tsne.fit_transform(X)
    """

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 theta: float = 0.5, max_iter: int = 1000,
                 learning_rate: float = 200.0, exaggeration: float = 12.0,
                 seed: int = 42):
        self.n_components = n_components
        self.perplexity = perplexity
        self.theta = theta  # API parity; exact gradient is used regardless
        self.max_iter = max_iter
        self.learning_rate = learning_rate
        self.exaggeration = exaggeration
        self.seed = seed
        self.embedding_: Optional[np.ndarray] = None
        self.kl_divergence_: float = float("nan")

    def fit_transform(self, X) -> np.ndarray:
        X = np.asarray(X, np.float64)
        n = X.shape[0]
        if n < 3:
            raise ValueError("need at least 3 points")
        perp = min(self.perplexity, (n - 1) / 3.0)
        P = _conditional_probs(X, perp)
        rng = np.random.default_rng(self.seed)
        Y0 = (rng.normal(0, 1e-4, (n, self.n_components))).astype(np.float32)
        Y, kl = _tsne_optimize(
            jnp.asarray(P, jnp.float32), jnp.asarray(Y0),
            n_iter=self.max_iter,
            exaggeration_iters=min(250, self.max_iter // 4),
            learning_rate=self.learning_rate,
            momentum_init=0.5, momentum_final=0.8,
            exaggeration=self.exaggeration)
        self.embedding_ = np.asarray(Y)
        self.kl_divergence_ = float(kl)
        return self.embedding_

    fit = fit_transform
