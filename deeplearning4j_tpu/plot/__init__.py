"""Visualization/embedding tools.

Reference analog: org.deeplearning4j.plot — BarnesHutTsne (t-SNE over a
VPTree for the Barnes-Hut approximation).
"""

from deeplearning4j_tpu.plot.tsne import BarnesHutTsne

__all__ = ["BarnesHutTsne"]
