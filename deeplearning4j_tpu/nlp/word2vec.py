"""Word2Vec — skip-gram / CBOW with negative sampling.

Reference analog: org.deeplearning4j.models.word2vec.Word2Vec (+ Builder) on
top of SequenceVectors/AbstractCache; the reference trains with per-thread
Hogwild updates over individual pairs. TPU-first redesign: pair generation is
host-side numpy; the update is one jitted XLA step over a BATCH of
(center, context, negatives[k]) triples — embedding scatter-adds come from
the gradient of gather, which XLA fuses; the MXU sees one [batch, dim] x
[dim, k+1] matmul per step instead of scalar dot products.
"""

from __future__ import annotations

import functools
import os
from typing import Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenizers import CommonPreprocessor, DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import (NegativeSampler, VocabCache,
                                          build_alias_table,
                                          cosine_similarity)


def cbow_windows(encoded, window: int):
    """(center [N], context-window [N, 2*window]) arrays over encoded
    sentences; short windows are padded by cycling the available context
    words. Shared by Word2Vec (CBOW) and ParagraphVectors (PV-DM)."""
    centers, ctxs = [], []
    for sent in encoded:
        n = len(sent)
        for i in range(n):
            ctx = [int(sent[j]) for j in range(max(0, i - window),
                                               min(n, i + window + 1)) if j != i]
            if not ctx:
                continue
            centers.append(int(sent[i]))
            ctxs.append([ctx[k % len(ctx)] for k in range(2 * window)])
    return (np.asarray(centers, np.int32),
            np.asarray(ctxs, np.int32).reshape(-1, 2 * window))


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _sg_neg_step(W, C, center, context, negatives, lr):
    """One negative-sampling SGD step.

    W [V, D] input vectors, C [V, D] output vectors; center [B], context [B],
    negatives [B, K]. Loss = -log σ(w·c) - Σ log σ(-w·n).
    """

    def loss_fn(params):
        W_, C_ = params
        w = W_[center]                       # [B, D]
        pos = jnp.einsum("bd,bd->b", w, C_[context])
        neg = jnp.einsum("bd,bkd->bk", w, C_[negatives])
        return -jax.nn.log_sigmoid(pos).sum() - jax.nn.log_sigmoid(-neg).sum()

    loss, grads = jax.value_and_grad(loss_fn)((W, C))
    W = W - lr * grads[0]
    C = C - lr * grads[1]
    return W, C, loss


@functools.partial(jax.jit, donate_argnums=(0, 1),
                   static_argnames=("k",))
def _sg_neg_steps_devneg(W, C, key, centers, contexts, aprob, aalias, lr, k):
    """S sequential negative-sampling steps in ONE dispatch: centers [S, B]
    and contexts [S, B] scanned over axis 0, so one host->device transfer
    and one XLA launch cover S batches — per-batch dispatch latency
    (significant under a tunneled PJRT client) amortizes S-fold while the
    update math stays bit-identical to S calls of _sg_neg_step.

    Negatives are sampled ON DEVICE from a Vose alias table (aprob [V]
    f32, aalias [V] i32) — the host ships only (center, context) pairs
    (uint16 when the vocab fits), cutting host->device bytes 14x vs
    staging int32 (center, context, negs[S, B, K]). Distribution is the
    same unigram^0.75 (alias method); draws come from the JAX PRNG
    instead of the host stream."""
    V = W.shape[0]

    def body(carry, batch):
        W_, C_, key_ = carry
        center, context = (b.astype(jnp.int32) for b in batch)
        key_, k1, k2 = jax.random.split(key_, 3)
        idx = jax.random.randint(k1, (center.shape[0], k), 0, V)
        u = jax.random.uniform(k2, (center.shape[0], k))
        negs = jnp.where(u < aprob[idx], idx, aalias[idx])

        def loss_fn(params):
            Wp, Cp = params
            w = Wp[center]
            pos = jnp.einsum("bd,bd->b", w, Cp[context])
            neg = jnp.einsum("bd,bkd->bk", w, Cp[negs])
            return (-jax.nn.log_sigmoid(pos).sum()
                    - jax.nn.log_sigmoid(-neg).sum())

        loss, g = jax.value_and_grad(loss_fn)((W_, C_))
        return (W_ - lr * g[0], C_ - lr * g[1], key_), loss

    (W, C, _), losses = jax.lax.scan(body, (W, C, key), (centers, contexts))
    return W, C, losses.sum()


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _cbow_neg_step(W, C, context_win, center, negatives, lr):
    """CBOW: mean of context window vectors predicts the center word.
    context_win [B, 2w] (padded with center index where window clipped)."""

    def loss_fn(params):
        W_, C_ = params
        h = W_[context_win].mean(axis=1)     # [B, D]
        pos = jnp.einsum("bd,bd->b", h, C_[center])
        neg = jnp.einsum("bd,bkd->bk", h, C_[negatives])
        return -jax.nn.log_sigmoid(pos).sum() - jax.nn.log_sigmoid(-neg).sum()

    loss, grads = jax.value_and_grad(loss_fn)((W, C))
    return W - lr * grads[0], C - lr * grads[1], loss


def build_huffman(freqs) -> tuple:
    """Huffman coding over word frequencies (the reference's Huffman class in
    deeplearning4j-nlp, used by its default hierarchical softmax).

    Returns (codes [V, L] int8 0/1, points [V, L] int32 inner-node ids,
    mask [V, L] float32) padded to the longest code length L — fixed shapes
    so the HS step jits once.
    """
    import heapq

    V = len(freqs)
    if V == 1:
        return (np.zeros((1, 1), np.int8), np.zeros((1, 1), np.int32),
                np.ones((1, 1), np.float32))
    heap = [(int(f), i, None, None) for i, f in enumerate(freqs)]
    heapq.heapify(heap)
    next_id = V
    nodes = {}
    while len(heap) > 1:
        f1, id1, l1, r1 = heapq.heappop(heap)
        f2, id2, l2, r2 = heapq.heappop(heap)
        nodes[next_id] = (id1, id2)
        heapq.heappush(heap, (f1 + f2, next_id, id1, id2))
        next_id += 1
    root = heap[0][1]

    codes: list = [None] * V
    points: list = [None] * V

    def walk(node, code, path):
        if node < V:
            codes[node] = code
            points[node] = path
            return
        left, right = nodes[node]
        # inner-node parameter index: node - V (V-1 inner nodes total)
        walk(left, code + [0], path + [node - V])
        walk(right, code + [1], path + [node - V])

    walk(root, [], [])
    L = max(len(c) for c in codes)
    code_m = np.zeros((V, L), np.int8)
    point_m = np.zeros((V, L), np.int32)
    mask_m = np.zeros((V, L), np.float32)
    for i in range(V):
        n = len(codes[i])
        code_m[i, :n] = codes[i]
        point_m[i, :n] = points[i]
        mask_m[i, :n] = 1.0
    return code_m, point_m, mask_m


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _sg_hs_step(W, Theta, accW, accT, center, context, codes, points, mask, lr):
    """Hierarchical-softmax skip-gram step with Adagrad scaling.

    For a (center, context) pair the loss walks the CONTEXT word's Huffman
    path with the center's input vector:
    loss = -sum_l mask * log sigma((1-2*code_l) * w . theta_l);
    Theta holds one vector per inner node ([V-1, D]).

    The summed batch loss concentrates B gradient contributions on the few
    inner nodes near the Huffman root (plain SGD diverges there at any lr
    that still moves the leaves), so the update is Adagrad-normalized per
    parameter — the classic fix for embedding-frequency imbalance; accW/accT
    carry the squared-gradient accumulators across batches."""

    def loss_fn(params):
        W_, T_ = params
        w = W_[center]                           # [B, D]
        th = T_[points[context]]                 # [B, L, D]
        sign = 1.0 - 2.0 * codes[context].astype(jnp.float32)  # [B, L]
        logits = sign * jnp.einsum("bd,bld->bl", w, th)
        logp = jax.nn.log_sigmoid(logits) * mask[context]
        return -logp.sum()

    loss, g = jax.value_and_grad(loss_fn)((W, Theta))
    accW = accW + g[0] * g[0]
    accT = accT + g[1] * g[1]
    W = W - lr * g[0] / jnp.sqrt(accW + 1e-8)
    Theta = Theta - lr * g[1] / jnp.sqrt(accT + 1e-8)
    return W, Theta, accW, accT, loss


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _sg_hs_steps(W, Theta, accW, accT, centers, contexts, codes, points,
                 mask, lr):
    """S sequential hierarchical-softmax steps in one dispatch (the scan
    twin of _sg_hs_step; see _sg_neg_steps_devneg for why): centers/contexts
    [S, B] scanned; the Huffman tables ride along unscanned."""

    def body(carry, batch):
        W_, T_, aW, aT = carry
        center, context = batch

        def loss_fn(params):
            Wp, Tp = params
            w = Wp[center]
            th = Tp[points[context]]
            sign = 1.0 - 2.0 * codes[context].astype(jnp.float32)
            logits = sign * jnp.einsum("bd,bld->bl", w, th)
            return -(jax.nn.log_sigmoid(logits) * mask[context]).sum()

        loss, g = jax.value_and_grad(loss_fn)((W_, T_))
        aW = aW + g[0] * g[0]
        aT = aT + g[1] * g[1]
        return (W_ - lr * g[0] / jnp.sqrt(aW + 1e-8),
                T_ - lr * g[1] / jnp.sqrt(aT + 1e-8), aW, aT), loss

    (W, Theta, accW, accT), losses = jax.lax.scan(
        body, (W, Theta, accW, accT), (centers, contexts))
    return W, Theta, accW, accT, losses.sum()


class Word2Vec:
    """Builder-style Word2Vec (reference: Word2Vec.Builder()...build().fit()).

    ``hs=True`` selects hierarchical softmax over a Huffman tree (the
    reference's default); otherwise negative sampling with ``negative``
    noise words."""

    def __init__(self, vector_size: int = 100, window: int = 5,
                 min_count: int = 1, negative: int = 5, epochs: int = 1,
                 learning_rate: float = 0.025, cbow: bool = False,
                 subsample: float = 0.0, batch_size: int = 512, seed: int = 42,
                 hs: bool = False, workers: int = 0,
                 min_learning_rate: Optional[float] = None):
        self.vector_size = vector_size
        # linear lr decay over the run's words, floored here (reference:
        # Word2Vec.Builder().minLearningRate — its alpha decays with words
        # processed). None keeps the fixed-lr behavior.
        self.min_lr = min_learning_rate
        self.window = window
        self.negative = negative
        self.hs = hs
        # host-side worker threads for the native concurrent front
        # (reference: Word2Vec.Builder().workers(n) — its Hogwild thread
        # count); 0 = auto
        self.workers = workers if workers > 0 else min(8, os.cpu_count() or 4)
        self.epochs = epochs
        self.lr = learning_rate
        self.cbow = cbow
        self.subsample = subsample
        self.batch_size = batch_size
        self.seed = seed
        self.vocab = VocabCache(min_count=min_count)
        self.tokenizer = DefaultTokenizerFactory(CommonPreprocessor())
        self.W: Optional[np.ndarray] = None   # input vectors (the embeddings)
        self.C: Optional[np.ndarray] = None   # output vectors

    # ------------------------------------------------------------------- fit
    def _iter_token_sents(self, corpus):
        """Streaming tokenized-sentence view of ``corpus``: a string (split
        on lines), any iterable of strings/token-lists, or a
        nlp.corpus.SentenceIterator — nothing is materialized, so file-
        backed corpora train at any size (r4). For epochs > 1 the corpus
        must be re-iterable (iterators expose reset(); plain generators
        are single-pass)."""
        if isinstance(corpus, str):
            corpus = corpus.splitlines()
        for line in corpus:
            toks = (self.tokenizer.tokenize(line) if isinstance(line, str)
                    else list(line))
            if toks:
                yield toks

    def _pairs(self, encoded: List[np.ndarray], rng) -> np.ndarray:
        """All (center, context) skip-gram pairs with random window shrink.

        Vectorized over the whole chunk (r5): sentences concatenate into
        one flat token array with per-token sentence positions, and each
        offset d in 1..window contributes its valid left/right pairs in
        two boolean-mask passes — no per-token Python loop. The measured
        host windowing rate went from ~50k words/sec (the r4 double loop,
        a 40x bottleneck under the 2M words/sec device step) to the
        numpy-bound rate; pair semantics are identical (one uniform
        window shrink b per center, both directions share it)."""
        lens = np.asarray([len(s) for s in encoded], np.int64)
        total = int(lens.sum())
        if total == 0:
            return np.zeros((0, 2), np.int32)
        flat = np.concatenate([np.asarray(s, np.int32) for s in encoded])
        starts = np.repeat(np.cumsum(lens) - lens, lens)
        pos = np.arange(total) - starts          # position within sentence
        slen = np.repeat(lens, lens)
        b = rng.integers(1, self.window + 1, total)
        cs, xs = [], []
        for d in range(1, self.window + 1):
            reach = b >= d
            right = reach & (pos + d < slen)
            left = reach & (pos >= d)
            ri = np.nonzero(right)[0]
            li = np.nonzero(left)[0]
            cs.append(flat[ri])
            xs.append(flat[ri + d])
            cs.append(flat[li])
            xs.append(flat[li - d])
        return np.stack([np.concatenate(cs), np.concatenate(xs)],
                        axis=1).astype(np.int32)

    # ------------------------------------------------- native concurrent front
    def _native_corpus_path(self, corpus) -> Optional[str]:
        """File path when ``corpus`` qualifies for the native concurrent
        front (see _fit_native), else None."""
        from deeplearning4j_tpu.native.lib import native_available
        from deeplearning4j_tpu.nlp.corpus import LineSentenceIterator

        if (type(corpus) is LineSentenceIterator
                and corpus.preprocessor is None
                and corpus.encoding.lower().replace("-", "") == "utf8"
                and not self.cbow
                and type(self.tokenizer) is DefaultTokenizerFactory
                and type(self.tokenizer.preprocessor) is CommonPreprocessor
                and os.path.isfile(corpus.path)
                and native_available()):
            return corpus.path
        return None

    @staticmethod
    def _ascii_sample(path: str, limit: int = 1 << 20) -> bool:
        """True when ``limit`` bytes sampled at the file's head, middle,
        and tail are pure ASCII (ADVICE r5: head-only sampling let late
        non-ASCII content ride the native front and silently diverge the
        vocabulary). The native tokenizer only matches the Python one
        (lowercase + [^\\w\\s] strip) for ASCII text — non-ASCII bytes pass
        through unlowercased and unicode punctuation survives — so AUTO
        selection requires ASCII samples; ``native_front=True`` overrides
        (byte-level semantics, documented in nlp.native_text)."""
        size = os.path.getsize(path)
        if size <= limit:
            offsets, chunk = [0], limit
        else:
            chunk = limit // 3
            offsets = [0, max(0, size // 2 - chunk // 2), size - chunk]
        with open(path, "rb") as f:
            for off in offsets:
                f.seek(off)
                sample = f.read(chunk)
                if sample and max(sample) >= 0x80:
                    return False
        return True

    def _lr_at(self, words_done: int, total_words: int) -> float:
        """Linear lr decay over the run's in-vocab words (the reference's
        alpha schedule), floored at min_learning_rate; fixed lr when the
        floor is unset. lr rides the jitted steps as a traced operand, so
        the per-chunk value never recompiles."""
        if self.min_lr is None:
            return self.lr
        frac = min(1.0, words_done / max(1, total_words))
        return max(self.min_lr, self.lr * (1.0 - frac))

    def _fit_native(self, path: str, rng) -> Optional["Word2Vec"]:
        """Train over the native concurrent text front: N C++ threads
        tokenize/encode/subsample/window/negative-sample line-chunks in
        parallel (native/dl4jtpu_native.cpp) while this thread runs the
        jitted device step — the reference's Hogwild host concurrency with
        a single-program device side. Like the reference's threaded
        trainer, batch arrival order is nondeterministic run-to-run; pass
        ``native_front=False`` to fit() for the deterministic Python
        stream. None = native pass unavailable (caller falls back)."""
        from deeplearning4j_tpu.nlp.native_text import (NativeSkipGramStream,
                                                        native_word_counts)

        counts = native_word_counts(path, self.workers)
        if counts is None:
            return None
        self.vocab.fit_from_counts(counts)
        V, D = len(self.vocab), self.vector_size
        if V == 0:
            raise ValueError("empty vocabulary")
        self.W = ((rng.random((V, D), np.float32) - 0.5) / D)
        self.C = np.zeros((V, D), np.float32)
        keep = (self.vocab.subsample_keep_probs(self.subsample)
                if self.subsample > 0 else None)
        W, C = jnp.asarray(self.W), jnp.asarray(self.C)
        if self.hs:
            freqs = [self.vocab.counts[w_] for w_ in self.vocab.words]
            codes_m, points_m, mask_m = (jnp.asarray(a)
                                         for a in build_huffman(freqs))
            C = jnp.zeros((max(V - 1, 1), D), jnp.float32)
            accW, accT = jnp.zeros_like(W), jnp.zeros_like(C)
            probs, negative = None, 0
        else:
            probs = self.vocab.unigram_table_probs()
            aprob, aalias = build_alias_table(probs)
            aprob, aalias = jnp.asarray(aprob), jnp.asarray(aalias)
            key = jax.random.PRNGKey(self.seed)
            tail_sampler = NegativeSampler(probs)
        # the C++ side ships ONLY (center, context) pairs — negatives are
        # sampled on-device from the alias table inside the scanned step,
        # and pair ids ride as uint16 when the vocab fits: 14x fewer
        # host->device bytes than staging int32 (center, context, negs[K]),
        # the measured bottleneck under a tunneled PJRT client
        total_words = self.vocab._total * self.epochs
        stream = NativeSkipGramStream(
            path, self.vocab.words, None, keep, self.window, 0,
            self.batch_size, seed=self.seed, n_threads=self.workers)
        # S batches ride each dispatch via the scanned step — per-batch
        # launch latency amortizes S-fold; the tail shorter than S runs on
        # the per-batch step with host-sampled negatives. S=32 measured
        # best on-chip (S=16: 528k, S=32: 619k, S=64+: tail-dominated)
        S, B = 32, self.batch_size
        pair_dt = np.uint16 if V <= 0xFFFF else np.int32
        cs = np.empty((S, B), pair_dt)
        xs = np.empty((S, B), pair_dt)
        try:
            for epoch in range(self.epochs):
                if epoch:
                    stream.reset()
                k = 0
                for c, x, _ in stream:
                    cs[k], xs[k] = c, x
                    k += 1
                    if k == S:
                        # PRODUCER-side schedule, like the reference: the
                        # original word2vec decays alpha by words READ per
                        # thread, and our C++ workers publish exactly that
                        # counter. It runs ahead of applied updates by the
                        # worker-buffer/queue lead (bounded; negligible on
                        # real corpora, up to an epoch on tiny ones)
                        lr_now = self._lr_at(stream.words_seen, total_words)
                        if self.hs:
                            W, C, accW, accT, _ = _sg_hs_steps(
                                W, C, accW, accT, jnp.asarray(cs),
                                jnp.asarray(xs), codes_m, points_m, mask_m,
                                lr=lr_now)
                        else:
                            key, sub = jax.random.split(key)
                            W, C, _ = _sg_neg_steps_devneg(
                                W, C, sub, jnp.asarray(cs), jnp.asarray(xs),
                                aprob, aalias, lr=lr_now, k=self.negative)
                        k = 0
                rng_tail = np.random.default_rng(self.seed + 31 * epoch)
                lr_now = self._lr_at(stream.words_seen, total_words)
                for i in range(k):
                    ci = cs[i].astype(np.int32)
                    xi = xs[i].astype(np.int32)
                    if self.hs:
                        W, C, accW, accT, _ = _sg_hs_step(
                            W, C, accW, accT, jnp.asarray(ci),
                            jnp.asarray(xi), codes_m, points_m, mask_m,
                            lr=lr_now)
                    else:
                        negs = tail_sampler.sample(rng_tail,
                                                   (B, self.negative))
                        W, C, _ = _sg_neg_step(W, C, jnp.asarray(ci),
                                               jnp.asarray(xi),
                                               jnp.asarray(negs),
                                               lr=lr_now)
        finally:
            stream.close()
        self.W, self.C = np.asarray(W), np.asarray(C)
        return self

    def fit(self, corpus, chunk_sentences: int = 4096,
            native_front: Optional[bool] = None) -> "Word2Vec":
        """Fit on a sentence corpus.

        **Determinism note:** even with a fixed ``seed``, eligible runs
        (file-backed ASCII LineSentenceIterator corpus, skip-gram config,
        default tokenizer, loadable native lib) AUTO-ROUTE to the native
        concurrent front, whose multi-threaded batch arrival order is
        NONDETERMINISTIC run-to-run — exactly like the reference's Hogwild
        workers, the same seed no longer reproduces embeddings
        bit-for-bit. Pass ``native_front=False`` to force the
        deterministic (seed-reproducible) Python stream, or ``True`` to
        require the concurrent native path.

        Two streaming passes per epoch over ``corpus`` (r4): pass 1
        builds the vocabulary sentence-by-sentence; each epoch then streams
        sentences again, encoding + subsampling on the fly and training in
        chunks of ``chunk_sentences`` — the corpus itself is never
        materialized, so file-backed SentenceIterators (nlp.corpus) train
        at any size. Batch shapes are fixed, so every chunk reuses the one
        compiled XLA step.

        ``native_front``: None (default) auto-selects the native concurrent
        host pipeline when the corpus is a plain file-backed
        LineSentenceIterator, the config is skip-gram (neg-sampling or HS)
        with the default tokenizer, and the native lib loads; True requires
        it (raising otherwise); False forces the deterministic Python
        stream."""
        rng = np.random.default_rng(self.seed)
        if self.hs and self.cbow:
            raise ValueError("cbow=True with hs=True is not supported; use "
                             "negative sampling for CBOW")
        path = (None if native_front is False
                else self._native_corpus_path(corpus))
        if native_front is True and path is None:
            raise ValueError(
                "native_front=True requires a file-backed "
                "LineSentenceIterator (no preprocessor, utf-8), a skip-gram "
                "config with the default tokenizer, and a loadable native "
                "library")
        if (native_front is None and path is not None
                and not self._ascii_sample(path)):
            # auto mode only routes ASCII corpora natively: tokenization
            # of non-ASCII text diverges from the Python front (see
            # _ascii_sample); native_front=True forces it
            path = None
        if path is not None:
            out = self._fit_native(path, rng)
            if out is not None:
                return out
        self.vocab.fit(self._iter_token_sents(corpus))
        V, D = len(self.vocab), self.vector_size
        if V == 0:
            raise ValueError("empty vocabulary")
        self.W = ((rng.random((V, D), np.float32) - 0.5) / D)
        self.C = np.zeros((V, D), np.float32)
        sampler = NegativeSampler(self.vocab.unigram_table_probs())
        keep = (self.vocab.subsample_keep_probs(self.subsample)
                if self.subsample > 0 else None)

        W, C = jnp.asarray(self.W), jnp.asarray(self.C)
        huffman = None
        accW = accT = None
        if self.hs and not self.cbow:
            # per-fit: the tree depends on THIS corpus's vocabulary
            freqs = [self.vocab.counts[w_] for w_ in self.vocab.words]
            huffman = tuple(jnp.asarray(a) for a in build_huffman(freqs))
            C = jnp.asarray(np.zeros((max(V - 1, 1), D), np.float32))
            accW = jnp.zeros_like(W)
            accT = jnp.zeros_like(C)

        def train_chunk(encoded, lr):
            nonlocal W, C, accW, accT
            if self.cbow:
                centers, ctxs = cbow_windows(encoded, self.window)
                if len(centers) == 0:
                    return
                order = rng.permutation(len(centers))
                centers, ctxs = centers[order], ctxs[order]
                B = min(self.batch_size, len(centers))
                for s in range(0, (len(centers) // B) * B, B):
                    negs = sampler.sample(rng, (B, self.negative))
                    W, C, _ = _cbow_neg_step(W, C, jnp.asarray(ctxs[s:s + B]),
                                             jnp.asarray(centers[s:s + B]),
                                             jnp.asarray(negs), lr=lr)
            elif self.hs:
                pairs = self._pairs(encoded, rng)
                if len(pairs) == 0:
                    return
                codes_m, points_m, mask_m = huffman
                pairs = pairs[rng.permutation(len(pairs))]
                B = min(self.batch_size, len(pairs))
                for s in range(0, (len(pairs) // B) * B, B):
                    batch = pairs[s:s + B]
                    W, C, accW, accT, _ = _sg_hs_step(
                        W, C, accW, accT, jnp.asarray(batch[:, 0]),
                        jnp.asarray(batch[:, 1]),
                        codes_m, points_m, mask_m, lr=lr)
            else:
                pairs = self._pairs(encoded, rng)
                if len(pairs) == 0:
                    return
                pairs = pairs[rng.permutation(len(pairs))]
                # batches reuse one compiled step shape; negatives for the
                # WHOLE chunk come from one sampler call (r5 — per-batch
                # searchsorted calls were a measured host hot spot)
                B = min(self.batch_size, len(pairs))
                nb = len(pairs) // B
                negs_all = sampler.sample(rng, (nb, B, self.negative))
                for k in range(nb):
                    s = k * B
                    batch = pairs[s:s + B]
                    W, C, _ = _sg_neg_step(W, C, jnp.asarray(batch[:, 0]),
                                           jnp.asarray(batch[:, 1]),
                                           jnp.asarray(negs_all[k]),
                                           lr=lr)

        total_words = self.vocab._total * self.epochs
        words_done = 0
        for epoch in range(self.epochs):
            if hasattr(corpus, "reset"):
                corpus.reset()
            buf = []
            seen = 0
            for toks in self._iter_token_sents(corpus):
                seen += 1
                enc = self.vocab.encode(toks)
                words_done += len(enc)
                if keep is not None and len(enc):
                    enc = enc[rng.random(len(enc)) < keep[enc]]
                if len(enc):
                    buf.append(enc)
                if len(buf) >= chunk_sentences:
                    train_chunk(buf, self._lr_at(words_done, total_words))
                    buf = []
            if buf:
                train_chunk(buf, self._lr_at(words_done, total_words))
            if seen == 0 and epoch == 0:
                # a single-pass generator was exhausted by the vocabulary
                # pass — fail loud instead of returning random embeddings
                raise ValueError(
                    "corpus yielded no sentences on the training pass; "
                    "fit() makes one vocabulary pass plus one pass per "
                    "epoch, so pass a re-iterable (list, str, or a "
                    "nlp.corpus SentenceIterator), not a generator")
        self.W, self.C = np.asarray(W), np.asarray(C)
        return self

    # ----------------------------------------------------------------- query
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.W[i]

    def similarity(self, a: str, b: str) -> float:
        return cosine_similarity(self.get_word_vector(a), self.get_word_vector(b))

    def words_nearest(self, word=None, top: int = 10, positive=None,
                      negative=None) -> List[str]:
        """wordsNearest — cosine neighbors of a word, or of an analogy
        query (reference: wordsNearest(positive, negative, top), the
        king - man + woman form)."""
        from deeplearning4j_tpu.nlp.vocab import nearest_neighbors

        return nearest_neighbors(self.vocab.words, self.vocab.index, self.W,
                                 word=word, top=top, positive=positive,
                                 negative=negative)

    # ----------------------------------------------------------------- serde
    def save(self, path: str):
        np.savez(path, W=self.W, C=self.C,
                 words=np.asarray(self.vocab.words, dtype=object))

    @classmethod
    def load(cls, path: str) -> "Word2Vec":
        data = np.load(path if path.endswith(".npz") else path + ".npz",
                       allow_pickle=True)
        m = cls(vector_size=data["W"].shape[1])
        m.W, m.C = data["W"], data["C"]
        words = [str(w) for w in data["words"]]
        m.vocab.words = words
        m.vocab.index = {w: i for i, w in enumerate(words)}
        return m
