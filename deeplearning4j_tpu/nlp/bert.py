"""BERT text front: WordPiece tokenization + batch iterator.

Reference analog: org.deeplearning4j.text.tokenization.tokenizer.
BertWordPieceTokenizer (greedy longest-match-first subword split against a
BERT vocab, "##" continuation prefix, [UNK] fallback) and
org.deeplearning4j.iterator.BertIterator (sentence provider -> padded
[ids, mask] feature arrays for SEQ_CLASSIFICATION, or masked-LM batches
for UNSUPERVISED pretraining: 15% of positions selected, 80% -> [MASK],
10% -> random token, 10% kept, with a label mask over just the selected
positions).

TPU-first: batches come out as fixed-shape int32/float32 arrays (pad to
``max_len`` AND to ``batch_size``), so the consuming jitted train step
compiles once. Masked-LM labels are int ids with a labels_mask over the
selected positions; ``BertIterator.one_hot`` converts a batch for the
mcxent output tier (practical for small/custom vocabularies).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np


class BertWordPieceTokenizer:
    """Greedy longest-match-first WordPiece (BertWordPieceTokenizer).

    ``vocab``: iterable of wordpieces (continuations prefixed "##") or a
    path to a BERT vocab.txt (one token per line). Basic tokenization
    (lowercase + punctuation split) mirrors the reference's
    BertWordPiecePreProcessor defaults."""

    def __init__(self, vocab, lower_case: bool = True,
                 unk_token: str = "[UNK]", max_chars_per_word: int = 100):
        if isinstance(vocab, str):
            with open(vocab, "r", encoding="utf-8") as f:
                vocab = [line.rstrip("\n") for line in f if line.rstrip("\n")]
        self.vocab = list(vocab)
        self.index = {w: i for i, w in enumerate(self.vocab)}
        self.lower_case = lower_case
        self.unk_token = unk_token
        self.max_chars = max_chars_per_word

    # ------------------------------------------------------------ tokenize
    def _basic_split(self, text: str) -> List[str]:
        if self.lower_case:
            text = text.lower()
        out, word = [], []
        for ch in text:
            if ch.isspace():
                if word:
                    out.append("".join(word))
                    word = []
            elif not (ch.isalnum() or ch == "_"):
                if word:
                    out.append("".join(word))
                    word = []
                out.append(ch)               # punctuation is its own token
            else:
                word.append(ch)
        if word:
            out.append("".join(word))
        return out

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.index:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]      # whole word becomes [UNK]
            pieces.append(cur)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out = []
        for word in self._basic_split(text):
            out.extend(self._wordpiece(word))
        return out

    create = tokenize  # reference naming parity with the other factories

    def encode(self, text: str) -> List[int]:
        unk = self.index.get(self.unk_token, 0)
        return [self.index.get(t, unk) for t in self.tokenize(text)]


class BertIterator:
    """Sentence provider -> fixed-shape BERT batches (BertIterator).

    ``task``: "seq_classification" (features = [ids, mask]; labels =
    one-hot from the provider's labels) or "unsupervised" (masked LM:
    labels are the ORIGINAL ids, labels_mask marks the selected
    positions). Batches always pad/truncate to ``max_len`` — fixed shapes,
    one XLA compile.

    ``sentences``: iterable of str (unsupervised) or (str, label) pairs
    (classification); re-iterated per epoch via reset().

    ``pad_minibatches`` (default True, the reference's padMinibatches):
    the trailing partial batch pads to ``batch_size`` with all-zero-mask
    rows (zero label vectors / zero labels_mask — they contribute nothing
    to the loss), so EVERY batch has the same shape and the consuming
    jitted step compiles once.

    Masked-LM labels are emitted as int32 ids (one-hot [B, L, V] for a
    real 30k vocab is gigabytes); ``one_hot(ds)`` converts a batch for
    the mcxent output tier directly — practical for the small/custom
    vocabs this front targets."""

    MASK_TOKEN = "[MASK]"
    CLS_TOKEN = "[CLS]"
    SEP_TOKEN = "[SEP]"
    PAD_TOKEN = "[PAD]"

    def __init__(self, tokenizer: BertWordPieceTokenizer, sentences,
                 batch_size: int = 32, max_len: int = 128,
                 task: str = "seq_classification",
                 labels: Optional[Sequence[str]] = None,
                 mask_prob: float = 0.15, seed: int = 0,
                 append_special: bool = True, pad_minibatches: bool = True):
        if task not in ("seq_classification", "unsupervised"):
            raise ValueError(f"unknown task {task!r}")
        self.tok = tokenizer
        self.sentences = sentences
        self.batch_size = batch_size
        self.max_len = max_len
        self.task = task
        self.mask_prob = mask_prob
        self.pad_minibatches = pad_minibatches
        self._rng = np.random.default_rng(seed)
        self._seed = seed
        idx = tokenizer.index
        self.pad_id = idx.get(self.PAD_TOKEN, 0)
        self.mask_id = idx.get(self.MASK_TOKEN)
        self.cls_id = idx.get(self.CLS_TOKEN)
        self.sep_id = idx.get(self.SEP_TOKEN)
        if task == "unsupervised" and self.mask_id is None:
            raise ValueError("unsupervised (masked LM) task needs a "
                             "[MASK] token in the vocabulary")
        if append_special and (self.cls_id is None) != (self.sep_id is None):
            raise ValueError(
                "append_special needs [CLS] and [SEP] together in the "
                "vocabulary (or neither); got exactly one of them")
        # one place decides the [CLS] ... [SEP] framing
        self._frame = bool(append_special and self.cls_id is not None)
        self.labels = list(labels) if labels is not None else None

    def reset(self):
        if hasattr(self.sentences, "reset"):
            self.sentences.reset()
        self._rng = np.random.default_rng(self._seed)

    # ------------------------------------------------------------- batching
    def _encode_one(self, text: str) -> List[int]:
        ids = self.tok.encode(text)
        ids = ids[:self.max_len - (2 if self._frame else 0)]
        if self._frame:
            ids = [self.cls_id] + ids + [self.sep_id]
        return ids

    def _emit(self, rows, labs):
        # pad the trailing partial batch to batch_size with zero-mask rows
        # so every batch has ONE shape (padMinibatches); padded rows carry
        # zero label vectors / zero labels_mask — no loss contribution
        n_real = len(rows)
        B = self.batch_size if self.pad_minibatches else n_real
        L = self.max_len
        ids = np.full((B, L), self.pad_id, np.int32)
        mask = np.zeros((B, L), np.float32)
        for i, r in enumerate(rows):
            ids[i, :len(r)] = r
            mask[i, :len(r)] = 1.0
        from deeplearning4j_tpu.datasets.dataset import DataSet

        if self.task == "seq_classification":
            if self.labels is None:
                raise ValueError("seq_classification needs the label list")
            y = np.zeros((B, len(self.labels)), np.float32)
            for i, l in enumerate(labs):
                y[i, self.labels.index(l)] = 1.0
            return DataSet(ids, y, mask)

        # masked LM: select ~mask_prob of REAL (non-special) positions;
        # 80% -> [MASK], 10% -> random vocab id, 10% unchanged
        V = len(self.tok.vocab)
        labels = ids.copy()
        lmask = np.zeros((B, L), np.float32)
        corrupted = ids.copy()
        edge = 1 if self._frame else 0
        for i, r in enumerate(rows):
            cand = np.arange(edge, len(r) - edge)
            if len(cand) == 0 or self.mask_prob <= 0.0:
                continue
            n_sel = max(1, int(round(self.mask_prob * len(cand))))
            sel = self._rng.choice(cand, size=min(n_sel, len(cand)),
                                   replace=False)
            lmask[i, sel] = 1.0
            for j in sel:
                roll = self._rng.random()
                if roll < 0.8:
                    corrupted[i, j] = self.mask_id
                elif roll < 0.9:
                    corrupted[i, j] = int(self._rng.integers(0, V))
                # else: keep the original token
        return DataSet(corrupted, labels, mask, lmask)

    def one_hot(self, ds):
        """Masked-LM batch -> (features, one-hot labels [B, L, V],
        labels_mask) ready for an mcxent RnnOutputLayer head. Intended for
        the small/custom vocabularies this front targets (a 30k vocab
        one-hot is gigabytes — use a sampled/softmax-sparse head there)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        V = len(self.tok.vocab)
        y = np.eye(V, dtype=np.float32)[ds.labels]
        return DataSet(ds.features, y, ds.features_mask, ds.labels_mask)

    def __iter__(self):
        rows, labs = [], []
        yielded = 0
        for item in self.sentences:
            if isinstance(item, tuple):
                text, lab = item
            elif hasattr(item, "content"):
                text, lab = item.content, item.label
            else:
                text, lab = item, None
            rows.append(self._encode_one(text))
            labs.append(lab)
            if len(rows) == self.batch_size:
                # arm the exhaustion guard BEFORE yielding: a consumer that
                # breaks out mid-epoch (the steps-bounded pattern) closes
                # this generator at the yield and the epilogue never runs
                self._ever_yielded = True
                yielded += 1
                yield self._emit(rows, labs)
                rows, labs = [], []
        if rows:
            self._ever_yielded = True
            yielded += 1
            yield self._emit(rows, labs)
        if yielded == 0 and getattr(self, "_ever_yielded", False):
            # a single-pass generator was exhausted on an earlier epoch —
            # fail loud instead of letting a multi-epoch loop spin forever
            raise ValueError(
                "sentence provider yielded nothing after a non-empty "
                "earlier pass; pass a list or a resettable iterator "
                "(nlp.corpus) for multi-epoch training, not a generator")
