"""Vocabulary cache.

Reference analog: org.deeplearning4j.models.word2vec.wordstore.inmemory.
AbstractCache (VocabCache interface): word frequencies, min-count pruning,
index assignment, and the unigram^0.75 negative-sampling table.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable, List, Optional

import numpy as np


class VocabCache:
    def __init__(self, min_count: int = 1):
        self.min_count = min_count
        self.counts: Counter = Counter()
        self.index: dict[str, int] = {}
        self.words: List[str] = []
        self._total = 0

    # ------------------------------------------------------------------ build
    def fit(self, sentences: Iterable[List[str]]) -> "VocabCache":
        for s in sentences:
            self.counts.update(s)
        kept = [(w, c) for w, c in self.counts.most_common()
                if c >= self.min_count]
        self.words = [w for w, _ in kept]
        self.index = {w: i for i, w in enumerate(self.words)}
        self._total = sum(c for _, c in kept)
        return self

    def __len__(self):
        return len(self.words)

    def __contains__(self, w):
        return w in self.index

    def word_frequency(self, w: str) -> int:
        return self.counts.get(w, 0)

    def index_of(self, w: str) -> int:
        return self.index.get(w, -1)

    def encode(self, tokens: List[str]) -> np.ndarray:
        """Token list -> index array, dropping OOV (reference drops unknowns)."""
        return np.asarray([self.index[t] for t in tokens if t in self.index],
                          np.int32)

    # --------------------------------------------------- negative sampling
    def unigram_table_probs(self, power: float = 0.75) -> np.ndarray:
        """P(w) ∝ count^0.75 — the word2vec negative-sampling distribution."""
        freqs = np.asarray([self.counts[w] for w in self.words], np.float64)
        p = freqs ** power
        return (p / p.sum()).astype(np.float32)

    def subsample_keep_probs(self, t: float = 1e-3) -> np.ndarray:
        """Mikolov frequent-word subsampling keep probability."""
        f = np.asarray([self.counts[w] for w in self.words], np.float64)
        f = f / max(self._total, 1)
        keep = np.minimum(1.0, np.sqrt(t / np.maximum(f, 1e-12)) + t / np.maximum(f, 1e-12))
        return keep.astype(np.float32)
