"""Vocabulary cache.

Reference analog: org.deeplearning4j.models.word2vec.wordstore.inmemory.
AbstractCache (VocabCache interface): word frequencies, min-count pruning,
index assignment, and the unigram^0.75 negative-sampling table.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable, List, Optional

import numpy as np


class VocabCache:
    def __init__(self, min_count: int = 1):
        self.min_count = min_count
        self.counts: Counter = Counter()
        self.index: dict[str, int] = {}
        self.words: List[str] = []
        self._total = 0

    # ------------------------------------------------------------------ build
    def fit(self, sentences: Iterable[List[str]]) -> "VocabCache":
        for s in sentences:
            self.counts.update(s)
        kept = [(w, c) for w, c in self.counts.most_common()
                if c >= self.min_count]
        self.words = [w for w, _ in kept]
        self.index = {w: i for i, w in enumerate(self.words)}
        self._total = sum(c for _, c in kept)
        return self

    def fit_from_counts(self, counts) -> "VocabCache":
        """Build from a precomputed word->count mapping (the native
        concurrent counting pass, nlp.native_text.native_word_counts).
        Ties order by word so the index assignment is deterministic even
        though concurrent counting loses first-seen order."""
        self.counts = Counter(counts)
        kept = sorted(((w, c) for w, c in self.counts.items()
                       if c >= self.min_count),
                      key=lambda wc: (-wc[1], wc[0]))
        self.words = [w for w, _ in kept]
        self.index = {w: i for i, w in enumerate(self.words)}
        self._total = sum(c for _, c in kept)
        return self

    def __len__(self):
        return len(self.words)

    def __contains__(self, w):
        return w in self.index

    def word_frequency(self, w: str) -> int:
        return self.counts.get(w, 0)

    def index_of(self, w: str) -> int:
        return self.index.get(w, -1)

    def encode(self, tokens: List[str]) -> np.ndarray:
        """Token list -> index array, dropping OOV (reference drops unknowns)."""
        return np.asarray([self.index[t] for t in tokens if t in self.index],
                          np.int32)

    # --------------------------------------------------- negative sampling
    def unigram_table_probs(self, power: float = 0.75) -> np.ndarray:
        """P(w) ∝ count^0.75 — the word2vec negative-sampling distribution."""
        freqs = np.asarray([self.counts[w] for w in self.words], np.float64)
        p = freqs ** power
        return (p / p.sum()).astype(np.float32)

    def subsample_keep_probs(self, t: float = 1e-3) -> np.ndarray:
        """Mikolov frequent-word subsampling keep probability."""
        f = np.asarray([self.counts[w] for w in self.words], np.float64)
        f = f / max(self._total, 1)
        keep = np.minimum(1.0, np.sqrt(t / np.maximum(f, 1e-12)) + t / np.maximum(f, 1e-12))
        return keep.astype(np.float32)


def build_alias_table(probs: np.ndarray):
    """Vose alias table (prob [V] f32, alias [V] i32) for O(1) categorical
    sampling: draw k uniform, return k if u < prob[k] else alias[k].
    Device-resident twin of the native AliasTable — the scanned Word2Vec
    step samples negatives ON the TPU so the host ships only (center,
    context) pairs."""
    p = np.asarray(probs, np.float64)
    n = len(p)
    scaled = p / p.sum() * n
    alias = np.zeros(n, np.int32)
    prob = np.ones(n, np.float64)
    small = [i for i in range(n) if scaled[i] < 1.0]
    large = [i for i in range(n) if scaled[i] >= 1.0]
    while small and large:
        s, l = small.pop(), large.pop()
        prob[s] = scaled[s]
        alias[s] = l
        scaled[l] += scaled[s] - 1.0
        (small if scaled[l] < 1.0 else large).append(l)
    return prob.astype(np.float32), alias


class NegativeSampler:
    """Precomputed-CDF sampler for the unigram^0.75 distribution.

    ``rng.choice(V, p=probs)`` rebuilds an O(V) CDF per call; for real
    vocabularies that would dominate each training batch. Build the CDF once
    and sample with searchsorted.
    """

    def __init__(self, probs: np.ndarray):
        self._cdf = np.cumsum(np.asarray(probs, np.float64))
        self._cdf[-1] = 1.0

    def sample(self, rng, size) -> np.ndarray:
        return np.searchsorted(self._cdf, rng.random(size)).astype(np.int32)


def nearest_neighbors(words: List[str], index: dict, W: np.ndarray,
                      word: Optional[str] = None, top: int = 10,
                      positive=None, negative=None) -> List[str]:
    """Shared wordsNearest engine (Word2Vec/GloVe; reference:
    wordsNearest(word | positive, negative, top)): cosine neighbors of a
    word or of a mean(positive) - mean(negative) analogy query, excluding
    the query words. [] on any OOV query word."""
    positive = list(positive or ([] if word is None else [word]))
    negative = list(negative or [])
    if word is not None and positive and word not in positive:
        positive = [word] + positive
    if not positive:      # negatives alone have no defined query direction
        return []
    idx = [index.get(w, -1) for w in positive + negative]
    if any(i < 0 for i in idx):
        return []
    Wn = W / np.maximum(np.linalg.norm(W, axis=1, keepdims=True), 1e-12)
    n_pos = len(positive)
    q = Wn[idx[:n_pos]].mean(axis=0)
    if negative:
        q = q - Wn[idx[n_pos:]].mean(axis=0)
    sims = Wn @ (q / max(np.linalg.norm(q), 1e-12))
    exclude = set(idx)
    return [words[j] for j in np.argsort(-sims) if j not in exclude][:top]


def cosine_similarity(a: Optional[np.ndarray], b: Optional[np.ndarray]) -> float:
    """Shared cosine helper (Word2Vec/Glove/ParagraphVectors .similarity)."""
    if a is None or b is None:
        return float("nan")
    denom = (np.linalg.norm(a) * np.linalg.norm(b)) or 1e-12
    return float(a @ b / denom)
