"""Vocabulary cache.

Reference analog: org.deeplearning4j.models.word2vec.wordstore.inmemory.
AbstractCache (VocabCache interface): word frequencies, min-count pruning,
index assignment, and the unigram^0.75 negative-sampling table.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable, List, Optional

import numpy as np


class VocabCache:
    def __init__(self, min_count: int = 1):
        self.min_count = min_count
        self.counts: Counter = Counter()
        self.index: dict[str, int] = {}
        self.words: List[str] = []
        self._total = 0

    # ------------------------------------------------------------------ build
    def fit(self, sentences: Iterable[List[str]]) -> "VocabCache":
        for s in sentences:
            self.counts.update(s)
        kept = [(w, c) for w, c in self.counts.most_common()
                if c >= self.min_count]
        self.words = [w for w, _ in kept]
        self.index = {w: i for i, w in enumerate(self.words)}
        self._total = sum(c for _, c in kept)
        return self

    def __len__(self):
        return len(self.words)

    def __contains__(self, w):
        return w in self.index

    def word_frequency(self, w: str) -> int:
        return self.counts.get(w, 0)

    def index_of(self, w: str) -> int:
        return self.index.get(w, -1)

    def encode(self, tokens: List[str]) -> np.ndarray:
        """Token list -> index array, dropping OOV (reference drops unknowns)."""
        return np.asarray([self.index[t] for t in tokens if t in self.index],
                          np.int32)

    # --------------------------------------------------- negative sampling
    def unigram_table_probs(self, power: float = 0.75) -> np.ndarray:
        """P(w) ∝ count^0.75 — the word2vec negative-sampling distribution."""
        freqs = np.asarray([self.counts[w] for w in self.words], np.float64)
        p = freqs ** power
        return (p / p.sum()).astype(np.float32)

    def subsample_keep_probs(self, t: float = 1e-3) -> np.ndarray:
        """Mikolov frequent-word subsampling keep probability."""
        f = np.asarray([self.counts[w] for w in self.words], np.float64)
        f = f / max(self._total, 1)
        keep = np.minimum(1.0, np.sqrt(t / np.maximum(f, 1e-12)) + t / np.maximum(f, 1e-12))
        return keep.astype(np.float32)


class NegativeSampler:
    """Precomputed-CDF sampler for the unigram^0.75 distribution.

    ``rng.choice(V, p=probs)`` rebuilds an O(V) CDF per call; for real
    vocabularies that would dominate each training batch. Build the CDF once
    and sample with searchsorted.
    """

    def __init__(self, probs: np.ndarray):
        self._cdf = np.cumsum(np.asarray(probs, np.float64))
        self._cdf[-1] = 1.0

    def sample(self, rng, size) -> np.ndarray:
        return np.searchsorted(self._cdf, rng.random(size)).astype(np.int32)


def cosine_similarity(a: Optional[np.ndarray], b: Optional[np.ndarray]) -> float:
    """Shared cosine helper (Word2Vec/Glove/ParagraphVectors .similarity)."""
    if a is None or b is None:
        return float("nan")
    denom = (np.linalg.norm(a) * np.linalg.norm(b)) or 1e-12
    return float(a @ b / denom)
