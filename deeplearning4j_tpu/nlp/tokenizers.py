"""Tokenizer factories.

Reference analog: org.deeplearning4j.text.tokenization.tokenizerfactory.
{DefaultTokenizerFactory, NGramTokenizerFactory} and the TokenPreProcess
chain (CommonPreprocessor lowercases + strips punctuation).
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional


class CommonPreprocessor:
    """Lowercase + strip punctuation (org.deeplearning4j...CommonPreprocessor)."""

    _punct = re.compile(r"[^\w\s]", re.UNICODE)

    def __call__(self, token: str) -> str:
        return self._punct.sub("", token.lower())


class DefaultTokenizerFactory:
    """Whitespace/word tokenizer (DefaultTokenizerFactory + DefaultTokenizer)."""

    def __init__(self, preprocessor: Optional[Callable[[str], str]] = None):
        self.preprocessor = preprocessor

    def tokenize(self, text: str) -> List[str]:
        if type(self.preprocessor) is CommonPreprocessor:
            # line-level fast path (r5): one lowercase + one regex pass
            # over the whole line, then split — equivalent to the
            # per-token chain ([^\w\s] never touches whitespace, and
            # punctuation-only tokens vanish either way) but ~6x faster
            # on the streaming Word2Vec front, where tokenize dominated
            # the host profile
            return self.preprocessor(text).split()
        toks = text.split()
        if self.preprocessor:
            toks = [self.preprocessor(t) for t in toks]
        return [t for t in toks if t]

    create = tokenize  # reference naming: factory.create(text).getTokens()


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """Word n-grams (NGramTokenizerFactory)."""

    def __init__(self, n_min: int = 1, n_max: int = 2,
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self.n_min, self.n_max = n_min, n_max

    def tokenize(self, text: str) -> List[str]:
        words = super().tokenize(text)
        out = []
        for n in range(self.n_min, self.n_max + 1):
            out.extend(" ".join(words[i:i + n])
                       for i in range(len(words) - n + 1))
        return out

    create = tokenize
