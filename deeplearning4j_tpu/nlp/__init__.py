"""NLP tooling.

Reference analog: deeplearning4j-nlp-parent (SURVEY.md §2.3) —
org.deeplearning4j.text.tokenization.** (tokenizers), org.deeplearning4j.
models.word2vec.** (Word2Vec, VocabCache), models.glove.Glove,
models.paragraphvectors.ParagraphVectors. TPU-first: corpus scanning and
pair generation stay host-side; the embedding-update inner loop is a single
jitted XLA program over batched (center, context, negatives) arrays instead
of the reference's per-pair Hogwild threads.
"""

from deeplearning4j_tpu.nlp.bert import BertIterator, BertWordPieceTokenizer
from deeplearning4j_tpu.nlp.corpus import (
    BasicLineIterator, CollectionSentenceIterator, FileLabelAwareIterator,
    FileSentenceIterator, LabelledDocument, LineSentenceIterator,
    PhraseDetector, SentencePreProcessor,
)
from deeplearning4j_tpu.nlp.tokenizers import (
    DefaultTokenizerFactory, NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.serializer import (
    load_word2vec, read_word_vectors, save_word2vec, write_word_vectors,
)

__all__ = ["DefaultTokenizerFactory", "NGramTokenizerFactory", "VocabCache",
           "Word2Vec", "Glove", "ParagraphVectors",
           "BasicLineIterator", "CollectionSentenceIterator",
           "FileLabelAwareIterator", "FileSentenceIterator",
           "LabelledDocument", "LineSentenceIterator", "PhraseDetector",
           "SentencePreProcessor", "BertIterator", "BertWordPieceTokenizer",
           "write_word_vectors", "read_word_vectors", "save_word2vec",
           "load_word2vec"]
