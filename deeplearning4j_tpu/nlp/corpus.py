"""Streaming corpus front for the NLP models.

Reference analog: org.deeplearning4j.text.sentenceiterator.
{SentenceIterator, BasicLineIterator, LineSentenceIterator,
FileSentenceIterator, CollectionSentenceIterator, SentencePreProcessor} and
org.deeplearning4j.text.documentiterator.FileLabelAwareIterator — the
surface that makes Word2Vec/ParagraphVectors usable on real corpora: text
streams from FILES, sentence by sentence, with a reset() for multi-epoch
passes; nothing is materialized beyond the current line. Phrase detection
is the word2phrase algorithm of Mikolov et al. (the reference exposes it as
the n-gram/phrase pipeline in deeplearning4j-nlp).

TPU-relevance: the host-side corpus stream is the input pipeline for the
jitted embedding steps in word2vec.py — iterators here feed the chunked
pair/window generators so vocabulary building and training are one pass
each over arbitrarily large files.
"""

from __future__ import annotations

import os
from collections import Counter
from typing import Callable, Iterable, Iterator, List, Optional


class SentencePreProcessor:
    """Lowercase pre-processor (sentenceiterator.SentencePreProcessor)."""

    def __call__(self, sentence: str) -> str:
        return sentence.lower()


class BaseSentenceIterator:
    """Iterable-of-strings with reset() — the SentenceIterator contract.

    Subclasses implement _lines(); the optional ``preprocessor`` maps each
    raw sentence string (the reference's setPreProcessor)."""

    def __init__(self, preprocessor: Optional[Callable[[str], str]] = None):
        self.preprocessor = preprocessor

    def _lines(self) -> Iterator[str]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        for line in self._lines():
            line = line.strip()
            if not line:
                continue
            yield self.preprocessor(line) if self.preprocessor else line

    def reset(self):
        """Iterators here are pull-based generators; reset is a no-op hook
        kept for the reference contract (file handles reopen per pass)."""


class LineSentenceIterator(BaseSentenceIterator):
    """One sentence per line from a single file (LineSentenceIterator /
    BasicLineIterator). The file is re-opened on every pass, so multi-epoch
    training never holds the corpus in memory."""

    def __init__(self, path: str,
                 preprocessor: Optional[Callable[[str], str]] = None,
                 encoding: str = "utf-8"):
        super().__init__(preprocessor)
        self.path = path
        self.encoding = encoding

    def _lines(self) -> Iterator[str]:
        with open(self.path, "r", encoding=self.encoding,
                  errors="replace") as f:
            yield from f


BasicLineIterator = LineSentenceIterator


class FileSentenceIterator(BaseSentenceIterator):
    """Every file under a directory, one sentence per line
    (FileSentenceIterator). Files stream in sorted order for
    reproducibility."""

    def __init__(self, directory: str,
                 preprocessor: Optional[Callable[[str], str]] = None,
                 encoding: str = "utf-8"):
        super().__init__(preprocessor)
        self.directory = directory
        self.encoding = encoding

    def _paths(self) -> List[str]:
        out = []
        for root, _, files in os.walk(self.directory):
            out.extend(os.path.join(root, f) for f in files)
        return sorted(out)

    def _lines(self) -> Iterator[str]:
        for p in self._paths():
            with open(p, "r", encoding=self.encoding, errors="replace") as f:
                yield from f


class CollectionSentenceIterator(BaseSentenceIterator):
    """In-memory list of sentences (CollectionSentenceIterator)."""

    def __init__(self, sentences: Iterable[str],
                 preprocessor: Optional[Callable[[str], str]] = None):
        super().__init__(preprocessor)
        self._sentences = list(sentences)

    def _lines(self) -> Iterator[str]:
        return iter(self._sentences)


class LabelledDocument:
    """documentiterator.LabelledDocument: content + label."""

    def __init__(self, content: str, label: str):
        self.content = content
        self.label = label


class FileLabelAwareIterator:
    """Directory-of-directories corpus: each subdirectory is a label, each
    file a document (documentiterator.FileLabelAwareIterator). Streams
    LabelledDocument objects; reset() restarts the walk."""

    def __init__(self, root: str, encoding: str = "utf-8"):
        self.root = root
        self.encoding = encoding

    def __iter__(self) -> Iterator[LabelledDocument]:
        for label in sorted(os.listdir(self.root)):
            d = os.path.join(self.root, label)
            if not os.path.isdir(d):
                continue
            for fname in sorted(os.listdir(d)):
                p = os.path.join(d, fname)
                if not os.path.isfile(p):
                    continue
                with open(p, "r", encoding=self.encoding,
                          errors="replace") as f:
                    yield LabelledDocument(f.read(), label)

    def reset(self):
        pass


class PhraseDetector:
    """word2phrase bigram collocation detection (Mikolov et al. 2013).

    score(a, b) = (count(ab) - delta) * N / (count(a) * count(b)); bigrams
    scoring above ``threshold`` merge into single ``a_b`` tokens. Run
    ``fit`` over tokenized sentences once, then ``transform`` token lists
    (or ``wrap`` a tokenized-sentence iterable); apply twice for trigrams+,
    exactly like chained word2phrase passes.
    """

    def __init__(self, min_count: int = 5, threshold: float = 10.0,
                 delimiter: str = "_"):
        self.min_count = min_count
        self.threshold = threshold
        self.delimiter = delimiter
        self.unigrams: Counter = Counter()
        self.bigrams: Counter = Counter()
        self.phrases: dict[tuple, str] = {}

    def fit(self, sentences: Iterable[List[str]]) -> "PhraseDetector":
        self.unigrams = Counter()           # refit replaces, never merges
        self.bigrams = Counter()
        for toks in sentences:
            self.unigrams.update(toks)
            self.bigrams.update(zip(toks, toks[1:]))
        total = sum(self.unigrams.values())
        delta = float(self.min_count)
        self.phrases = {}
        for (a, b), cab in self.bigrams.items():
            ca, cb = self.unigrams[a], self.unigrams[b]
            if cab < self.min_count:
                continue
            score = (cab - delta) * total / (ca * cb)
            if score > self.threshold:
                self.phrases[(a, b)] = f"{a}{self.delimiter}{b}"
        return self

    def score(self, a: str, b: str) -> float:
        total = sum(self.unigrams.values())
        ca, cb = self.unigrams.get(a, 0), self.unigrams.get(b, 0)
        cab = self.bigrams.get((a, b), 0)
        if not (ca and cb):
            return 0.0
        return (cab - float(self.min_count)) * total / (ca * cb)

    def transform(self, tokens: List[str]) -> List[str]:
        """Greedy left-to-right merge (word2phrase's output pass)."""
        out = []
        i = 0
        n = len(tokens)
        while i < n:
            if i + 1 < n and (tokens[i], tokens[i + 1]) in self.phrases:
                out.append(self.phrases[(tokens[i], tokens[i + 1])])
                i += 2
            else:
                out.append(tokens[i])
                i += 1
        return out

    def wrap(self, sentences: Iterable[List[str]]):
        """Lazily phrase-merge a tokenized-sentence stream (re-iterable if
        the source is)."""
        detector = self

        class _Wrapped:
            def __iter__(self):
                for toks in sentences:
                    yield detector.transform(toks)

            def reset(self):
                if hasattr(sentences, "reset"):
                    sentences.reset()

        return _Wrapped()
