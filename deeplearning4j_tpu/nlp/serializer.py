"""Word-vector interchange formats.

Reference analog: org.deeplearning4j.models.embeddings.loader.
WordVectorSerializer — the reference reads/writes the ORIGINAL word2vec
formats (Mikolov's text and binary layouts), which is what makes its
embeddings interoperable with gensim/fastText/the C tool. Same here:

- text:   header line "V D", then one "word f1 f2 ... fD" line per word
- binary: header line "V D\\n", then per word: "word " + D float32
          (little-endian) + "\\n"

Both round-trip through ``Word2Vec`` (the output C/Theta side is not part
of the interchange format — only the input embeddings travel, exactly like
the reference).
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np


def write_word_vectors(words: List[str], W, path: str,
                       binary: bool = False) -> None:
    """WordVectorSerializer.writeWordVectors: the original word2vec
    formats. ``W`` is [V, D]; words[i] labels row i."""
    W = np.asarray(W, np.float32)
    if len(words) != W.shape[0]:
        raise ValueError(f"{len(words)} words vs {W.shape[0]} vector rows")
    if binary:
        with open(path, "wb") as f:
            f.write(f"{W.shape[0]} {W.shape[1]}\n".encode())
            for w, row in zip(words, W):
                f.write(w.encode("utf-8") + b" ")
                f.write(row.astype("<f4").tobytes())
                f.write(b"\n")
    else:
        with open(path, "w", encoding="utf-8") as f:
            f.write(f"{W.shape[0]} {W.shape[1]}\n")
            for w, row in zip(words, W):
                f.write(w + " " + " ".join(f"{v:.6g}" for v in row) + "\n")


def read_word_vectors(path: str,
                      binary: bool = False) -> Tuple[List[str], np.ndarray]:
    """WordVectorSerializer.loadTxtVectors / readWord2VecModel: returns
    (words, W [V, D] float32). The text reader tolerates a missing header
    (some exporters omit it) by inferring V/D from the first data line."""
    if binary:
        with open(path, "rb") as f:
            header = b""
            while not header.endswith(b"\n"):
                c = f.read(1)
                if not c:
                    raise ValueError("truncated binary word2vec file")
                header += c
            V, D = (int(x) for x in header.split())
            words, rows = [], []
            for _ in range(V):
                w = b""
                while True:
                    c = f.read(1)
                    if not c:
                        raise ValueError("truncated binary word2vec file")
                    if c == b" ":
                        break
                    w += c
                buf = f.read(4 * D)
                if len(buf) != 4 * D:
                    raise ValueError("truncated binary word2vec file")
                rows.append(np.frombuffer(buf, "<f4"))
                nl = f.read(1)          # trailing separator (C tool: '\n')
                if nl not in (b"\n", b"", b" "):
                    # some writers omit it; step back for the next word
                    f.seek(-1, 1)
                words.append(w.decode("utf-8", errors="replace").lstrip("\n"))
            return words, np.vstack(rows).astype(np.float32)
    words, rows = [], []
    V = D = None
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        first = ""
        consumed = 0
        while not first.strip():        # tolerate leading blank lines
            first = f.readline()
            consumed += 1
            if not first:
                raise ValueError(f"{path}: empty word-vector file")
        parts = first.split()
        if len(parts) == 2 and all(p.isdigit() for p in parts):
            V, D = int(parts[0]), int(parts[1])   # "V D" header
        else:                           # headerless: first line is data
            # infer D from the trailing float-parseable fields — a first
            # WORD containing spaces ("new york 0.1 ...") must not inflate
            # D and mis-split every later row (ADVICE r5). At least one
            # leading field is always the word, so the scan stops there;
            # an all-numeric line keeps the old single-token-word reading.
            D = 0
            for p in reversed(parts[1:]):
                try:
                    float(p)
                except ValueError:
                    break
                D += 1
            if D == 0:
                raise ValueError(
                    f"{path}:1: headerless first line has no trailing "
                    f"float fields to infer the vector dimension from")
            words.append(" ".join(parts[:-D]))
            rows.append(np.asarray([float(v) for v in parts[-D:]],
                                   np.float32))
        for lineno, line in enumerate(f, consumed + 1):
            parts = line.split()        # any whitespace separates fields
            if not parts:
                continue                # blank line
            if len(parts) < D + 1:
                raise ValueError(
                    f"{path}:{lineno}: expected a word + {D} floats, got "
                    f"{len(parts)} fields")
            # words may contain spaces in some exports: floats are the
            # LAST D fields, the word is everything before them
            try:
                row = np.asarray([float(v) for v in parts[-D:]], np.float32)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{lineno}: last {D} fields must be floats "
                    f"({e})") from None
            words.append(" ".join(parts[:-D]))
            rows.append(row)
    if V is not None and len(words) != V:
        # also catches the ambiguous case of a headerless file whose
        # first line happened to look like a "V D" header
        raise ValueError(
            f"{path}: header declares {V} vectors but {len(words)} data "
            f"lines were read")
    if not rows:
        raise ValueError(f"{path}: no word vectors found")
    return words, np.vstack(rows)


def save_word2vec(model, path: str, binary: bool = False) -> None:
    """Write a fitted Word2Vec's input embeddings in the interchange
    format (reference: WordVectorSerializer.writeWord2VecModel)."""
    write_word_vectors(model.vocab.words, model.W, path, binary=binary)


def load_word2vec(path: str, binary: bool = False):
    """Read a word2vec text/binary file into a query-ready Word2Vec
    (similarity / words_nearest work; further training starts fresh —
    the interchange formats carry no output-side vectors, as in the
    reference)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    words, W = read_word_vectors(path, binary=binary)
    m = Word2Vec(vector_size=W.shape[1])
    m.W = W
    m.C = np.zeros_like(W)
    m.vocab.words = list(words)
    m.vocab.index = {w: i for i, w in enumerate(words)}
    return m
