"""Native concurrent text front for Word2Vec.

Reference analog (SURVEY.md §2.3 NLP row): the reference's Word2Vec trains
with PER-THREAD Hogwild workers over the corpus — the host side of
`org.deeplearning4j.models.word2vec.Word2Vec` (via SequenceVectors) is
inherently concurrent. The TPU-first split keeps the device update as ONE
jitted XLA step (nlp/word2vec.py) and makes the HOST side concurrent here:
N native threads tokenize, encode, subsample, window and negative-sample
line-chunks of a corpus file in parallel (native/dl4jtpu_native.cpp text
front), delivering fixed-shape int32 batches that feed the compiled step.

Like the reference's Hogwild workers, batch arrival order is
nondeterministic run-to-run; the pure-Python front in word2vec.py remains
the deterministic path. Tokenizer semantics match DefaultTokenizerFactory +
CommonPreprocessor for ASCII text; non-ASCII bytes pass through as word
characters WITHOUT lowercasing or unicode-punctuation stripping, so
Word2Vec only auto-selects this front for ASCII corpora (sampled gate in
Word2Vec._ascii_sample) — ``native_front=True`` forces byte-level
semantics on any corpus. Caveat for forced non-UTF-8 corpora:
native_word_counts decodes words with errors="replace", so byte sequences
that are invalid UTF-8 can collapse onto replacement-character vocab keys
that the raw byte stream then never matches (collided counts SUM onto the
shared key; such words count toward the vocabulary but produce no
training pairs).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.native.lib import load_native_lib

_F32P = ctypes.POINTER(ctypes.c_float)
_I32P = ctypes.POINTER(ctypes.c_int32)


def native_word_counts(path: str, n_threads: int = 4) -> Optional[Dict[str, int]]:
    """Multithreaded word-count pass over a text file — the vocabulary-build
    half of Word2Vec.fit. None if the native lib is unavailable or the file
    can't be read (caller falls back to the Python Counter pass)."""
    lib = load_native_lib()
    if lib is None:
        return None
    h = lib.dl4j_wc_create(str(path).encode(), int(n_threads))
    if not h:
        return None
    try:
        buf = ctypes.create_string_buffer(lib.dl4j_wc_bytes(h))
        lib.dl4j_wc_dump(h, buf)
        counts: Dict[str, int] = {}
        for line in buf.value.decode("utf-8", errors="replace").splitlines():
            word, _, n = line.rpartition(" ")
            # errors="replace" can collapse distinct invalid-UTF-8 byte
            # sequences onto one replacement-character key: sum, don't
            # overwrite (ADVICE r5)
            counts[word] = counts.get(word, 0) + int(n)
        return counts
    finally:
        lib.dl4j_wc_destroy(h)


class NativeSkipGramStream:
    """Iterator of (center[B], context[B], negatives[B, K]) int32 batches
    from the native concurrent pipeline. K == 0 (hierarchical softmax)
    yields (center, context, None). ``reset()`` rewinds for the next epoch
    with fresh window-shrink/negative draws.

    ``words_seen`` / ``pairs_emitted`` read the native counters: in-vocab
    tokens consumed (pre-subsample) and full batches' pairs delivered.
    """

    def __init__(self, path: str, words, probs: Optional[np.ndarray],
                 keep: Optional[np.ndarray], window: int, negative: int,
                 batch: int, seed: int = 0, n_threads: int = 4,
                 queue_cap: int = 8):
        lib = load_native_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.batch = int(batch)
        self.negative = int(negative)
        blob = "\n".join(words).encode("utf-8")
        probs_arr = (np.ascontiguousarray(probs, np.float32)
                     if negative > 0 else np.zeros(len(words), np.float32))
        self._probs = probs_arr                    # keepalive for the C call
        keep_arr = (np.ascontiguousarray(keep, np.float32)
                    if keep is not None else None)
        self._keep = keep_arr
        self._h = lib.dl4j_w2v_create(
            str(path).encode(), blob, len(words),
            probs_arr.ctypes.data_as(_F32P),
            keep_arr.ctypes.data_as(_F32P) if keep_arr is not None else None,
            int(window), int(negative), int(batch), int(seed) & 0xFFFFFFFF,
            int(n_threads), int(queue_cap))
        if not self._h:
            raise RuntimeError(f"dl4j_w2v_create failed for {path!r}")
        # reused delivery buffers; consumers must copy if they hold on
        self._c = np.empty(batch, np.int32)
        self._x = np.empty(batch, np.int32)
        self._n = np.empty((batch, max(negative, 1)), np.int32)

    def _handle(self):
        if not self._h:   # NULL through ctypes would segfault the C side
            raise RuntimeError("NativeSkipGramStream is closed")
        return self._h

    def __iter__(self):
        cp = self._c.ctypes.data_as(_I32P)
        xp = self._x.ctypes.data_as(_I32P)
        np_ = self._n.ctypes.data_as(_I32P)
        # re-read the handle every iteration: close() between next() calls
        # must raise, not hand a freed pointer to the C side
        while self._lib.dl4j_w2v_next(self._handle(), cp, xp, np_) == 0:
            yield (self._c, self._x,
                   self._n if self.negative > 0 else None)

    def reset(self):
        self._lib.dl4j_w2v_reset(self._handle())

    @property
    def words_seen(self) -> int:
        return int(self._lib.dl4j_w2v_words(self._handle()))

    @property
    def pairs_emitted(self) -> int:
        return int(self._lib.dl4j_w2v_pairs(self._handle()))

    def close(self):
        if self._h:
            self._lib.dl4j_w2v_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
