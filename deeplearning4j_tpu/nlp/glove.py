"""GloVe — global word-vector training on co-occurrence statistics.

Reference analog: org.deeplearning4j.models.glove.Glove (+ builder). The
reference streams co-occurrence pairs and applies per-pair AdaGrad updates;
TPU-first the co-occurrence table is built host-side once, then the weighted
least-squares objective is minimized with full-batch jitted AdaGrad steps
over the (sparse, flattened) co-occurrence entries — one XLA program per
epoch, MXU-friendly gathers/matmuls.
"""

from __future__ import annotations

import functools
from collections import Counter
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenizers import CommonPreprocessor, DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, cosine_similarity


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("lr",))
def _glove_step(params, rows, cols, logx, weight, lr):
    """AdaGrad step on J = Σ f(X_ij) (w_i·c_j + b_i + b̄_j − log X_ij)²."""

    def loss_fn(p):
        W, C, bw, bc = p["W"], p["C"], p["bw"], p["bc"]
        pred = (jnp.einsum("bd,bd->b", W[rows], C[cols])
                + bw[rows] + bc[cols])
        return (weight * (pred - logx) ** 2).sum()

    loss, grads = jax.value_and_grad(loss_fn)(
        {k: params[k] for k in ("W", "C", "bw", "bc")})
    new = dict(params)
    for k in ("W", "C", "bw", "bc"):
        g = grads[k]
        acc = params["acc_" + k] + g * g
        new[k] = params[k] - lr * g / jnp.sqrt(acc + 1e-8)
        new["acc_" + k] = acc
    return new, loss


class Glove:
    def __init__(self, vector_size: int = 100, window: int = 5,
                 min_count: int = 1, epochs: int = 25, learning_rate: float = 0.05,
                 x_max: float = 100.0, alpha: float = 0.75, seed: int = 42):
        self.vector_size = vector_size
        self.window = window
        self.epochs = epochs
        self.lr = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.seed = seed
        self.vocab = VocabCache(min_count=min_count)
        self.tokenizer = DefaultTokenizerFactory(CommonPreprocessor())
        self.W: Optional[np.ndarray] = None

    def _cooccurrences(self, encoded):
        cooc: Counter = Counter()
        for sent in encoded:
            n = len(sent)
            for i in range(n):
                for j in range(max(0, i - self.window), min(n, i + self.window + 1)):
                    if i == j:
                        continue
                    cooc[(int(sent[i]), int(sent[j]))] += 1.0 / abs(i - j)
        return cooc

    def fit(self, corpus) -> "Glove":
        if isinstance(corpus, str):
            corpus = corpus.splitlines()
        sents = [self.tokenizer.tokenize(l) if isinstance(l, str) else l
                 for l in corpus]
        self.vocab.fit(sents)
        V, D = len(self.vocab), self.vector_size
        rng = np.random.default_rng(self.seed)
        encoded = [self.vocab.encode(s) for s in sents]
        cooc = self._cooccurrences(encoded)
        if not cooc:
            raise ValueError("no co-occurrences (corpus too small?)")
        rows = np.asarray([k[0] for k in cooc], np.int32)
        cols = np.asarray([k[1] for k in cooc], np.int32)
        x = np.asarray(list(cooc.values()), np.float32)
        logx = np.log(x)
        weight = np.minimum(1.0, (x / self.x_max) ** self.alpha).astype(np.float32)

        params = {
            "W": jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D),
            "C": jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D),
            "bw": jnp.zeros(V), "bc": jnp.zeros(V),
        }
        for k in ("W", "C", "bw", "bc"):
            params["acc_" + k] = jnp.zeros_like(params[k])
        r, c, lx, wt = map(jnp.asarray, (rows, cols, logx, weight))
        for _ in range(self.epochs):
            params, _ = _glove_step(params, r, c, lx, wt, lr=self.lr)
        self.W = np.asarray(params["W"]) + np.asarray(params["C"])  # GloVe sums
        return self

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.W[i]

    def similarity(self, a: str, b: str) -> float:
        return cosine_similarity(self.get_word_vector(a), self.get_word_vector(b))

    def words_nearest(self, word=None, top: int = 10, positive=None,
                      negative=None):
        """wordsNearest over the summed W+C GloVe vectors (single-word and
        analogy forms, shared engine with Word2Vec)."""
        from deeplearning4j_tpu.nlp.vocab import nearest_neighbors

        return nearest_neighbors(self.vocab.words, self.vocab.index, self.W,
                                 word=word, top=top, positive=positive,
                                 negative=negative)
