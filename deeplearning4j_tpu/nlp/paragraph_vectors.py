"""ParagraphVectors (doc2vec).

Reference analog: org.deeplearning4j.models.paragraphvectors.ParagraphVectors
— PV-DM/PV-DBOW document embeddings trained jointly with (or on top of) word
vectors, plus inferVector for unseen documents. TPU-first: same batched
negative-sampling jitted steps as Word2Vec with the doc vector added to the
context mean (PV-DM) or used alone (PV-DBOW).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenizers import CommonPreprocessor, DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import NegativeSampler, VocabCache, cosine_similarity
from deeplearning4j_tpu.nlp.word2vec import cbow_windows


@functools.partial(jax.jit, donate_argnums=(0, 1, 2), static_argnames=("lr", "train_words"))
def _pvdm_step(Dv, W, C, doc_ids, ctx, center, negatives, lr, train_words=True):
    """PV-DM: (doc vector + context mean)/2 predicts center word."""

    def loss_fn(p):
        Dv_, W_, C_ = p
        h = (Dv_[doc_ids] + W_[ctx].mean(axis=1)) / 2.0
        pos = jnp.einsum("bd,bd->b", h, C_[center])
        neg = jnp.einsum("bd,bkd->bk", h, C_[negatives])
        return -jax.nn.log_sigmoid(pos).sum() - jax.nn.log_sigmoid(-neg).sum()

    loss, grads = jax.value_and_grad(loss_fn)((Dv, W, C))
    Dv = Dv - lr * grads[0]
    if train_words:
        W = W - lr * grads[1]
    C = C - lr * grads[2]
    return Dv, W, C, loss


class ParagraphVectors:
    """PV-DM doc embeddings with Word2Vec-style negative sampling."""

    def __init__(self, vector_size: int = 100, window: int = 4,
                 min_count: int = 1, negative: int = 5, epochs: int = 5,
                 learning_rate: float = 0.05, batch_size: int = 512,
                 seed: int = 42):
        self.vector_size = vector_size
        self.window = window
        self.negative = negative
        self.epochs = epochs
        self.lr = learning_rate
        self.batch_size = batch_size
        self.seed = seed
        self.vocab = VocabCache(min_count=min_count)
        self.tokenizer = DefaultTokenizerFactory(CommonPreprocessor())
        self.doc_vectors: Optional[np.ndarray] = None
        self.labels: List[str] = []
        self.W: Optional[np.ndarray] = None
        self.C: Optional[np.ndarray] = None

    def _examples(self, encoded):
        docs, all_centers, all_ctxs = [], [], []
        for d, sent in enumerate(encoded):
            centers, ctxs = cbow_windows([sent], self.window)
            docs.extend([d] * len(centers))
            all_centers.append(centers)
            all_ctxs.append(ctxs)
        centers = (np.concatenate(all_centers) if all_centers
                   else np.zeros(0, np.int32))
        ctxs = (np.concatenate(all_ctxs) if all_ctxs
                else np.zeros((0, 2 * self.window), np.int32))
        return (np.asarray(docs, np.int32), ctxs.astype(np.int32),
                centers.astype(np.int32))

    def fit(self, documents: Sequence[str], labels: Optional[Sequence[str]] = None
            ) -> "ParagraphVectors":
        rng = np.random.default_rng(self.seed)
        documents = list(documents)
        # label-aware document streams (nlp.corpus.FileLabelAwareIterator /
        # LabelledDocument) carry their own labels (r4)
        if documents and hasattr(documents[0], "content"):
            if labels is None:
                labels = [d.label for d in documents]
            documents = [d.content for d in documents]
        sents = [self.tokenizer.tokenize(d) for d in documents]
        self.labels = list(labels) if labels is not None else [
            f"DOC_{i}" for i in range(len(documents))]
        self.vocab.fit(sents)
        V, D, N = len(self.vocab), self.vector_size, len(documents)
        encoded = [self.vocab.encode(s) for s in sents]
        sampler = NegativeSampler(self.vocab.unigram_table_probs())

        Dv = jnp.asarray((rng.random((N, D), np.float32) - 0.5) / D)
        W = jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D)
        C = jnp.zeros((V, D), jnp.float32)
        docs, ctxs, centers = self._examples(encoded)
        if len(docs) == 0:
            raise ValueError("no context windows — every document is empty "
                             "or a single token after tokenization")
        for _ in range(self.epochs):
            order = rng.permutation(len(docs))
            B = min(self.batch_size, len(docs))
            for s in range(0, (len(docs) // B) * B, B):
                sl = order[s:s + B]
                negs = sampler.sample(rng, (B, self.negative))
                Dv, W, C, _ = _pvdm_step(Dv, W, C, jnp.asarray(docs[sl]),
                                         jnp.asarray(ctxs[sl]),
                                         jnp.asarray(centers[sl]),
                                         jnp.asarray(negs), lr=self.lr)
        self.doc_vectors, self.W, self.C = (np.asarray(Dv), np.asarray(W),
                                            np.asarray(C))
        return self

    # ----------------------------------------------------------------- query
    def get_doc_vector(self, label: str) -> Optional[np.ndarray]:
        try:
            return self.doc_vectors[self.labels.index(label)]
        except ValueError:
            return None

    def infer_vector(self, text: str, steps: int = 20) -> np.ndarray:
        """inferVector — gradient steps on a fresh doc vector, words frozen."""
        rng = np.random.default_rng(self.seed)
        toks = self.vocab.encode(self.tokenizer.tokenize(text))
        D = self.vector_size
        if len(toks) == 0:
            return np.zeros(D, np.float32)
        encoded = [toks]
        docs, ctxs, centers = self._examples(encoded)
        if len(docs) == 0:
            return np.zeros(D, np.float32)
        sampler = NegativeSampler(self.vocab.unigram_table_probs())
        Dv = jnp.asarray((rng.random((1, D), np.float32) - 0.5) / D)
        W, C = jnp.asarray(self.W), jnp.asarray(self.C)
        B = len(docs)
        for _ in range(steps):
            negs = sampler.sample(rng, (B, self.negative))
            Dv, W, C, _ = _pvdm_step(Dv, W, C, jnp.asarray(docs),
                                     jnp.asarray(ctxs), jnp.asarray(centers),
                                     jnp.asarray(negs), lr=self.lr,
                                     train_words=False)
        return np.asarray(Dv[0])

    def similarity(self, a: str, b: str) -> float:
        return cosine_similarity(self.get_doc_vector(a), self.get_doc_vector(b))

    def nearest_labels(self, text: str, top: int = 10):
        """nearestLabels — infer a vector for raw text and return the
        closest trained document labels by cosine (the reference's
        ParagraphVectors.nearestLabels(rawText, topN))."""
        v = self.infer_vector(text)
        n = np.linalg.norm(v)
        if n == 0 or len(self.labels) == 0:
            return []
        Dn = self.doc_vectors / np.maximum(
            np.linalg.norm(self.doc_vectors, axis=1, keepdims=True), 1e-12)
        sims = Dn @ (v / n)
        return [self.labels[j] for j in np.argsort(-sims)][:top]
