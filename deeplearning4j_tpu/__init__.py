"""deeplearning4j_tpu — a TPU-native deep learning framework.

A from-scratch rebuild of the Deeplearning4j capability surface
(reference: paladin74/deeplearning4j) designed TPU-first on JAX/XLA/Pallas:

- ``ops``       — named op registry with runtime-selectable Pallas kernels
                  (the libnd4j "platform helper" idea, TPU-native).
                  Reference: libnd4j/include/ops/declarable/**.
- ``autodiff``  — SameDiff-style define-then-run graph layer.
                  Reference: nd4j-api :: org.nd4j.autodiff.samediff.SameDiff.
- ``nn``        — declarative layer configs + MultiLayerNetwork /
                  ComputationGraph. Reference: deeplearning4j-nn ::
                  org.deeplearning4j.nn.{conf,multilayer,graph}.
- ``optimize``  — updaters, LR schedules, listeners, early stopping.
                  Reference: org.nd4j.linalg.learning, org.deeplearning4j.optimize.
- ``datasets``  — DataSet/DataSetIterator contracts + fetchers.
                  Reference: org.nd4j.linalg.dataset, deeplearning4j-data.
- ``datavec``   — RecordReader / TransformProcess ETL. Reference: datavec/.
- ``parallel``  — device-mesh parallelism (DP/TP/PP/SP) as XLA collectives;
                  replaces ParallelWrapper / Spark / Aeron. Reference:
                  org.deeplearning4j.parallelism.ParallelWrapper.
- ``zoo``       — model zoo. Reference: deeplearning4j-zoo.
- ``eval``      — Evaluation / ROC / RegressionEvaluation.
                  Reference: org.nd4j.evaluation.
- ``modelimport`` — Keras h5 / TF frozen-graph import.
                  Reference: deeplearning4j-modelimport, org.nd4j.imports.

Unlike the reference's per-op JNI dispatch into CUDA kernels, everything here
funnels into XLA: model configs trace to a single jitted (and, on a mesh,
pjit-sharded) XLA program per train/inference step.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.common.dtypes import DtypePolicy, get_policy, set_policy
from deeplearning4j_tpu.common.env import env as _env

if _env.compile_cache_dir:
    # DL4J_TPU_COMPILE_CACHE=<dir>: persist XLA compiles across processes
    # (and register the dl4j_compile_* metrics bridge)
    from deeplearning4j_tpu.monitoring.compile import configure_compile_cache

    configure_compile_cache()

__all__ = [
    "DtypePolicy",
    "get_policy",
    "set_policy",
    "__version__",
]
