"""Network-config search spaces.

Reference analog: org.deeplearning4j.arbiter.MultiLayerSpace /
layers.DenseLayerSpace etc. — parameter spaces that *generate
MultiLayerConfiguration candidates*. Here a LayerSpace is any layer
dataclass whose fields may be ParameterSpace objects; MultiLayerSpace
samples every space field and builds a concrete MultiLayerConfiguration.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType


def _is_space(v) -> bool:
    return hasattr(v, "sample") and callable(v.sample)


def _sample_layer(layer, rng):
    """Replace every ParameterSpace field of a layer dataclass with a draw."""
    repl = {}
    for f in dataclasses.fields(layer):
        v = getattr(layer, f.name)
        if _is_space(v):
            repl[f.name] = v.sample(rng)
    return dataclasses.replace(layer, **repl) if repl else layer


def _seeded_builder(rng, updater_fn):
    """Shared sample() preamble: seeded base config + drawn updater."""
    b = NeuralNetConfiguration.builder().seed(int(rng.integers(1 << 30)))
    if updater_fn is not None:
        b = b.updater(updater_fn(rng))
    return b


def _candidate_generator(space, seed):
    """Infinite {'conf': sampled config} generator (RandomSearch over the
    space), pluggable into OptimizationRunner."""
    rng = np.random.default_rng(seed)
    while True:
        yield {"conf": space.sample(rng)}


class MultiLayerSpace:
    """Builder over layer templates with ParameterSpace-valued fields.

        space = (MultiLayerSpace.builder()
                 .updater_space(lambda rng: Adam(lr=lr_space.sample(rng)))
                 .add_layer(DenseLayer(n_out=IntegerParameterSpace(8, 64),
                                       activation="relu"))
                 .add_layer(OutputLayer(n_out=3, activation="softmax",
                                        loss="mcxent"))
                 .set_input_type(InputType.feed_forward(10))
                 .build())
        conf = space.sample(rng)   # -> concrete MultiLayerConfiguration
    """

    def __init__(self, layers, input_type, updater_fn=None, seed: int = 0):
        self._layers = layers
        self._input_type = input_type
        self._updater_fn = updater_fn
        self._seed = seed
        self._rng = np.random.default_rng(seed)

    def sample(self, rng=None):
        # default to the instance rng so repeated sample() calls draw NEW
        # candidates (a fresh rng per call would resample the same point)
        rng = rng if rng is not None else self._rng
        lb = _seeded_builder(rng, self._updater_fn).list()
        for layer in self._layers:
            lb = lb.layer(_sample_layer(layer, rng))
        return lb.set_input_type(self._input_type).build()

    def candidate_generator(self, seed: int = 0):
        return _candidate_generator(self, seed)

    # --------------------------------------------------------------- builder
    class Builder:
        def __init__(self):
            self._layers: List = []
            self._input_type: Optional[InputType] = None
            self._updater_fn = None
            self._seed = 0

        def add_layer(self, layer) -> "MultiLayerSpace.Builder":
            self._layers.append(layer)
            return self

        def updater_space(self, fn) -> "MultiLayerSpace.Builder":
            """fn(rng) -> Updater instance (sample learning rates etc.)."""
            self._updater_fn = fn
            return self

        def set_input_type(self, itype: InputType) -> "MultiLayerSpace.Builder":
            self._input_type = itype
            return self

        def seed(self, s: int) -> "MultiLayerSpace.Builder":
            self._seed = s
            return self

        def build(self) -> "MultiLayerSpace":
            if self._input_type is None:
                raise ValueError("MultiLayerSpace requires an input type")
            return MultiLayerSpace(self._layers, self._input_type,
                                   self._updater_fn, seed=self._seed)

    @staticmethod
    def builder() -> "MultiLayerSpace.Builder":
        return MultiLayerSpace.Builder()


class ComputationGraphSpace:
    """Graph-topology search space (org.deeplearning4j.arbiter
    .ComputationGraphSpace analog): the graph builder idiom with
    ParameterSpace-valued layer fields; ``sample`` draws every space and
    builds a concrete ComputationGraphConfiguration. Vertices are fixed
    topology (as in the reference); only layer hyperparameters vary.

        space = (ComputationGraphSpace.builder()
                 .add_inputs("in")
                 .set_input_types(**{"in": InputType.feed_forward(10)})
                 .add_layer("fc", DenseLayer(n_out=IntegerParameterSpace(8, 64),
                                             activation="relu"), "in")
                 .add_layer("out", OutputLayer(...), "fc")
                 .set_outputs("out")
                 .build())
    """

    def __init__(self, inputs, input_types, nodes, outputs, updater_fn=None,
                 seed: int = 0):
        self._inputs = inputs
        self._input_types = input_types
        self._nodes = nodes          # [(kind, name, layer_or_vertex, parents)]
        self._outputs = outputs
        self._updater_fn = updater_fn
        self._rng = np.random.default_rng(seed)

    def sample(self, rng=None):
        # instance rng default, same contract as MultiLayerSpace.sample
        rng = rng if rng is not None else self._rng
        gb = (_seeded_builder(rng, self._updater_fn).graph_builder()
              .add_inputs(*self._inputs)
              .set_input_types(**self._input_types))
        for kind, name, obj, parents in self._nodes:
            if kind == "layer":
                gb = gb.add_layer(name, _sample_layer(obj, rng), *parents)
            else:
                gb = gb.add_vertex(name, obj, *parents)
        return gb.set_outputs(*self._outputs).build()

    def candidate_generator(self, seed: int = 0):
        return _candidate_generator(self, seed)

    # --------------------------------------------------------------- builder
    class Builder:
        def __init__(self):
            self._inputs: List[str] = []
            self._input_types: Dict[str, InputType] = {}
            self._nodes: List = []
            self._outputs: List[str] = []
            self._updater_fn = None
            self._seed = 0

        def add_inputs(self, *names: str) -> "ComputationGraphSpace.Builder":
            self._inputs = list(names)
            return self

        def set_input_types(self, **types) -> "ComputationGraphSpace.Builder":
            self._input_types.update(types)
            return self

        def add_layer(self, name: str, layer, *parents: str
                      ) -> "ComputationGraphSpace.Builder":
            self._nodes.append(("layer", name, layer, list(parents)))
            return self

        def add_vertex(self, name: str, vertex, *parents: str
                       ) -> "ComputationGraphSpace.Builder":
            self._nodes.append(("vertex", name, vertex, list(parents)))
            return self

        def set_outputs(self, *names: str) -> "ComputationGraphSpace.Builder":
            self._outputs = list(names)
            return self

        def updater_space(self, fn) -> "ComputationGraphSpace.Builder":
            self._updater_fn = fn
            return self

        def seed(self, s: int) -> "ComputationGraphSpace.Builder":
            self._seed = s
            return self

        def build(self) -> "ComputationGraphSpace":
            if not (self._inputs and self._outputs):
                raise ValueError("ComputationGraphSpace requires inputs and "
                                 "outputs")
            return ComputationGraphSpace(self._inputs, self._input_types,
                                         self._nodes, self._outputs,
                                         self._updater_fn, seed=self._seed)

    @staticmethod
    def builder() -> "ComputationGraphSpace.Builder":
        return ComputationGraphSpace.Builder()
