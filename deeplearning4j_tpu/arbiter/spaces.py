"""Parameter spaces.

Reference analog: org.deeplearning4j.arbiter.optimize.parameter.
{continuous.ContinuousParameterSpace, discrete.DiscreteParameterSpace,
integer.IntegerParameterSpace}.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Sequence


@dataclasses.dataclass(frozen=True)
class ContinuousParameterSpace:
    lo: float
    hi: float
    log_scale: bool = False

    def sample(self, rng) -> float:
        if self.log_scale:
            return float(math.exp(rng.uniform(math.log(self.lo),
                                              math.log(self.hi))))
        return float(rng.uniform(self.lo, self.hi))

    def grid(self, n: int) -> List[float]:
        if n == 1:
            return [(self.lo + self.hi) / 2]
        if self.log_scale:
            lo, hi = math.log(self.lo), math.log(self.hi)
            return [math.exp(lo + i * (hi - lo) / (n - 1)) for i in range(n)]
        return [self.lo + i * (self.hi - self.lo) / (n - 1) for i in range(n)]


@dataclasses.dataclass(frozen=True)
class DiscreteParameterSpace:
    values: Sequence

    def sample(self, rng):
        return self.values[rng.integers(len(self.values))]

    def grid(self, n: int = 0) -> List:
        return list(self.values)


@dataclasses.dataclass(frozen=True)
class IntegerParameterSpace:
    lo: int
    hi: int  # inclusive

    def sample(self, rng) -> int:
        return int(rng.integers(self.lo, self.hi + 1))

    def grid(self, n: int) -> List[int]:
        span = self.hi - self.lo
        if n >= span + 1:
            return list(range(self.lo, self.hi + 1))
        return sorted({self.lo + round(i * span / max(n - 1, 1))
                       for i in range(n)})
