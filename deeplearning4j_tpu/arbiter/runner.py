"""Candidate generators + optimization runner.

Reference analog: org.deeplearning4j.arbiter.optimize.runner.
LocalOptimizationRunner with RandomSearchGenerator /
GridSearchCandidateGenerator, ScoreFunction, and TerminationCondition
(MaxCandidatesCondition, MaxTimeCondition). The runner is model-agnostic:
``build_fn(hyperparams) -> model`` and ``score_fn(model) -> float`` — the
arbiter DL4J couples to MultiLayerConfiguration via its own layer spaces;
here any model/config factory composes.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Callable, Dict, List, Optional

import numpy as np


class RandomSearchGenerator:
    def __init__(self, spaces: Dict[str, object], seed: int = 0):
        self.spaces = spaces
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        while True:
            yield {k: s.sample(self._rng) for k, s in self.spaces.items()}


class GridSearchGenerator:
    """Cartesian product over per-space grids (discretization_count for
    continuous spaces, as in GridSearchCandidateGenerator)."""

    def __init__(self, spaces: Dict[str, object], discretization_count: int = 5):
        self.spaces = spaces
        self.n = discretization_count

    def __iter__(self):
        keys = list(self.spaces)
        grids = [self.spaces[k].grid(self.n) for k in keys]
        for combo in itertools.product(*grids):
            yield dict(zip(keys, combo))


@dataclasses.dataclass
class MaxCandidatesCondition:
    max_candidates: int

    def done(self, n_done: int, t_start: float) -> bool:
        return n_done >= self.max_candidates


@dataclasses.dataclass
class MaxTimeCondition:
    seconds: float

    def done(self, n_done: int, t_start: float) -> bool:
        return time.monotonic() - t_start >= self.seconds


@dataclasses.dataclass
class OptimizationResult:
    hyperparams: Dict
    score: float
    model: object
    index: int


class OptimizationRunner:
    """Sequential candidate evaluation with best-tracking.

    minimize=True treats score as loss (the reference's ScoreFunction
    minimizeScore flag).
    """

    def __init__(self, generator, build_fn: Callable[[Dict], object],
                 score_fn: Callable[[object], float],
                 termination_conditions: Optional[List] = None,
                 minimize: bool = True,
                 listeners: Optional[List[Callable]] = None):
        self.generator = generator
        self.build_fn = build_fn
        self.score_fn = score_fn
        self.conditions = termination_conditions or [MaxCandidatesCondition(10)]
        self.minimize = minimize
        self.listeners = listeners or []
        self.results: List[OptimizationResult] = []

    def execute(self) -> OptimizationResult:
        t0 = time.monotonic()
        best: Optional[OptimizationResult] = None
        for i, hp in enumerate(self.generator):
            if any(c.done(i, t0) for c in self.conditions):
                break
            model = self.build_fn(hp)
            score = float(self.score_fn(model))
            res = OptimizationResult(hp, score, model, i)
            self.results.append(res)
            for lst in self.listeners:
                lst(res)
            better = (best is None or
                      (score < best.score if self.minimize else score > best.score))
            if np.isfinite(score) and better:
                best = res
        if best is None:
            if self.results:
                raise RuntimeError(
                    f"all {len(self.results)} candidate scores were non-finite")
            raise RuntimeError("no candidates evaluated")
        return best

    def best(self) -> OptimizationResult:
        finite = [r for r in self.results if np.isfinite(r.score)]
        if not finite:
            raise RuntimeError("no finite-scored candidates")
        key = (lambda r: r.score) if self.minimize else (lambda r: -r.score)
        return min(finite, key=key)
