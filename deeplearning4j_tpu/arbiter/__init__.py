"""Hyperparameter optimization (Arbiter).

Reference analog: the `arbiter/` module — org.deeplearning4j.arbiter.
optimize.api.ParameterSpace, CandidateGenerator (RandomSearchGenerator,
GridSearchCandidateGenerator), OptimizationRunner with score functions and
termination conditions (SURVEY.md §2.3 "Tooling" / §7 step 8).
"""

from deeplearning4j_tpu.arbiter.spaces import (
    ContinuousParameterSpace, DiscreteParameterSpace, IntegerParameterSpace,
)
from deeplearning4j_tpu.arbiter.spaces_net import (ComputationGraphSpace,
                                                   MultiLayerSpace)
from deeplearning4j_tpu.arbiter.runner import (
    GridSearchGenerator, MaxCandidatesCondition, MaxTimeCondition,
    OptimizationResult, OptimizationRunner, RandomSearchGenerator,
)

__all__ = [
    "ContinuousParameterSpace", "DiscreteParameterSpace",
    "IntegerParameterSpace", "MultiLayerSpace", "ComputationGraphSpace", "RandomSearchGenerator", "GridSearchGenerator",
    "OptimizationRunner", "OptimizationResult", "MaxCandidatesCondition",
    "MaxTimeCondition",
]
