"""Continuous-batching generation engine: one compiled decode step, replayed.

PyGraph (arxiv 2503.19779) frames decode latency as a LAUNCH problem: the
per-token work is small, so the win is capturing the whole step into one
replayable device program. The XLA analog here: a single fixed-shape jitted
decode step — gather the slot pool, model step ``[n_slots, 1]``, seeded
sampler, scatter state, emit tokens — whose argument shapes never change, so
the entire serving lifetime is ONE program replay (``decode_programs``
witnesses it; tests assert it stays 1 under churn).

Two model families share the engine through small adapters:

- ``RecurrentDecodeAdapter`` — LSTM/GRU/SimpleRnn stacks (zoo/textgen.py):
  slot state is the per-layer carry dict from ``MultiLayerNetwork``'s own
  machinery (``_init_carries`` / ``_forward_carry``), the cuDNN-persistent-
  RNN serving story (arxiv 1410.0759) riding the fused-LSTM op tier.
- ``AttentionDecodeAdapter`` — causal transformer stacks (zoo/bert.py
  topology with ``causal=True``): slot state is per-layer KV ring buffers,
  stepped through ``TransformerEncoderLayer.apply_step`` and the
  ``cached_dot_product_attention`` op.

Prefill is pow2-bucketed (``serving/warmup.py`` buckets), so prompt shapes
compile O(log max_len) programs, not O(#lengths). Recurrent prefill uses a
gated ``lax.scan`` — the carry stops updating once the step index passes the
true prompt length, because right-padding WOULD corrupt an LSTM carry (every
scan step feeds it). Attention prefill right-pads freely: under the causal
mask, position i never sees j > i, and the pad rows written into the cache
ring are each overwritten by the real decode step that reaches that
position before the validity mask ever admits them.

Scheduling is continuous batching: new requests are admitted into free
slots every step and finished ones retire immediately, so throughput never
degrades to run-to-completion of the longest sequence in a batch.
``continuous=False`` switches to exactly that static policy — the bench A/B
baseline (bench.py generate).
"""

from __future__ import annotations

import collections
import dataclasses
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults, monitoring
from deeplearning4j_tpu.generation.sampler import sample_keys, sample_logits
from deeplearning4j_tpu.generation.slots import SlotPool
from deeplearning4j_tpu.nn.layers.attention import (
    PositionalEmbeddingLayer, TransformerEncoderLayer,
)
from deeplearning4j_tpu.nn.layers.core import (
    EmbeddingLayer, EmbeddingSequenceLayer,
)
from deeplearning4j_tpu.nn.multilayer import _tree_cast
from deeplearning4j_tpu.serving.warmup import bucket_for, pow2_buckets


# ---------------------------------------------------------------- requests
@dataclasses.dataclass(frozen=True)
class GenerationRequest:
    """One decode job: prompt token ids + sampling knobs + stop conditions."""

    prompt: Tuple[int, ...]
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: Optional[int] = None


_DONE = object()


class GenerationStream:
    """Token stream for one request: iterate to receive tokens as the engine
    emits them; iteration ends when the request finishes or is cancelled.
    ``finish_reason`` is one of eos / length / cancelled / preempted
    afterwards.

    Session-tracked streams (journal-armed engines) carry a ``request_id``
    and a sequence offset ``seq0``: a stream resumed after a preemption
    continues the ORIGINAL session's numbering, so a reconnecting client's
    ``last_seq`` means the same thing across restarts. ``__iter__`` is the
    single-consumer fast path (a SimpleQueue); :meth:`follow` is the
    multi-consumer reconnect path.
    """

    def __init__(self, request: GenerationRequest,
                 request_id: Optional[str] = None, seq0: int = 0):
        self.request = request
        self.request_id = request_id
        #: absolute sequence number already emitted BEFORE this stream
        #: (non-zero only on session resume)
        self.seq0 = int(seq0)
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        #: RequestTrace riding this stream (traced gateways); the engine
        #: records queue_wait / prefill / decode spans into it. None = the
        #: engine performs zero trace calls for this stream.
        self.trace = None
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._cancelled = False
        self._cancel_reason = "cancelled"
        self._last_at: Optional[float] = None
        self._done_evt = threading.Event()
        self._cv = threading.Condition()

    # engine side -----------------------------------------------------
    def _emit(self, token: int) -> None:
        with self._cv:
            self.tokens.append(token)
            self._cv.notify_all()
        self._q.put(token)

    def _finish(self, reason: str) -> None:
        self.finish_reason = reason
        self.finished_at = time.monotonic()
        self._q.put(_DONE)
        self._done_evt.set()
        with self._cv:
            self._cv.notify_all()

    # consumer side ---------------------------------------------------
    def cancel(self, reason: str = "cancelled") -> None:
        """Ask the engine to retire this request at its next step.
        ``reason`` becomes the stream's ``finish_reason`` (the preemption
        drain passes ``"preempted"``, which keeps the session journal
        record open for resume)."""
        self._cancel_reason = reason
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def done(self) -> bool:
        return self.finish_reason is not None

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _DONE:
                return
            yield item

    def follow(self, last_seq: int = 0):
        """Yield ``(seq, token)`` pairs with absolute sequence numbers
        strictly greater than ``last_seq`` (1-based), then return when the
        stream finishes. Unlike ``__iter__`` this does not consume the
        queue, so any number of reconnecting consumers can follow one
        stream concurrently and each sees every token exactly once."""
        i = max(0, int(last_seq) - self.seq0)
        while True:
            with self._cv:
                while len(self.tokens) <= i and not self.done:
                    self._cv.wait(timeout=0.1)
                avail = len(self.tokens)
                done = self.done
            while i < avail:
                yield (self.seq0 + i + 1, self.tokens[i])
                i += 1
            if done and i >= len(self.tokens):
                return

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes (without consuming the token
        queue); False if ``timeout`` expired first."""
        return self._done_evt.wait(timeout)

    def result(self) -> List[int]:
        """Block until the request finishes; returns all emitted tokens."""
        for _ in self:
            pass
        return self.tokens


# ---------------------------------------------------------------- adapters
class RecurrentDecodeAdapter:
    """Slot state = the net's own carry dict ({layer_idx: (h, c)/(h,)}).

    ``vocab`` sizes the one-hot input for raw-recurrent stacks (defaults to
    the output layer's vocab — the char-RNN convention where input and
    output alphabets coincide); nets whose first layer is an Embedding take
    token indices directly and ignore it.
    """

    def __init__(self, net, vocab: Optional[int] = None):
        self.net = net
        self._embed_first = isinstance(
            net.layers[0], (EmbeddingLayer, EmbeddingSequenceLayer))
        self.vocab = vocab if vocab is not None else net.layers[-1].n_out

    def init_state(self, n: int):
        return self.net._init_carries(n)

    def _encode(self, tokens):
        """Token ids [B] -> one model input step [B, 1, ...]."""
        if self._embed_first:
            return tokens[:, None]
        dt = self.net._policy.compute_dtype
        return jax.nn.one_hot(tokens, self.vocab, dtype=dt)[:, None, :]

    def decode(self, params, net_state, carries, tokens, pos):
        """One step for every slot: logits [B, vocab] + advanced carries."""
        net = self.net
        cp = _tree_cast(params, net._policy.compute_dtype)
        preout, _, _, _, new_c = net._forward_carry(
            cp, net_state, self._encode(tokens), carries, False, None, None)
        merged = dict(carries)
        merged.update(new_c)
        return preout[:, 0].astype(jnp.float32), merged

    def prefill(self, params, net_state, prompt, length):
        """Consume a padded prompt [1, Tb] into a carry for one slot. The
        scan gate freezes the carry once the step index reaches ``length``
        — right-pad steps MUST NOT advance a recurrent carry."""
        net = self.net
        cp = _tree_cast(params, net._policy.compute_dtype)
        carries0 = self.init_state(prompt.shape[0])

        def body(carries, xs):
            tok_t, t = xs
            _, _, _, _, new_c = net._forward_carry(
                cp, net_state, self._encode(tok_t), carries, False, None,
                None)
            merged = dict(carries)
            merged.update(new_c)
            gate = t < length
            return jax.tree_util.tree_map(
                lambda o, n: jnp.where(gate, n, o), carries, merged), None

        Tb = prompt.shape[1]
        final, _ = jax.lax.scan(
            body, carries0, (prompt.T, jnp.arange(Tb, dtype=jnp.int32)))
        return final


class AttentionDecodeAdapter:
    """Slot state = per-transformer-layer KV ring buffers
    ({layer_idx: (k, v)}, each [n_slots, n_heads, max_len, head_dim]).

    Walks the net's layer list directly: Embedding -> table lookup,
    PositionalEmbedding -> ``P[pos]`` per row, TransformerEncoderLayer ->
    ``apply_step`` against its cache, output layer -> ``preout`` logits;
    anything else (LayerNorm, activations) runs its normal ``apply`` on a
    singleton time axis. Requires a causal stack — decode replays exactly
    what the full forward would compute (tests hold it to 1e-5).
    """

    def __init__(self, net, max_len: int, kv_dtype: Optional[str] = None):
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r}")
        self.net = net
        self.max_len = max_len
        self.kv_dtype = kv_dtype
        self._tf_layers = [i for i, l in enumerate(net.layers)
                           if hasattr(l, "apply_step")]
        if not self._tf_layers:
            raise ValueError("no transformer layers with a cached-decode "
                             "path in this network")
        for i in self._tf_layers:
            if not net.layers[i].causal:
                raise ValueError(
                    f"layer {i} is not causal=True; KV-cached decode only "
                    "matches a causal forward")
        for l in net.layers:
            if isinstance(l, PositionalEmbeddingLayer) and l.max_len < max_len:
                raise ValueError(
                    f"engine max_len {max_len} exceeds positional table "
                    f"({l.max_len})")

    def init_state(self, n: int):
        return {i: self.net.layers[i].init_cache(n, self.max_len,
                                                 kv_dtype=self.kv_dtype)
                for i in self._tf_layers}

    def decode(self, params, net_state, caches, tokens, pos):
        net = self.net
        cp = _tree_cast(params, net._policy.compute_dtype)
        x = None
        new_caches = dict(caches)
        last = len(net.layers) - 1
        for i, layer in enumerate(net.layers):
            p = cp[i]
            if i == last and hasattr(layer, "preout"):
                return (layer.preout(p, x[:, None, :])[:, 0].astype(
                    jnp.float32), new_caches)
            if isinstance(layer, (EmbeddingLayer, EmbeddingSequenceLayer)):
                x = p["W"][tokens]
                if layer.has_bias:
                    x = x + p["b"]
            elif isinstance(layer, PositionalEmbeddingLayer):
                x = x + p["P"][pos]
            elif hasattr(layer, "apply_step"):
                x, new_caches[i] = layer.apply_step(p, x, caches[i], pos)
            else:
                y, _ = layer.apply(p, net_state[i], x[:, None, :],
                                   train=False)
                x = y[:, 0]
        raise ValueError("network has no preout output layer")

    def prefill(self, params, net_state, prompt, length):
        """Causal forward over the padded prompt, harvesting each layer's
        K/V into a fresh cache ring.

        When the bucketed prompt fits the ring (``Tb <= L``, the usual
        engine configuration where ring == max_len), positions map to ring
        slots 1:1 and ``length`` is unused: pad rows beyond it land in
        ring positions the validity mask only admits AFTER the sequential
        decode has overwritten them with real K/V. When the prompt is
        LONGER than the ring (sliding-window adapters; session resume past
        a ring wrap), the last ``L`` true positions are gathered into
        their wrapped slots ``pos % L`` — exactly the ring a sequential
        decode would have left behind."""
        net = self.net
        cp = _tree_cast(params, net._policy.compute_dtype)
        x = None
        caches = {}
        L = self.max_len
        for i, layer in enumerate(net.layers):
            p = cp[i]
            if i == len(net.layers) - 1 and hasattr(layer, "preout"):
                break
            if isinstance(layer, (EmbeddingLayer, EmbeddingSequenceLayer)):
                x, _ = layer.apply(p, net_state[i], prompt, train=False)
            elif hasattr(layer, "apply_step"):
                x, (k, v) = layer.apply_prefill(p, x)
                Tb = prompt.shape[1]
                if Tb <= L:
                    ck, cv = layer.init_cache(prompt.shape[0], L,
                                              dtype=k.dtype)
                    ck = ck.at[:, :, :Tb].set(k)
                    cv = cv.at[:, :, :Tb].set(v)
                else:
                    # ring slot r holds the one position p ≡ r (mod L)
                    # inside the live window [length - L, length); slots
                    # whose window position is negative (length < L) stay
                    # zero and are either masked (index > pos) or
                    # overwritten by the first decode step (index == pos)
                    r = jnp.arange(L)
                    start = length - L
                    p_abs = start + jnp.mod(r - start, L)
                    idx = jnp.clip(p_abs, 0, Tb - 1)
                    keep = (p_abs >= 0)[None, None, :, None]
                    zero = jnp.zeros((), k.dtype)
                    ck = jnp.where(keep, k[:, :, idx], zero)
                    cv = jnp.where(keep, v[:, :, idx], zero)
                if self.kv_dtype == "int8":
                    # quantize the whole seeded ring in one pass; the
                    # running absmax scale then only grows during decode
                    from deeplearning4j_tpu.quantize.kvcache import (
                        quantize_cache)
                    qk, sk = quantize_cache(ck)
                    qv, sv = quantize_cache(cv)
                    caches[i] = (qk, qv, sk, sv)
                else:
                    caches[i] = (ck, cv)
            else:
                x, _ = layer.apply(p, net_state[i], x, train=False)
        return caches


def _auto_adapter(net, max_len: int, kv_dtype: Optional[str] = None):
    if any(hasattr(l, "apply_step") for l in net.layers):
        return AttentionDecodeAdapter(net, max_len, kv_dtype=kv_dtype)
    if kv_dtype is not None:
        raise ValueError("kv_dtype requires attention layers with a "
                         "KV-cached decode path")
    if any(hasattr(l, "apply_with_carry") for l in net.layers):
        return RecurrentDecodeAdapter(net)
    raise ValueError("network has neither transformer apply_step nor "
                     "recurrent apply_with_carry layers")


# ------------------------------------------------------------------ engine
class GenerationEngine:
    """Continuous-batching decode over a fixed slot pool.

    ``slots`` is device-resident capacity (see docs/generation.md for the
    sizing runbook), ``max_len`` bounds prompt+generation positions (and
    sizes the attention KV ring). ``continuous=False`` degrades scheduling
    to static run-to-completion batching — only for A/B measurement.

    Drive it synchronously (``step()``/``drain()``/``generate()``) or start
    the background loop (``start()``) and consume ``submit()`` streams from
    other threads — the serving gateway does the latter. Only one driver
    may call ``step()``; ``submit()``/``cancel()`` are thread-safe.
    """

    def __init__(self, net, *, slots: int = 8, max_len: int = 128,
                 eos_id: Optional[int] = None, continuous: bool = True,
                 adapter=None, codec=None, kv_dtype: Optional[str] = None,
                 journal=None):
        self.net = net
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self.continuous = continuous
        self.codec = codec
        #: SessionJournal (generation/sessions.py) or None — with None the
        #: engine performs ZERO journal calls (spy-guarded contract)
        self.journal = journal
        if adapter is not None and kv_dtype is not None:
            raise ValueError("pass kv_dtype to the adapter OR let the "
                             "engine build one, not both")
        self.adapter = adapter if adapter is not None else _auto_adapter(
            net, self.max_len, kv_dtype=kv_dtype)
        self.pool = SlotPool(int(slots), self.adapter.init_state)
        self.buckets = pow2_buckets(max(1, self.max_len - 1))
        self._decode = jax.jit(self._decode_impl)
        self._prefill = jax.jit(self.adapter.prefill)
        self._pending: "collections.deque[GenerationStream]" = (
            collections.deque())
        # low-priority lane (klass="batch"): admitted into freed slots only
        # when no interactive/default request is waiting
        self._pending_lo: "collections.deque[GenerationStream]" = (
            collections.deque())
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._accepting = True
        # the stream currently inside _admit's prefill: not pending, not
        # yet pooled — shutdown() cancels it here so a drain never waits
        # for a decode step the grace budget can't afford
        self._admitting: Optional[GenerationStream] = None
        self.steps_run = 0

    def attach_journal(self, journal) -> None:
        """Arm session journaling (see generation/sessions.py). Attach
        BEFORE traffic: only requests submitted with a ``request_id``
        after this point are durable."""
        self.journal = journal

    # ---------------------------------------------------- compiled pieces
    def _decode_impl(self, params, net_state, pool_state, tokens, pos,
                     seeds, temps, top_k, top_p):
        logits, new_state = self.adapter.decode(
            params, net_state, pool_state, tokens, pos)
        keys = sample_keys(seeds, pos)
        nxt = sample_logits(keys, logits, temperature=temps, top_k=top_k,
                            top_p=top_p)
        return nxt, new_state

    @property
    def decode_programs(self) -> int:
        """Compiled decode-step count — the PyGraph witness. Stays 1 for
        the engine's whole lifetime (fixed shapes)."""
        return self._decode._cache_size()

    @property
    def prefill_programs(self) -> int:
        """Compiled prefill count — bounded by the pow2 bucket list."""
        return self._prefill._cache_size()

    # ------------------------------------------------------------- submit
    def submit(self, prompt: Union[str, Sequence[int]], *,
               max_new_tokens: int = 32, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0, seed: int = 0,
               eos_id: Optional[int] = None,
               klass: Optional[str] = None,
               trace=None, request_id: Optional[str] = None
               ) -> GenerationStream:
        """Queue a request; returns its token stream immediately.
        ``klass="batch"`` rides the low-priority pending lane — freed
        slots go to interactive/default requests first. ``trace`` (if any)
        is attached BEFORE the stream is enqueued, so the engine loop never
        races a late trace assignment. ``request_id`` (journal-armed
        engines) makes the session durable: every emitted token is
        journaled, and a known id is a resume whose sequence numbers
        continue where the journal left off."""
        if isinstance(prompt, str):
            if self.codec is None:
                raise ValueError("string prompt needs a codec")
            ids = tuple(self.codec.encode(prompt))
        else:
            ids = tuple(int(t) for t in prompt)
        if not ids:
            raise ValueError("empty prompt")
        if len(ids) > self.max_len:
            raise ValueError(
                f"prompt length {len(ids)} exceeds max_len {self.max_len}")
        if (hasattr(self.adapter, "max_len")
                and len(ids) + max_new_tokens > self.max_len):
            # attention state is position-addressed (positional table + KV
            # ring): the whole stream must fit; recurrent carries don't care
            raise ValueError(
                f"prompt + max_new_tokens = {len(ids) + max_new_tokens} "
                f"exceeds max_len {self.max_len}")
        req = GenerationRequest(
            prompt=ids, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), seed=int(seed),
            eos_id=self.eos_id if eos_id is None else eos_id)
        stream = GenerationStream(req, request_id=request_id)
        stream.trace = trace
        with self._cond:
            if not self._accepting:
                raise RuntimeError("engine is shut down")
            if self.journal is not None and request_id is not None:
                # journal the admission before the stream is reachable by
                # the engine loop — a token can never precede its open line
                self.journal.attach(stream, klass=klass)
            if klass == "batch":
                self._pending_lo.append(stream)
            else:
                self._pending.append(stream)
            self._cond.notify_all()
        return stream

    def has_work(self) -> bool:
        return (bool(self._pending) or bool(self._pending_lo)
                or self.pool.occupancy() > 0)

    def pending_count(self) -> int:
        """Queued-but-not-yet-admitted requests across both priority lanes
        (the admission-control backlog signal)."""
        return len(self._pending) + len(self._pending_lo)

    # ---------------------------------------------------------- scheduler
    def _prefill_state(self, ids: Tuple[int, ...]):
        n = len(ids)
        if n == 1:
            return self.adapter.init_state(1)
        Tb = bucket_for(n - 1, self.buckets)
        padded = np.zeros((1, Tb), np.int32)
        padded[0, :n - 1] = ids[:-1]
        return self._prefill(self.net.params, self.net.state, padded,
                             np.int32(n - 1))

    def _admit(self) -> None:
        if not self.continuous and self.pool.occupancy() > 0:
            return  # static batching: wait for the whole batch to finish
        free = self.pool.free_slots()
        while free:
            with self._cond:
                # interactive/default lane first: a freed slot is never
                # given to queued batch work while higher-priority requests
                # are waiting
                if self._pending:
                    stream = self._pending.popleft()
                elif self._pending_lo:
                    stream = self._pending_lo.popleft()
                else:
                    return
            if stream.cancelled:
                self._finish_stream(stream, stream._cancel_reason)
                continue
            ids = stream.request.prompt
            t0 = time.monotonic()
            self._admitting = stream
            try:
                sub = self._prefill_state(ids)
            finally:
                self._admitting = None
            if stream.cancelled:
                # a shutdown/cancel landed DURING the prefill: retire now,
                # never paying the decode step the old code waited for
                self._finish_stream(stream, stream._cancel_reason)
                continue
            slot = free.pop(0)
            req = stream.request
            self.pool.admit(
                slot, sub, token=ids[-1], pos=len(ids) - 1, seed=req.seed,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, meta=stream)
            t1 = time.monotonic()
            mon = monitoring.generate_monitor()
            if mon is not None:
                mon.prefill_seconds.observe(t1 - t0)
            if stream.trace is not None:
                # queue_wait is retroactive (submit -> slot grant), exact
                # because both ends are monotonic instants
                stream.trace.add_span("queue_wait", stream.submitted_at, t0)
                stream.trace.add_span("prefill", t0, t1,
                                      prompt_len=len(ids))
                stream.trace.event("admit", slot=slot)

    def _finish_stream(self, stream: GenerationStream, reason: str) -> None:
        if self.journal is not None and stream.request_id is not None:
            self.journal.finished(stream, reason)
        stream._finish(reason)
        if stream.trace is not None:
            if stream.first_token_at is not None:
                # the aggregate decode span: first token -> finish, one
                # span regardless of token count
                stream.trace.add_span("decode", stream.first_token_at,
                                      stream.finished_at,
                                      tokens=len(stream.tokens))
            stream.trace.event("retire", reason=reason)
        mon = monitoring.generate_monitor()
        if mon is not None:
            mon.requests_total.labels(outcome=reason).inc()

    def _retire(self, slot: int, reason: str) -> None:
        stream = self.pool.retire(slot)
        self._finish_stream(stream, reason)

    def step(self) -> bool:
        """Admit + one decode step for the whole pool. Returns False when
        there was nothing to do. Single-driver only."""
        plan = faults.active()
        if plan is not None and plan.fires("preempt", step=self.steps_run):
            # the in-process SIGTERM-equivalent: hand off to the lifecycle
            # manager (which drains + journals from its own thread), or —
            # unmanaged — raise so the driver/loop performs a hard
            # self-preemption. Lazy import keeps `import ...generation`
            # free of the serving stack (import-graph guard).
            from deeplearning4j_tpu.serving import lifecycle
            lifecycle.deliver_preemption(source="generation",
                                         step=self.steps_run)
        self._admit()
        # sweep cancellations BEFORE the decode: a cancel that landed after
        # admission must not pay (or hold a slot through) a full step
        for s in self.pool.active_slots():
            st: GenerationStream = self.pool.meta[s]
            if st.cancelled:
                self._retire(s, st._cancel_reason)
        act = self.pool.active_slots()
        mon = monitoring.generate_monitor()
        if not act:
            if mon is not None:
                mon.slot_occupancy.set(0)
            return False
        pool = self.pool
        nxt, pool.state = self._decode(
            self.net.params, self.net.state, pool.state, pool.tokens,
            pool.pos, pool.seeds, pool.temps, pool.top_k, pool.top_p)
        nxt = np.asarray(nxt)
        now = time.monotonic()
        self.steps_run += 1
        for s in act:
            stream: GenerationStream = pool.meta[s]
            if stream.cancelled:
                self._retire(s, stream._cancel_reason)
                continue
            tok = int(nxt[s])
            pool.pos[s] += 1
            pool.tokens[s] = tok
            req = stream.request
            if req.eos_id is not None and tok == req.eos_id:
                self._retire(s, "eos")
                continue
            stream._emit(tok)
            if self.journal is not None and stream.request_id is not None:
                self.journal.emitted(stream, tok)
            if mon is not None:
                if stream.first_token_at is None:
                    mon.ttft_seconds.observe(
                        now - stream.submitted_at,
                        exemplar=({"trace_id": stream.trace.trace_id}
                                  if stream.trace is not None else None))
                elif stream._last_at is not None:
                    mon.inter_token_seconds.observe(now - stream._last_at)
            if stream.first_token_at is None:
                stream.first_token_at = now
            stream._last_at = now
            if len(stream.tokens) >= req.max_new_tokens:
                self._retire(s, "length")
        if mon is not None:
            mon.tokens_total.inc(len(act))
            mon.decode_steps_total.inc()
            mon.slot_occupancy.set(self.pool.occupancy())
        return True

    def drain(self, max_steps: Optional[int] = None) -> int:
        """Synchronous driver: step until idle (or ``max_steps``)."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    def generate(self, prompt, **kw) -> List[int]:
        """Convenience one-shot: submit + run to completion + tokens."""
        stream = self.submit(prompt, **kw)
        if self._thread is None:
            self.drain()
        return stream.result()

    # ----------------------------------------------------- background loop
    def start(self) -> "GenerationEngine":
        """Run the step loop in a daemon thread (the serving mode)."""
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="dl4j-generate", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._running and not self.has_work():
                    self._cond.wait(timeout=0.05)
                if not self._running and not self.has_work():
                    return
            try:
                self.step()
            except faults.PreemptionFault:
                # an injected preemption with no lifecycle manager: behave
                # like the process died mid-decode — retire everything as
                # "preempted" (journal records stay open for resume) and
                # stop the loop, leaving the engine shut down
                self._self_preempt()
                return

    def _self_preempt(self) -> None:
        """Hard in-loop preemption: runs ON the loop thread, so it must not
        join it — everything in flight finishes as ``preempted``."""
        with self._cond:
            self._accepting = False
            self._running = False
            pending = list(self._pending) + list(self._pending_lo)
            self._pending.clear()
            self._pending_lo.clear()
            self._cond.notify_all()
        for stream in pending:
            self._finish_stream(stream, "preempted")
        for s in self.pool.active_slots():
            self._retire(s, "preempted")
        self._thread = None

    def shutdown(self, timeout: float = 10.0,
                 reason: str = "cancelled") -> None:
        """Stop accepting, let in-flight streams finish up to ``timeout``
        seconds, then cancel whatever remains and stop the loop.

        ``reason="preempted"`` is the grace-budgeted preemption drain: the
        stragglers' terminal lines say ``preempted`` and — on journal-armed
        engines — their session records stay OPEN on disk, so a restarted
        engine resumes them (serving/lifecycle.py drives this path).
        """
        deadline = time.monotonic() + timeout
        with self._cond:
            self._accepting = False
            self._cond.notify_all()
        if self._thread is not None:
            while time.monotonic() < deadline and self.has_work():
                time.sleep(0.01)
        else:
            while time.monotonic() < deadline and self.has_work():
                self.step()
        # past the deadline: cancel stragglers (both priority lanes, plus
        # any stream caught mid-prefill — see _admit's post-prefill check)
        with self._cond:
            pending = list(self._pending) + list(self._pending_lo)
            self._pending = collections.deque()
            self._pending_lo = collections.deque()
        admitting = self._admitting
        if admitting is not None:
            admitting.cancel(reason)
        for stream in pending:
            self._finish_stream(stream, reason)
        for s in self.pool.active_slots():
            self.pool.meta[s].cancel(reason)
        if self._thread is not None:
            with self._cond:
                self._running = False
                self._cond.notify_all()
            self._thread.join(timeout=5.0)
            self._thread = None
        for s in self.pool.active_slots():
            self._retire(s, reason)
