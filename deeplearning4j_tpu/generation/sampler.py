"""Seeded token samplers for autoregressive decode.

Reference analog: the dl4j-examples char-RNN sampling loop
(GravesLSTMCharModellingExample.sampleCharactersFromNetwork: manual
softmax-CDF walk over Nd4j.getRandom()) — here lifted into shape-static,
jit-safe primitives so sampling lives INSIDE the one compiled decode step
(generation/engine.py) instead of on the host between steps.

Every knob is a per-row ARRAY, not a python branch: temperature <= 0 means
greedy (argmax), top_k <= 0 and top_p >= 1 disable their filters. That keeps
the decode program's shape signature constant no matter how requests mix
greedy/temperature/top-k/top-p — the whole slot pool samples in one fused
kernel, and the program compiles exactly once.

Determinism: keys derive from (per-request seed, absolute position) via
``fold_in``, so a request's token stream is a pure function of its seed and
prompt — replayable regardless of which slot it landed in or what was
co-batched with it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_keys(seeds, pos):
    """Per-row PRNG keys from (request seed, absolute position) — slot- and
    cohort-independent, so streams are replayable."""
    return jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p))(
        jnp.asarray(seeds, jnp.uint32), jnp.asarray(pos, jnp.int32))


def _sample_row(key, logits, temperature, top_k, top_p):
    """One row: greedy when temperature <= 0; else temperature-scaled
    categorical restricted by top-k ranks and the top-p nucleus."""
    V = logits.shape[-1]
    f32 = logits.astype(jnp.float32)
    neg = jnp.finfo(jnp.float32).min

    scaled = f32 / jnp.maximum(temperature, 1e-6)
    desc = jnp.sort(scaled)[::-1]
    # top-k: keep ranks < k (k <= 0 disables). Threshold at the k-th value:
    # ties at the boundary all stay in — a superset of k, never a subset.
    kth = jnp.where(top_k > 0, desc[jnp.clip(top_k - 1, 0, V - 1)], neg)
    keep = scaled >= kth

    # top-p nucleus over the top-k-filtered distribution: the smallest
    # probability-sorted prefix with cumulative mass >= p (p >= 1 disables).
    probs = jax.nn.softmax(jnp.where(keep, scaled, neg))
    pdesc = jnp.sort(probs)[::-1]
    csum = jnp.cumsum(pdesc)
    in_nucleus = (csum - pdesc) < jnp.minimum(top_p, 1.0)
    n_keep = jnp.maximum(in_nucleus.sum(), 1)
    pth = pdesc[n_keep - 1]
    keep = keep & (probs >= pth)

    sampled = jax.random.categorical(key, jnp.where(keep, scaled, neg))
    return jnp.where(temperature <= 0.0, jnp.argmax(f32), sampled).astype(
        jnp.int32)


def sample_logits(keys, logits, *, temperature, top_k, top_p):
    """Sample one token per row. logits [B, V]; keys [B] PRNG keys;
    temperature/top_p [B] float32; top_k [B] int32. Shape-static — safe
    inside a jitted decode step."""
    B = logits.shape[0]
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))
    p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))
    return jax.vmap(_sample_row)(keys, logits, t, k, p)
