"""Fixed-capacity slot pool: per-sequence decode state resident on device.

The pool is the continuous-batching engine's memory plan: ONE device pytree
whose every leaf has a leading ``[n_slots, ...]`` axis (KV ring buffers for
attention models, per-layer (h, c)/(h,) carries for recurrent ones), plus a
handful of tiny HOST-side numpy arrays (next token, absolute position,
sampler knobs) that ride into the jitted decode step as same-shape arguments
every call — so the step's signature, and therefore its compiled program,
never changes across the serving lifetime.

Admit/evict is row surgery on that tree, reusing the generic
``extract_carry_rows``/``merge_carry_rows`` helpers from ``nn/multilayer.py``
(the same machinery that backs ``rnn_set_carry_rows``). Admission always
scatters a slot's ENTIRE state row, so nothing a retired sequence left
behind can leak into a newcomer — witnessed by tests/test_generation.py.
Eviction is free: the host just marks the slot inactive; the stale device
row is dead weight until the next admit overwrites it.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.nn.multilayer import merge_carry_rows


class SlotPool:
    """``n_slots`` sequence slots: device state tree + host scheduling arrays.

    ``init_state(n_slots)`` builds the zeroed device tree (every leaf
    ``[n_slots, ...]``). Host arrays per slot: ``tokens`` (next input token),
    ``pos`` (absolute position of that token), ``active``, and the sampler
    knobs (``seeds``/``temps``/``top_k``/``top_p``) — all fixed-shape, so
    passing them into the jitted decode step never retraces.
    """

    def __init__(self, n_slots: int, init_state: Callable[[int], Any]):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        self.n_slots = n_slots
        self.state = init_state(n_slots)
        self.tokens = np.zeros((n_slots,), np.int32)
        self.pos = np.zeros((n_slots,), np.int32)
        self.active = np.zeros((n_slots,), bool)
        self.seeds = np.zeros((n_slots,), np.uint32)
        self.temps = np.zeros((n_slots,), np.float32)
        self.top_k = np.zeros((n_slots,), np.int32)
        self.top_p = np.ones((n_slots,), np.float32)
        self.meta: List[Optional[Any]] = [None] * n_slots
        # one jitted row scatter; rows always shape [1] -> one program total
        self._scatter = jax.jit(merge_carry_rows)

    # ------------------------------------------------------------ queries
    def free_slots(self) -> List[int]:
        return [i for i in range(self.n_slots) if not self.active[i]]

    def active_slots(self) -> List[int]:
        return [i for i in range(self.n_slots) if self.active[i]]

    def occupancy(self) -> int:
        return int(self.active.sum())

    # ----------------------------------------------------------- lifecycle
    def admit(self, slot: int, sub_state: Any, *, token: int, pos: int,
              seed: int, temperature: float, top_k: int, top_p: float,
              meta: Any = None) -> None:
        """Claim ``slot`` for a new sequence: overwrite its ENTIRE device
        state row with ``sub_state`` (leaves ``[1, ...]``, e.g. a prefill
        result) and set its host scheduling entries."""
        if self.active[slot]:
            raise ValueError(f"slot {slot} is occupied")
        self.state = self._scatter(self.state, sub_state,
                                   np.asarray([slot], np.int32))
        self.tokens[slot] = token
        self.pos[slot] = pos
        self.seeds[slot] = np.uint32(seed)
        self.temps[slot] = temperature
        self.top_k[slot] = top_k
        self.top_p[slot] = top_p
        self.meta[slot] = meta
        self.active[slot] = True

    def retire(self, slot: int) -> Any:
        """Release ``slot`` (host-side only — the device row is overwritten
        by the next admit). Returns the slot's meta."""
        meta, self.meta[slot] = self.meta[slot], None
        self.active[slot] = False
        return meta
