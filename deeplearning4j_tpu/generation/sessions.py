"""Durable generation sessions: the crash-recovery journal.

On a real TPU pod the dominant failure is a preemption — the process is
SIGTERM'd and every in-flight decode dies with it. This module makes that
survivable: a journal-armed :class:`GenerationEngine` appends one line per
session event to an append-only ndjson file, and after a restart
:meth:`SessionJournal.resume_into` re-submits every interrupted session with
``prompt + already-emitted tokens`` as the new prompt. Because sampler keys
are ``fold_in(seed, absolute_position)`` (generation/sampler.py) and slot
admission sets ``pos = len(prompt) - 1``, the resumed stream continues with
EXACTLY the keys the uninterrupted run would have used — the reconnect-
concatenated token sequence is bit-identical (tests/test_sessions.py holds
it to equality across several kill positions, including past a KV ring
wrap).

Journal format (one JSON object per line)::

    {"e":"open","id":R,"prompt":[...],"max_new":N,"temp":T,
     "top_k":K,"top_p":P,"seed":S,"eos":E,"klass":C,"t":...}
    {"e":"tok","id":R,"seq":n,"tok":t}      # n is 1-based and contiguous
    {"e":"fin","id":R,"reason":"eos"|"length"|"cancelled"}
    {"e":"res","id":R,"at":n}               # audit: session resumed at n

A session with no ``fin`` line is *interrupted* (the preemption path
deliberately never writes one — see ``GenerationEngine.shutdown``'s
``reason="preempted"``). A torn tail or a sequence gap marks the affected
session corrupt: it is never resumed, and a reconnect gets a clean 503
instead of silently wrong tokens (exactly-once beats at-least-once here).

Zero-overhead contract: an engine without an attached journal performs a
single ``is None`` check per touch point — no file, no locks (spy-guarded
in tests/test_sessions.py).

See docs/fault_tolerance.md ("Preemption & session recovery") for the
client reconnect contract (``X-Request-Id`` + ``last_seq``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight


class SessionRecord:
    """One journaled generation session: the durable request plus every
    token emitted so far. ``stream`` points at the live engine stream while
    one exists (reconnects follow it); ``corrupt``/``lost`` sessions answer
    503 on reconnect and are never resumed."""

    __slots__ = ("request_id", "prompt", "max_new_tokens", "temperature",
                 "top_k", "top_p", "seed", "eos_id", "klass", "tokens",
                 "finish_reason", "corrupt", "lost", "resumes", "stream",
                 "opened_at")

    def __init__(self, request_id: str, prompt, max_new_tokens: int,
                 temperature: float, top_k: int, top_p: float, seed: int,
                 eos_id: Optional[int], klass: Optional[str] = None):
        self.request_id = request_id
        self.prompt = tuple(int(t) for t in prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.eos_id = eos_id
        self.klass = klass
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.corrupt = False
        self.lost = False
        self.resumes = 0
        self.stream = None
        self.opened_at = time.time()

    @property
    def emitted(self) -> int:
        return len(self.tokens)

    @property
    def open(self) -> bool:
        """Interrupted-or-running: no terminal ``fin`` line yet."""
        return self.finish_reason is None and not self.corrupt

    def describe(self) -> dict:
        return {"request_id": self.request_id,
                "prompt_len": len(self.prompt),
                "emitted": self.emitted,
                "max_new_tokens": self.max_new_tokens,
                "finish_reason": self.finish_reason,
                "corrupt": self.corrupt, "lost": self.lost,
                "resumes": self.resumes,
                "live": self.stream is not None and not self.stream.done}


class SessionJournal:
    """Append-only session journal over one ndjson file.

        journal = SessionJournal(path)          # replays any existing file
        engine = GenerationEngine(net, journal=journal)
        ...crash/preempt...
        journal2 = SessionJournal(path)         # fresh process
        engine2 = GenerationEngine(net, journal=journal2).start()
        journal2.resume_into(engine2)           # before accepting traffic

    ``fsync=True`` fsyncs every line (preemption-grade durability);
    the default flushes to the OS per line, and :meth:`sync` (called by the
    lifecycle drain) forces the fsync at preemption time.
    """

    def __init__(self, path: str, fsync: bool = False):
        self.path = str(path)
        self.fsync = bool(fsync)
        self._lock = threading.RLock()
        self._records: Dict[str, SessionRecord] = {}
        self.corrupt_lines = 0
        self._replay()
        self._f = open(self.path, "a", encoding="utf-8")

    # ------------------------------------------------------------- replay
    def _tombstone(self, rid: str) -> SessionRecord:
        rec = SessionRecord(rid, (), 0, 0.0, 0, 1.0, 0, None)
        rec.corrupt = True
        return rec

    def _replay(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "r", encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    ev = json.loads(raw)
                    kind, rid = ev["e"], ev["id"]
                except Exception:
                    self.corrupt_lines += 1
                    continue
                if kind == "open":
                    try:
                        self._records[rid] = SessionRecord(
                            rid, ev["prompt"], ev["max_new"], ev["temp"],
                            ev["top_k"], ev["top_p"], ev["seed"],
                            ev.get("eos"), ev.get("klass"))
                    except Exception:
                        self.corrupt_lines += 1
                        self._records[rid] = self._tombstone(rid)
                elif kind == "tok":
                    rec = self._records.get(rid)
                    if rec is None:
                        self._records[rid] = self._tombstone(rid)
                        continue
                    if rec.corrupt:
                        continue
                    if ev.get("seq") != rec.emitted + 1:
                        rec.corrupt = True  # gap: token tally unprovable
                        continue
                    rec.tokens.append(int(ev["tok"]))
                elif kind == "fin":
                    rec = self._records.get(rid)
                    if rec is None:
                        self._records[rid] = self._tombstone(rid)
                    else:
                        rec.finish_reason = ev.get("reason") or "length"
                elif kind == "res":
                    rec = self._records.get(rid)
                    if rec is not None:
                        rec.resumes += 1
                else:
                    self.corrupt_lines += 1
        if self.corrupt_lines:
            # a torn tail could have swallowed token lines of ANY session
            # still open at crash time — their tallies are unprovable, and
            # resuming from a wrong position would produce silently wrong
            # tokens. Finished sessions keep replaying: their fin line
            # proves the tally was complete when written.
            for rec in self._records.values():
                if rec.finish_reason is None:
                    rec.corrupt = True

    # -------------------------------------------------------------- write
    def _write(self, ev: dict) -> None:
        line = json.dumps(ev, separators=(",", ":"))
        self._f.write(line + "\n")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())

    def sync(self) -> None:
        """Force everything journaled so far onto disk (the lifecycle
        manager calls this inside the preemption grace budget)."""
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            self._f.close()

    # ---------------------------------------------------- engine-side API
    def attach(self, stream, klass: Optional[str] = None) -> SessionRecord:
        """Bind a just-submitted stream to its session record; called by
        ``GenerationEngine.submit`` on journal-armed engines. A known
        request id is a RESUME: the stream's sequence numbers continue
        where the journal left off (``stream.seq0``)."""
        rid = stream.request_id
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                req = stream.request
                rec = SessionRecord(
                    rid, req.prompt, req.max_new_tokens, req.temperature,
                    req.top_k, req.top_p, req.seed, req.eos_id, klass)
                self._records[rid] = rec
                self._write({"e": "open", "id": rid,
                             "prompt": list(req.prompt),
                             "max_new": req.max_new_tokens,
                             "temp": req.temperature, "top_k": req.top_k,
                             "top_p": req.top_p, "seed": req.seed,
                             "eos": req.eos_id, "klass": klass,
                             "t": time.time()})
            else:
                rec.resumes += 1
                self._write({"e": "res", "id": rid, "at": rec.emitted})
            stream.seq0 = rec.emitted
            rec.stream = stream
            return rec

    def emitted(self, stream, token: int) -> None:
        with self._lock:
            rec = self._records.get(stream.request_id)
            if rec is None or rec.finish_reason is not None:
                return
            rec.tokens.append(int(token))
            self._write({"e": "tok", "id": stream.request_id,
                         "seq": rec.emitted, "tok": int(token)})

    def finished(self, stream, reason: str) -> None:
        if reason == "preempted":
            # the whole point: a preempted session stays OPEN on disk so
            # the restarted engine resumes it
            return
        with self._lock:
            rec = self._records.get(stream.request_id)
            if rec is None or rec.finish_reason is not None:
                return
            rec.finish_reason = reason
            self._write({"e": "fin", "id": stream.request_id,
                         "reason": reason})

    # -------------------------------------------------------------- query
    def get(self, request_id: str) -> Optional[SessionRecord]:
        with self._lock:
            return self._records.get(request_id)

    def interrupted(self) -> List[SessionRecord]:
        """Sessions with no terminal line and a provable token tally —
        the resumable set."""
        with self._lock:
            return [r for r in self._records.values()
                    if r.finish_reason is None and not r.corrupt
                    and not r.lost]

    def describe(self) -> dict:
        with self._lock:
            recs = list(self._records.values())
        return {"path": self.path,
                "sessions": len(recs),
                "open": sum(1 for r in recs if r.open),
                "finished": sum(1 for r in recs
                                if r.finish_reason is not None),
                "corrupt": sum(1 for r in recs if r.corrupt),
                "lost": sum(1 for r in recs if r.lost),
                "corrupt_lines": self.corrupt_lines}

    # ------------------------------------------------------------- resume
    def resume_into(self, engine) -> dict:
        """Re-submit every interrupted session into ``engine`` (call after
        ``start()`` and BEFORE accepting new traffic). The resumed prompt
        is ``original prompt + emitted tokens``, the token budget is the
        unspent remainder, and the sampler seed is unchanged — admission
        sets ``pos = len(prompt) - 1``, so the next sampler key is
        ``fold_in(seed, pos)`` exactly as in the uninterrupted run.

        Returns ``{"resumed", "lost", "completed"}``; outcomes land in
        ``dl4j_recovery_total{component="generation"}`` and one
        ``session_resume`` flight event summarizes the pass.
        """
        mon = monitoring.recovery_monitor()
        resumed = lost = completed = 0
        for rec in self.interrupted():
            remaining = rec.max_new_tokens - rec.emitted
            if remaining <= 0:
                # crashed between the final token and its fin line: the
                # session is actually complete — close it for replay
                with self._lock:
                    if rec.finish_reason is None:
                        rec.finish_reason = "length"
                        self._write({"e": "fin", "id": rec.request_id,
                                     "reason": "length"})
                completed += 1
                continue
            try:
                engine.submit(
                    rec.prompt + tuple(rec.tokens),
                    max_new_tokens=remaining, temperature=rec.temperature,
                    top_k=rec.top_k, top_p=rec.top_p, seed=rec.seed,
                    eos_id=rec.eos_id, klass=rec.klass,
                    request_id=rec.request_id)
                resumed += 1
                outcome = "session_resumed"
            except (ValueError, RuntimeError):
                rec.lost = True
                lost += 1
                outcome = "session_lost"
            if mon is not None:
                mon.recovery_total.labels(component="generation",
                                          outcome=outcome).inc()
        rec_flight = flight.recorder()
        if rec_flight is not None and (resumed or lost or completed):
            rec_flight.record("session_resume", resumed=resumed, lost=lost,
                              completed=completed, path=self.path)
        return {"resumed": resumed, "lost": lost, "completed": completed}


__all__ = ["SessionJournal", "SessionRecord"]
