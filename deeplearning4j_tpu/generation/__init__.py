"""Continuous-batching text-generation engine.

Autoregressive decode as a first-class serving workload: a fixed-capacity
slot pool of per-sequence device state (KV ring buffers for causal
transformers, layer carries for LSTM/GRU stacks), ONE compiled decode step
replayed for the whole serving lifetime (the PyGraph lever, witnessed by
``GenerationEngine.decode_programs``), continuous admission/retirement so
mixed-length streams never degrade to run-to-completion batching, and
pow2-bucketed prefill. The serving gateway streams it at
``POST /v1/<name>/generate`` (serving/generate.py).

See docs/generation.md for architecture, sampler knobs, and the slot-pool
sizing runbook.
"""

from deeplearning4j_tpu.generation.codec import CharCodec
from deeplearning4j_tpu.generation.engine import (
    AttentionDecodeAdapter, GenerationEngine, GenerationRequest,
    GenerationStream, RecurrentDecodeAdapter,
)
from deeplearning4j_tpu.generation.sampler import sample_keys, sample_logits
from deeplearning4j_tpu.generation.sessions import SessionJournal, SessionRecord
from deeplearning4j_tpu.generation.slots import SlotPool

__all__ = [
    "AttentionDecodeAdapter", "CharCodec", "GenerationEngine",
    "GenerationRequest", "GenerationStream", "RecurrentDecodeAdapter",
    "SessionJournal", "SessionRecord", "SlotPool",
    "sample_keys", "sample_logits",
]
