"""Character codec for char-RNN style generation.

Reference analog: dl4j-examples' CharacterIterator — the fixed character
alphabet the GravesLSTM char-modelling example indexes into. The engine is
token-id native; a codec is only the string boundary the HTTP route and
examples use.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence


class CharCodec:
    """Bijective char <-> id mapping over a fixed alphabet. Unknown chars
    encode to ``unk_id`` (default: drop them, the CharacterIterator
    behaviour)."""

    def __init__(self, alphabet: Sequence[str], unk_id: int = -1):
        self.alphabet = list(alphabet)
        self.unk_id = unk_id
        self._to_id = {c: i for i, c in enumerate(self.alphabet)}
        if len(self._to_id) != len(self.alphabet):
            raise ValueError("alphabet has duplicate characters")

    @classmethod
    def ascii_printable(cls) -> "CharCodec":
        """The 95 printable ASCII chars + newline — a serviceable default
        alphabet for char-RNN demos."""
        return cls([chr(c) for c in range(32, 127)] + ["\n"])

    @property
    def vocab_size(self) -> int:
        return len(self.alphabet)

    def encode(self, text: str) -> List[int]:
        if self.unk_id < 0:
            return [self._to_id[c] for c in text if c in self._to_id]
        return [self._to_id.get(c, self.unk_id) for c in text]

    def decode(self, ids: Iterable[int]) -> str:
        n = len(self.alphabet)
        return "".join(self.alphabet[i] for i in ids if 0 <= i < n)
