"""Deterministic fault injection — failure as a first-class, testable input.

Reference analog (SURVEY.md §5 "Failure detection"): the reference gets its
fault coverage for free from Spark chaos (worker retry, RDD lineage) and
never needs to *simulate* failure. A TPU-native stack has no Spark between
it and the hardware, so this module makes every production failure mode an
injectable, seeded, reproducible event — the same philosophy PyGraph
(PAPERS.md) applies to failed CUDA-graph capture: a structured event with a
safe fallback path, never an abort.

Fault classes (the injection points that consume them in parentheses):

    ``ckpt_io``          checkpoint save/restore I/O error
                         (util.checkpoints.TrainingCheckpointer)
    ``ckpt_corrupt``     truncated/corrupted checkpoint payload on disk
                         (TrainingCheckpointer.save, post-commit)
    ``coord_connect``    coordinator-connect refusal
                         (parallel.distributed.initialize_distributed)
    ``collective_delay`` delayed sync round — a straggling worker
                         (parallel.spark local-SGD round supervisor)
    ``worker_crash``     sync-round worker loss
                         (parallel.spark local-SGD round supervisor)
    ``data_io``          dataset read error (datasets.iterators, mnist)
    ``infer_crash``      inference-worker crash (parallel.inference)
    ``slow_worker``      inference worker stalls for ``delay_s`` before
                         dispatching a batch — the latency half of chaos
                         testing (parallel.inference)
    ``traffic_spike``    load-generator burst trigger: clients/bench loops
                         that poll it multiply their request rate while it
                         fires (bench.py chaos, tests) — the faults
                         grammar drives the OFFERED load, not just the
                         serving side
    ``preempt``          in-process SIGTERM-equivalent at a chosen step
                         (``@step==N``): with a LifecycleManager installed
                         (serving.lifecycle) it runs the grace-budgeted
                         preemption drain; unmanaged it raises
                         :class:`PreemptionFault` so the driver dies
                         mid-decode exactly like a real preemption
                         (generation engine step loop, trainer fit loop)
    ``nan_grad``         NaNs written into the step's feature batch so the
                         gradients (and loss) go non-finite — the numeric
                         sentinel's hard-trip drill (fit_batch input path
                         via :func:`poison_batch`)
    ``loss_spike``       features scaled by 1e4: a huge-but-usually-finite
                         loss/gradient spike for the gnorm and z-score
                         screens (fit_batch input path)
    ``data_corrupt``     features overwritten with structured finite
                         garbage — the sneaky corruption that may pass
                         per-step screens and only derail later steps,
                         exercising rollback + bisection blame
                         (fit_batch input path)

Spec grammar (``DL4J_TPU_FAULTS`` env var or :func:`configure`)::

    spec     := entry (";" entry)*
    entry    := class ":" rate ["@" predicate]
    rate     := float in (0,1)  -> per-call probability (seeded RNG)
              | int >= 1        -> fire on the first N matching calls
    predicate:= var op number   with op in  == != >= <= > <
                (vars come from the injection point's context, e.g.
                 ``step``, ``round``, ``call``, ``worker``)

    DL4J_TPU_FAULTS="ckpt_io:0.3;collective_delay:2@step>10;worker_crash:1@round==3"

``DL4J_TPU_FAULTS_SEED`` (default 0) seeds the probability draws — the same
spec + seed + call sequence always injects the same faults.
``DL4J_TPU_FAULTS_DELAY_S`` (default 0.05) is the simulated straggler delay
for ``collective_delay``.

Zero-overhead contract (same as ``DL4J_TPU_MONITORING``): with no spec
configured, :func:`active` returns ``None`` and every injection point is a
single None check — no parsing, no RNG, no locks (tier-1 guard in
tests/test_faults.py).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import random
import threading
from collections import Counter as _Counter
from typing import Dict, List, Optional

from deeplearning4j_tpu.faults.retry import RetryPolicy  # noqa: F401 (re-export)

CLASSES = ("ckpt_io", "ckpt_corrupt", "coord_connect", "collective_delay",
           "worker_crash", "data_io", "infer_crash", "slow_worker",
           "traffic_spike", "preempt", "nan_grad", "loss_spike",
           "data_corrupt")

ENV_SPEC = "DL4J_TPU_FAULTS"
ENV_SEED = "DL4J_TPU_FAULTS_SEED"
ENV_DELAY = "DL4J_TPU_FAULTS_DELAY_S"


class InjectedFault(Exception):
    """Marker base: every exception raised by an injection point derives
    from it, so tests (and retry policies) can tell injected failures from
    organic ones."""


class CheckpointIOFault(InjectedFault, OSError):
    """Injected checkpoint save/restore I/O failure (``ckpt_io``)."""


class DataReadFault(InjectedFault, OSError):
    """Injected dataset read failure (``data_io``)."""


class CoordinatorConnectFault(InjectedFault, ConnectionRefusedError):
    """Injected coordinator connection refusal (``coord_connect``)."""


class InferenceWorkerCrash(InjectedFault, RuntimeError):
    """Injected inference-worker crash (``infer_crash``)."""


class PreemptionFault(InjectedFault, RuntimeError):
    """Injected preemption (``preempt``) with no lifecycle manager to
    deliver it to — the raising driver is expected to die (or self-preempt)
    exactly as a SIGTERM'd process would."""


_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
}


@dataclasses.dataclass
class FaultRule:
    """One parsed spec entry. ``rate`` < 1 is a per-call probability;
    >= 1 is an absolute fire budget over matching calls."""

    cls: str
    rate: float
    var: Optional[str] = None
    op: Optional[str] = None
    value: float = 0.0
    fired: int = 0
    calls: int = 0

    def matches(self, ctx: Dict[str, float]) -> bool:
        if self.var is None:
            return True
        v = ctx.get(self.var)
        if v is None:
            return False
        return _OPS[self.op](float(v), self.value)


def parse_spec(spec: str) -> List[FaultRule]:
    """Parse the ``cls:rate[@cond]`` grammar; raises ValueError with the
    offending entry on any malformed input."""
    rules: List[FaultRule] = []
    for raw in spec.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        if "@" in entry:
            head, cond = entry.split("@", 1)
        else:
            head, cond = entry, None
        try:
            cls, rate_s = head.split(":", 1)
        except ValueError:
            raise ValueError(f"fault spec entry {entry!r}: expected "
                             f"'class:rate[@cond]'") from None
        cls = cls.strip()
        if cls not in CLASSES:
            raise ValueError(f"fault spec entry {entry!r}: unknown class "
                             f"{cls!r} (known: {', '.join(CLASSES)})")
        try:
            rate = float(rate_s)
        except ValueError:
            raise ValueError(f"fault spec entry {entry!r}: rate {rate_s!r} "
                             f"is not a number") from None
        if rate <= 0:
            raise ValueError(f"fault spec entry {entry!r}: rate must be > 0")
        rule = FaultRule(cls=cls, rate=rate)
        if cond is not None:
            cond = cond.strip()
            for op in ("==", "!=", ">=", "<=", ">", "<"):  # longest first
                if op in cond:
                    var, val = cond.split(op, 1)
                    rule.var, rule.op = var.strip(), op
                    try:
                        rule.value = float(val)
                    except ValueError:
                        raise ValueError(
                            f"fault spec entry {entry!r}: predicate value "
                            f"{val.strip()!r} is not a number") from None
                    break
            else:
                raise ValueError(f"fault spec entry {entry!r}: predicate "
                                 f"{cond!r} has no comparison operator")
        rules.append(rule)
    return rules


class FaultPlan:
    """A configured, seeded set of fault rules. Thread-safe: injection
    points fire from worker threads (serving) and the main loop alike."""

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 delay_s: float = 0.05):
        self.rules = list(rules)
        self.seed = int(seed)
        self.delay_s = float(delay_s)
        self._rng = random.Random(self.seed)
        self._lock = threading.Lock()
        self.injected: _Counter = _Counter()   # fired count per class

    def fires(self, cls: str, **ctx) -> bool:
        """Decide (and consume budget) for one call at injection point
        ``cls``. Context vars feed the rule predicates; an auto ``call``
        var counts matching calls per rule (1-based)."""
        with self._lock:
            hit = False
            for rule in self.rules:
                if rule.cls != cls:
                    continue
                rule.calls += 1
                if "call" not in ctx:
                    ctx = dict(ctx, call=rule.calls)
                if not rule.matches(ctx):
                    continue
                if rule.rate < 1.0:
                    if self._rng.random() < rule.rate:
                        rule.fired += 1
                        hit = True
                        break
                elif rule.fired < int(rule.rate):
                    rule.fired += 1
                    hit = True
                    break
            if hit:
                self.injected[cls] += 1
        if hit:
            from deeplearning4j_tpu import monitoring

            mon = monitoring.recovery_monitor()
            if mon is not None:
                mon.faults_injected.labels(cls=cls).inc()
            rec = monitoring.flight.recorder()
            if rec is not None:
                rec.record("fault_injected", cls=cls,
                           **{k: v for k, v in ctx.items()
                              if isinstance(v, (int, float, str))})
        return hit

    def describe(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "delay_s": self.delay_s,
                "rules": [dataclasses.asdict(r) for r in self.rules],
                "injected": dict(self.injected),
            }


_PLAN: Optional[FaultPlan] = None


def active() -> Optional[FaultPlan]:
    """The installed plan, or None when fault injection is off — callers
    skip ALL injection work on None (the zero-overhead contract)."""
    return _PLAN


def configure(spec: Optional[str] = None, seed: Optional[int] = None,
              delay_s: Optional[float] = None) -> Optional[FaultPlan]:
    """Install a fault plan from a spec string (or the environment when
    ``spec`` is None). An empty/absent spec uninstalls. Returns the plan."""
    global _PLAN
    if spec is None:
        spec = os.environ.get(ENV_SPEC, "")
    if seed is None:
        seed = int(os.environ.get(ENV_SEED, "0") or 0)
    if delay_s is None:
        delay_s = float(os.environ.get(ENV_DELAY, "0.05") or 0.05)
    rules = parse_spec(spec) if spec else []
    _PLAN = FaultPlan(rules, seed=seed, delay_s=delay_s) if rules else None
    return _PLAN


def reset() -> None:
    """Back to the environment configuration (test isolation hook)."""
    configure(None)


def _poison_features(x, mode: str):
    """Return a poisoned copy of a features entry (host numpy). Multi-input
    lists/dicts (the ComputationGraph shape) poison their first float
    entry; integer features (token ids) are left alone — there is nothing
    numeric to corrupt before the embedding lookup."""
    import numpy as np

    if isinstance(x, dict):
        for k, v in x.items():
            p = _poison_features(v, mode)
            if p is not v:
                return {**x, k: p}
        return x
    if isinstance(x, (list, tuple)):
        for i, v in enumerate(x):
            p = _poison_features(v, mode)
            if p is not v:
                out = list(x)
                out[i] = p
                return out
        return x
    a = np.array(x, copy=True)
    if not np.issubdtype(a.dtype, np.floating) or a.size == 0:
        return x
    flat = a.reshape(-1)
    if mode == "nan_grad":
        flat[:: max(1, a.size // 4)] = np.nan
    elif mode == "loss_spike":
        flat *= 1e4
    else:  # data_corrupt: large, structured, FINITE garbage
        flat[:] = np.sign(flat + 0.5) * (np.abs(flat) * 97.0 + 31.0)
    return a


def poison_batch(plan: FaultPlan, x, y, step: int):
    """Train-step input-path injection for the numeric fault classes
    (``nan_grad`` / ``loss_spike`` / ``data_corrupt``). Called by the fit
    loops right after unpacking a batch, BEFORE the guardrail's replay
    ring records it — so a rollback replays the poisoned bytes exactly
    and the bisection can name them. Returns (x, y)."""
    for cls in ("nan_grad", "loss_spike", "data_corrupt"):
        if plan.fires(cls, step=step):
            x = _poison_features(x, cls)
    return x, y


@contextlib.contextmanager
def injected(spec: str, seed: int = 0, delay_s: float = 0.05):
    """Scoped programmatic injection::

        with faults.injected("ckpt_io:2") as plan:
            ...                       # first two checkpoint I/Os fail
        assert plan.injected["ckpt_io"] == 2
    """
    global _PLAN
    prev = _PLAN
    plan = FaultPlan(parse_spec(spec), seed=seed, delay_s=delay_s)
    _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = prev


# install from the environment at import (mirrors monitoring's env flag)
configure(None)

__all__ = [
    "CLASSES", "FaultPlan", "FaultRule", "RetryPolicy",
    "InjectedFault", "CheckpointIOFault", "DataReadFault",
    "CoordinatorConnectFault", "InferenceWorkerCrash", "PreemptionFault",
    "active", "configure", "injected", "parse_spec", "poison_batch",
    "reset",
]
