"""Shared retry policy: exponential backoff + jitter + deadline.

Reference analog (SURVEY.md §5): Spark's worker retry and the Aeron
parameter server's reconnect loops — the reference never exposes a policy
object because Spark owns it. Here the policy is explicit and shared by
every transient-failure site (coordinator connect, checkpoint I/O, dataset
reads), instrumented through ``monitoring.recovery_monitor()`` so every
retry and every recovery outcome lands in ``dl4j_recovery_total``.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type


class RetryDeadlineExceeded(Exception):
    """Raised when the policy's wall-clock deadline expires before an
    attempt succeeds; ``__cause__`` carries the last attempt's error."""


class RetryPolicy:
    """Exponential backoff with jitter, bounded by attempts AND deadline.

        policy = RetryPolicy(max_attempts=4, base_delay_s=0.05)
        out = policy.call(flaky_fn, arg, component="checkpoint")

    ``retry_on``: exception types treated as transient; anything else
    propagates immediately. The ``component`` label threads through to
    ``dl4j_retry_attempts_total{component}`` and
    ``dl4j_recovery_total{component,outcome}`` (outcomes: ``retried_ok``
    when an attempt after the first succeeds, ``gave_up`` when the budget
    runs out).
    """

    def __init__(self, max_attempts: int = 4, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, deadline_s: float = 30.0,
                 jitter: float = 0.5,
                 retry_on: Tuple[Type[BaseException], ...] = (
                     OSError, ConnectionError, TimeoutError),
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.deadline_s = float(deadline_s)
        self.jitter = float(jitter)
        self.retry_on = tuple(retry_on)
        self._rng = random.Random(seed)
        self._sleep = sleep

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based): exponential, capped,
        with multiplicative jitter in [1, 1+jitter)."""
        d = min(self.max_delay_s, self.base_delay_s * (2.0 ** (attempt - 1)))
        return d * (1.0 + self.jitter * self._rng.random())

    def call(self, fn: Callable, *args, component: str = "",
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             **kw):
        """Run ``fn(*args, **kw)`` under the policy. ``on_retry(attempt,
        error)`` fires before each backoff sleep."""
        from deeplearning4j_tpu import monitoring

        start = time.monotonic()
        attempt = 0
        while True:
            try:
                out = fn(*args, **kw)
            except self.retry_on as e:
                attempt += 1
                mon = monitoring.recovery_monitor()
                if mon is not None:
                    mon.retry_attempts.labels(component=component).inc()
                delay = self.delay_for(attempt)
                exhausted = attempt >= self.max_attempts
                past_deadline = (time.monotonic() - start + delay
                                 > self.deadline_s)
                if exhausted or past_deadline:
                    if mon is not None:
                        mon.recovery_total.labels(
                            component=component, outcome="gave_up").inc()
                    if past_deadline and not exhausted:
                        raise RetryDeadlineExceeded(
                            f"{component or 'operation'} still failing after "
                            f"{attempt} attempt(s) and "
                            f"{time.monotonic() - start:.2f}s") from e
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self._sleep(delay)
                continue
            if attempt > 0:
                mon = monitoring.recovery_monitor()
                if mon is not None:
                    mon.recovery_total.labels(
                        component=component, outcome="retried_ok").inc()
            return out
