"""Pretrained-weight converters: Keras-h5 / ONNX -> zoo model params.

Reference analog: org.deeplearning4j.zoo.ZooModel.initPretrained() — there
it downloads a DL4J-format zip; here (no egress) the converters produce that
zip from real framework artifacts, making ``init_pretrained`` true end to
end: convert once, restore anywhere.

Layout rules handled:
- Keras h5 (TF backend) conv kernels are HWIO — identical to ours (both
  frameworks are channels-last); BN moving stats go to layer STATE.
- ONNX (torch export) conv kernels are OIHW -> transposed to HWIO; Gemm
  weights are [out, in] (transB=1) -> transposed; the FIRST dense after a
  flatten permutes its input features from torch's C,H,W flatten order to
  our H,W,C order (the NCHW->NHWC pitfall).
- GravesLSTM-style gate reorder lives in the Keras importer
  (modelimport.keras handles i,f,c,o -> our gate order); reused here.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def keras_h5_to_zoo(h5_path: str, model,
                    name_map: Optional[Dict[str, str]] = None):
    """Load weights from a REAL keras h5 into an initialized zoo model.

    MultiLayerNetwork: keras weighted layers are matched to our weighted
    layers in order (architecture must align — the zoo builders mirror the
    canonical architectures). ComputationGraph: ``name_map`` maps our vertex
    name -> keras layer name; ResNet50's map is built in
    (resnet50_keras_map). Returns the model, weights loaded in place.
    """
    import h5py

    from deeplearning4j_tpu.modelimport.keras import (KerasModelImport,
                                                      h5_layer_order,
                                                      read_h5_layer_arrays)
    from deeplearning4j_tpu.nn.conf.graph import LayerVertex

    with h5py.File(h5_path, "r") as f:
        order = h5_layer_order(f)
        arrays = {n: read_h5_layer_arrays(f, n) for n in order}
        arrays = {n: ws for n, ws in arrays.items() if ws}

    if isinstance(model, MultiLayerNetwork):
        # creation order from the h5 layer_names attr (group iteration is
        # alphabetical, which would interleave layer types)
        knames = [n for n in order if n in arrays]
        ours = [(i, l) for i, l in enumerate(model.layers)
                if model.params[i]]
        if len(knames) != len(ours):
            raise ValueError(
                f"keras h5 has {len(knames)} weighted layers, model has "
                f"{len(ours)} — architectures do not align")
        for kname, (i, layer) in zip(knames, ours):
            KerasModelImport._copy_layer_weights(
                layer, model.params[i], model.state[i], arrays[kname])
        model._jit_cache.clear()
        return model

    # ComputationGraph
    if name_map is None:
        raise ValueError("ComputationGraph conversion needs name_map "
                         "(ours -> keras layer name)")
    uncovered = [n for n, p in model.params.items()
                 if p and n not in name_map]
    if uncovered:
        raise ValueError(f"name_map leaves weighted vertices unmapped "
                         f"(they would keep random init): {uncovered[:8]}")
    missing = []
    for ours_name, keras_name in name_map.items():
        vertex = model.conf.vertices.get(ours_name)
        if vertex is None or not isinstance(vertex, LayerVertex):
            missing.append(ours_name)
            continue
        ws = arrays.get(keras_name)
        if ws is None:
            missing.append(f"{ours_name} <- {keras_name}")
            continue
        if ours_name not in model.params:
            raise ValueError(f"vertex {ours_name!r} holds no params to load "
                             f"{keras_name!r} into")
        KerasModelImport._copy_layer_weights(
            vertex.layer, model.params[ours_name],
            model.state.get(ours_name, {}), ws)
    if missing:
        raise ValueError(f"unmapped layers: {missing[:8]}")
    model._jit_cache.clear()
    return model


def resnet50_keras_map() -> Dict[str, str]:
    """Our zoo ResNet50 vertex names -> keras.applications.ResNet50 layer
    names (stem conv1_*, stages conv{2..5}_block{1..N}_{0|1|2|3}_{conv|bn},
    head 'predictions')."""
    m = {"conv1": "conv1_conv", "bn1": "conv1_bn", "output": "predictions"}
    stages = [(64, 3), (128, 4), (256, 6), (512, 3)]
    for si, (_, blocks) in enumerate(stages):
        for bi in range(blocks):
            ours = f"s{si}b{bi}"
            keras = f"conv{si + 2}_block{bi + 1}"
            for suffix, knum in (("a", 1), ("b", 2), ("c", 3)):
                m[f"{ours}_conv{suffix}"] = f"{keras}_{knum}_conv"
                m[f"{ours}_bn{suffix}"] = f"{keras}_{knum}_bn"
            if bi == 0:
                m[f"{ours}_proj"] = f"{keras}_0_conv"
                m[f"{ours}_projbn"] = f"{keras}_0_bn"
    return m


# ---------------------------------------------------------------- ONNX path


def onnx_to_zoo(onnx_path: str, model,
                flatten_spatial: Optional[tuple] = None):
    """Load weights from a torch-exported ONNX file into a sequential
    (MultiLayerNetwork) CNN zoo model.

    Walks the ONNX graph in order collecting Conv/Gemm/BatchNormalization
    weights, converts OIHW->HWIO, [out,in]->[in,out], and permutes the first
    post-flatten dense from C,H,W to H,W,C feature order
    (``flatten_spatial`` = (H, W, C) at the flatten point; inferred from the
    model's preprocessors when omitted)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.modelimport.onnx import OnnxModelImport
    from deeplearning4j_tpu.nn.layers import (BatchNormalizationLayer,
                                              ConvolutionLayer, DenseLayer)

    imp = OnnxModelImport.import_model(onnx_path)
    inits = imp.initializers

    def node_ws(node):
        ws = [inits[i] for i in node.inputs if i in inits]
        if node.op == "MatMul" and ws:
            # torch decomposes Linear on >2-D input into MatMul + Add;
            # recover the bias from the consuming Add's initializer
            out = node.outputs[0]
            for n2 in imp.nodes:
                if n2.op == "Add" and out in n2.inputs:
                    ws += [inits[i] for i in n2.inputs if i in inits]
                    break
        return ws

    weighted = [(n, node_ws(n)) for n in imp.nodes
                if n.op in ("Conv", "Gemm", "BatchNormalization", "MatMul")]
    weighted = [(n, ws) for n, ws in weighted if ws]
    ours = [(i, l) for i, l in enumerate(model.layers) if model.params[i]]
    if len(weighted) != len(ours):
        raise ValueError(
            f"onnx has {len(weighted)} weighted nodes, model has "
            f"{len(ours)} weighted layers — architectures do not align")

    if flatten_spatial is None:
        flatten_spatial = _infer_flatten_spatial(model)

    seen_dense = False
    for (node, ws), (i, layer) in zip(weighted, ours):
        p = model.params[i]
        if node.op == "Conv":
            if not isinstance(layer, ConvolutionLayer):
                raise ValueError(f"layer {i} is not a conv")
            p["W"] = jnp.asarray(np.transpose(ws[0], (2, 3, 1, 0)))  # OIHW->HWIO
            if len(ws) > 1:
                if "b" not in p:
                    raise ValueError(f"conv layer {i} has no bias param but "
                                     f"the ONNX node carries one")
                p["b"] = jnp.asarray(ws[1])
        elif node.op == "BatchNormalization":
            if not isinstance(layer, BatchNormalizationLayer):
                raise ValueError(f"layer {i} is not batch norm")
            gamma, beta, mean, var = ws[:4]
            p["gamma"] = jnp.asarray(gamma)
            p["beta"] = jnp.asarray(beta)
            model.state[i]["mean"] = jnp.asarray(mean)
            model.state[i]["var"] = jnp.asarray(var)
        else:  # Gemm / MatMul
            if not isinstance(layer, DenseLayer):
                raise ValueError(f"layer {i} is not dense")
            W = ws[0]
            tb = node.attr("transB")
            if node.op == "Gemm" and tb is not None and tb.i:
                W = W.T  # [out, in] -> [in, out]
            if not seen_dense and flatten_spatial is not None:
                H, Wd, C = flatten_spatial
                if W.shape[0] == H * Wd * C:
                    # torch flattened C,H,W; our pipeline flattens H,W,C
                    W = (W.reshape(C, H, Wd, -1).transpose(1, 2, 0, 3)
                         .reshape(H * Wd * C, -1))
                seen_dense = True
            p["W"] = jnp.asarray(W)
            if len(ws) > 1:
                if "b" not in p:
                    raise ValueError(f"dense layer {i} has no bias param but "
                                     f"the ONNX node carries one")
                p["b"] = jnp.asarray(ws[1])
    model._jit_cache.clear()
    return model


def _infer_flatten_spatial(model):
    """(H, W, C) at the FlattenPreProcessor (CnnToFeedForward analog),
    from the resolved conf's per-layer input types."""
    for i in range(len(model.conf.layers)):
        pre = model.conf.preprocessors.get(i)
        if pre is not None and type(pre).__name__ == "FlattenPreProcessor":
            prev = (model.conf.layers[i - 1].output_type(
                model.conf.layer_input_types[i - 1]) if i
                else model.conf.input_type)
            if getattr(prev, "kind", None) == "cnn":
                return tuple(prev.shape)  # (h, w, c) NHWC
    return None


def save_pretrained(model, path: str):
    """Write the converted model as a restorable zip — the artifact
    ZooModel.init_pretrained() consumes."""
    from deeplearning4j_tpu.util.serialization import write_model

    write_model(model, path)
    return path
