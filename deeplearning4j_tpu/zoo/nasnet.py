"""NASNet-A (mobile-scale).

Reference analog: org.deeplearning4j.zoo.model.NASNet [UNVERIFIED in the
survey snapshot] — NASNet-A architecture built from repeated Normal and
Reduction cells of separable-conv / pooling branches combined by adds and a
final channel concat.

This is a faithful-in-structure, compact implementation: each cell uses the
NASNet-A branch pattern (sep3x3/sep5x5/avgpool/identity), with 1x1 "fit"
convs keeping branch channel counts equal so ElementWise adds compose.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    GlobalPoolingLayer, OutputLayer, SeparableConvolution2DLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import RMSProp
from deeplearning4j_tpu.zoo._blocks import cbr
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class NASNet(ZooModel):
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    penultimate_filters: int = 1056
    n_cells: int = 4  # normal cells per stack (NASNet-A mobile: 4)
    lr: float = 0.04
    dtype: str = "bf16"

    def _sep(self, g, name, inp, f, kernel, strides=(1, 1)):
        g.add_layer(name, SeparableConvolution2DLayer(
            n_out=f, kernel=kernel, strides=strides, activation="relu",
            has_bias=False), inp)
        return name

    def _fit(self, g, name, inp, f, strides=(1, 1)):
        return cbr(g, name, inp, f, (1, 1), strides=strides)

    def _normal_cell(self, g, name, x, f):
        """NASNet-A normal cell (compact): 4 combined branches, concat."""
        h = self._fit(g, f"{name}_h", x, f)
        b1a = self._sep(g, f"{name}_b1a", h, f, (3, 3))
        g.add_vertex(f"{name}_add1", ElementWiseVertex(op="add"), b1a, h)
        b2a = self._sep(g, f"{name}_b2a", h, f, (5, 5))
        b2b = self._sep(g, f"{name}_b2b", h, f, (3, 3))
        g.add_vertex(f"{name}_add2", ElementWiseVertex(op="add"), b2a, b2b)
        g.add_layer(f"{name}_avg", SubsamplingLayer(
            kernel=(3, 3), strides=(1, 1), padding="same",
            pooling_type="avg"), h)
        g.add_vertex(f"{name}_add3", ElementWiseVertex(op="add"),
                     f"{name}_avg", h)
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_add1",
                     f"{name}_add2", f"{name}_add3")
        return f"{name}_cat"

    def _reduction_cell(self, g, name, x, f):
        h = self._fit(g, f"{name}_h", x, f)
        b1 = self._sep(g, f"{name}_b1", h, f, (5, 5), strides=(2, 2))
        b2 = self._sep(g, f"{name}_b2", h, f, (7, 7), strides=(2, 2))
        g.add_vertex(f"{name}_add1", ElementWiseVertex(op="add"), b1, b2)
        g.add_layer(f"{name}_maxp", SubsamplingLayer(
            kernel=(3, 3), strides=(2, 2), padding="same",
            pooling_type="max"), h)
        b3 = self._sep(g, f"{name}_b3", h, f, (3, 3), strides=(2, 2))
        g.add_vertex(f"{name}_add2", ElementWiseVertex(op="add"),
                     f"{name}_maxp", b3)
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_add1",
                     f"{name}_add2")
        return f"{name}_cat"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(RMSProp(lr=self.lr))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        f = self.penultimate_filters // 24  # NASNet convention
        prev = cbr(g, "stem", "input", 32, (3, 3), strides=(2, 2))
        prev = self._reduction_cell(g, "stem_r1", prev, f)
        prev = self._reduction_cell(g, "stem_r2", prev, f * 2)
        for i in range(self.n_cells):
            prev = self._normal_cell(g, f"n1_{i}", prev, f * 2)
        prev = self._reduction_cell(g, "r1", prev, f * 4)
        for i in range(self.n_cells):
            prev = self._normal_cell(g, f"n2_{i}", prev, f * 4)
        prev = self._reduction_cell(g, "r2", prev, f * 8)
        for i in range(self.n_cells):
            prev = self._normal_cell(g, f"n3_{i}", prev, f * 8)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), prev)
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation="softmax", loss="mcxent"),
                    "gap")
        g.set_outputs("output")
        return g.build()
