"""AlexNet (org.deeplearning4j.zoo.model.AlexNet — the one-tower variant)."""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, DenseLayer, LocalResponseNormalizationLayer, OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class AlexNet(ZooModel):
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    lr: float = 1e-2
    dtype: str = "float32"

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Nesterovs(lr=self.lr, momentum=0.9))
            .data_type(self.dtype)
            .list()
            .layer(ConvolutionLayer(n_out=96, kernel=(11, 11), strides=(4, 4),
                                    padding="truncate", activation="relu"))
            .layer(LocalResponseNormalizationLayer())
            .layer(SubsamplingLayer(kernel=(3, 3), strides=(2, 2), pooling_type="max"))
            .layer(ConvolutionLayer(n_out=256, kernel=(5, 5), padding="same",
                                    activation="relu"))
            .layer(LocalResponseNormalizationLayer())
            .layer(SubsamplingLayer(kernel=(3, 3), strides=(2, 2), pooling_type="max"))
            .layer(ConvolutionLayer(n_out=384, kernel=(3, 3), activation="relu"))
            .layer(ConvolutionLayer(n_out=384, kernel=(3, 3), activation="relu"))
            .layer(ConvolutionLayer(n_out=256, kernel=(3, 3), activation="relu"))
            .layer(SubsamplingLayer(kernel=(3, 3), strides=(2, 2), pooling_type="max"))
            .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )
