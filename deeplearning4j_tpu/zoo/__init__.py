"""Model zoo.

Reference analog: deeplearning4j-zoo :: org.deeplearning4j.zoo.ZooModel and
org.deeplearning4j.zoo.model.{LeNet, AlexNet, SimpleCNN, VGG16, VGG19,
ResNet50, SqueezeNet, Darknet19, TinyYOLO, YOLO2, UNet, Xception,
InceptionResNetV1, NASNet, TextGenerationLSTM, ...}. Each zoo entry builds a ready-to-train model from
hyperparameters; pretrained-weight download is gated on network availability
(no egress here), so ``init_pretrained`` loads from a local path instead.
"""

from deeplearning4j_tpu.zoo.base import ZooModel
from deeplearning4j_tpu.zoo.lenet import LeNet
from deeplearning4j_tpu.zoo.alexnet import AlexNet
from deeplearning4j_tpu.zoo.simplecnn import SimpleCNN
from deeplearning4j_tpu.zoo.vgg import VGG16, VGG19
from deeplearning4j_tpu.zoo.resnet import ResNet50
from deeplearning4j_tpu.zoo.darknet import Darknet19, TinyYOLO, YOLO2
from deeplearning4j_tpu.zoo.squeezenet import SqueezeNet
from deeplearning4j_tpu.zoo.xception import Xception
from deeplearning4j_tpu.zoo.unet import UNet
from deeplearning4j_tpu.zoo.inception_resnet import InceptionResNetV1
from deeplearning4j_tpu.zoo.nasnet import NASNet
from deeplearning4j_tpu.zoo.textgen import TextGenerationLSTM, BidirectionalGravesLSTMCharRnn
from deeplearning4j_tpu.zoo.bert import Bert, BertBase

__all__ = [
    "ZooModel", "LeNet", "AlexNet", "SimpleCNN", "VGG16", "VGG19", "ResNet50",
    "Darknet19", "TinyYOLO", "YOLO2", "SqueezeNet", "Xception", "UNet",
    "InceptionResNetV1", "NASNet",
    "TextGenerationLSTM", "BidirectionalGravesLSTMCharRnn", "Bert", "BertBase",
]
