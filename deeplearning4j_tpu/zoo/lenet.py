"""LeNet — the BASELINE.json config-#1 model.

Reference analog: org.deeplearning4j.zoo.model.LeNet and the dl4j-examples
LenetMnistExample topology: conv5x5(20) -> maxpool2 -> conv5x5(50) ->
maxpool2 -> dense(500, relu) -> softmax(10).
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class LeNet(ZooModel):
    height: int = 28
    width: int = 28
    channels: int = 1
    num_classes: int = 10
    lr: float = 1e-3
    dtype: str = "float32"

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(lr=self.lr))
            .data_type(self.dtype)
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel=(5, 5), padding="same",
                                    activation="identity"))
            .layer(SubsamplingLayer(kernel=(2, 2), strides=(2, 2), pooling_type="max"))
            .layer(ConvolutionLayer(n_out=50, kernel=(5, 5), padding="same",
                                    activation="identity"))
            .layer(SubsamplingLayer(kernel=(2, 2), strides=(2, 2), pooling_type="max"))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(self.height, self.width,
                                                         self.channels))
            .build()
        )
