"""ResNet-50 — the BASELINE.json config-#2 / north-star model.

Reference analog: org.deeplearning4j.zoo.model.ResNet50 — a ComputationGraph
of bottleneck residual blocks (conv/identity shortcut via ElementWiseVertex
add), conv1 7x7/2 + maxpool, stages [3,4,6,3], avg-pool + softmax(1000).

TPU-first notes: NHWC layout throughout; BatchNorm after every conv; bf16
compute policy recommended for the MXU (``dtype="bf16"``); the whole graph
traces to one XLA program, so the residual DAG costs nothing at runtime.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalizationLayer, ConvolutionLayer, GlobalPoolingLayer,
    OutputLayer, SubsamplingLayer, ZeroPadding2DLayer,
)
from deeplearning4j_tpu.optimize.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class ResNet50(ZooModel):
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    lr: float = 0.1
    dtype: str = "bf16"

    def conf(self):
        g = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Nesterovs(lr=self.lr, momentum=0.9))
            .data_type(self.dtype)
            .graph_builder()
            .add_inputs("input")
            .set_input_types(
                input=InputType.convolutional(self.height, self.width, self.channels))
        )
        # stem
        g.add_layer("conv1", ConvolutionLayer(n_out=64, kernel=(7, 7), strides=(2, 2),
                                              padding="same", activation="identity",
                                              has_bias=False), "input")
        g.add_layer("bn1", BatchNormalizationLayer(), "conv1")
        g.add_layer("relu1", ActivationLayer(activation="relu"), "bn1")
        g.add_layer("pool1", SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                              padding="same", pooling_type="max"), "relu1")

        prev = "pool1"
        stages = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
        for si, (width, blocks, first_stride) in enumerate(stages):
            for bi in range(blocks):
                stride = first_stride if bi == 0 else 1
                prev = self._bottleneck(g, prev, f"s{si}b{bi}", width, stride,
                                        project=(bi == 0))
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), prev)
        g.add_layer("output", OutputLayer(n_out=self.num_classes, activation="softmax",
                                          loss="mcxent"), "avgpool")
        g.set_outputs("output")
        return g.build()

    def _bottleneck(self, g, prev, name, width, stride, project):
        """1x1 reduce -> 3x3 -> 1x1 expand(4w), shortcut add, relu."""

        def cbr(suffix, inp, n_out, kernel, strides, act="relu"):
            g.add_layer(f"{name}_conv{suffix}",
                        ConvolutionLayer(n_out=n_out, kernel=kernel, strides=strides,
                                         padding="same", activation="identity",
                                         has_bias=False), inp)
            g.add_layer(f"{name}_bn{suffix}", BatchNormalizationLayer(),
                        f"{name}_conv{suffix}")
            if act:
                g.add_layer(f"{name}_relu{suffix}", ActivationLayer(activation=act),
                            f"{name}_bn{suffix}")
                return f"{name}_relu{suffix}"
            return f"{name}_bn{suffix}"

        a = cbr("a", prev, width, (1, 1), (stride, stride))
        b = cbr("b", a, width, (3, 3), (1, 1))
        c = cbr("c", b, width * 4, (1, 1), (1, 1), act=None)

        if project:
            g.add_layer(f"{name}_proj",
                        ConvolutionLayer(n_out=width * 4, kernel=(1, 1),
                                         strides=(stride, stride), padding="same",
                                         activation="identity", has_bias=False), prev)
            g.add_layer(f"{name}_projbn", BatchNormalizationLayer(), f"{name}_proj")
            shortcut = f"{name}_projbn"
        else:
            shortcut = prev
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), c, shortcut)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
        return f"{name}_out"


def resnet50_pipeline_plan(model, input_shape):
    """Cut an inited ResNet-50 ComputationGraph at its four conv stage
    boundaries for :class:`~deeplearning4j_tpu.parallel.HeteroPipe`
    (r5, VERDICT r4 #4 — PP over the conv flagship).

    Returns (stage_name_lists, head_names, shapes):
    - stage_name_lists: four contiguous topological vertex slices (the stem
      folds into the first); each slice's only external input is the
      previous slice's output — the conv2/3/4/5 boundaries.
    - head_names: the replicated tail (global pool + classifier head).
    - shapes: per-example activation shapes [input, s1_in, s2_in, s3_in,
      pipeline_out] — what HeteroPipe needs for its padded ring buffer.

    ``input_shape``: per-example input, e.g. (32, 32, 3).
    """
    import jax
    import jax.numpy as jnp

    conf = model.conf
    order = [n for n in conf.topological_order
             if n not in conf.network_inputs]
    cuts = []
    for si in range(4):
        idx = max(i for i, n in enumerate(order)
                  if n.startswith(f"s{si}b"))
        cuts.append(idx)
    stages, start = [], 0
    for idx in cuts:
        stages.append(order[start:idx + 1])
        start = idx + 1
    head = order[start:]

    # activation shapes at the stage entries, via eval_shape (no FLOPs)
    acts = jax.eval_shape(
        lambda p, s, x: model._forward(p, s, {"input": x}, False, None)[0],
        model.params, model.state,
        jax.ShapeDtypeStruct((1,) + tuple(input_shape), jnp.float32))
    shapes = [tuple(input_shape)]
    for st in stages:
        shapes.append(tuple(acts[st[-1]].shape[1:]))
    return stages, head, shapes
