"""Shared building blocks for zoo architectures (conv-bn-act stacks)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalizationLayer, ConvolutionLayer,
)


def cbr(g, name, inp, n_out, kernel, strides=(1, 1), activation="relu",
        batch_norm=True, padding="same"):
    """conv -> [bn] -> activation on a graph builder; returns output vertex name."""
    g.add_layer(f"{name}_conv",
                ConvolutionLayer(n_out=n_out, kernel=kernel, strides=strides,
                                 padding=padding, activation="identity",
                                 has_bias=not batch_norm), inp)
    prev = f"{name}_conv"
    if batch_norm:
        g.add_layer(f"{name}_bn", BatchNormalizationLayer(), prev)
        prev = f"{name}_bn"
    if activation and activation != "identity":
        g.add_layer(f"{name}_act", ActivationLayer(activation=activation), prev)
        prev = f"{name}_act"
    return prev
