"""Inception-ResNet v1 (FaceNet-style).

Reference analog: org.deeplearning4j.zoo.model.InceptionResNetV1 — stem
convs, Inception-ResNet-A/B/C blocks (multi-branch convs merged on channels,
1x1 linear projection, scaled residual add via ScaleVertex + ElementWise
add), Reduction-A/B, global avg pool, bottleneck embedding and a center-loss
softmax head (used for face recognition).
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex, ScaleVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, CenterLossOutputLayer, ConvolutionLayer, DenseLayer,
    GlobalPoolingLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import RMSProp
from deeplearning4j_tpu.zoo._blocks import cbr
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class InceptionResNetV1(ZooModel):
    height: int = 160
    width: int = 160
    channels: int = 3
    num_classes: int = 1001
    embedding_size: int = 128
    blocks_a: int = 5
    blocks_b: int = 10
    blocks_c: int = 5
    lr: float = 0.1
    dtype: str = "bf16"

    # ------------------------------------------------------------- blocks
    def _residual(self, g, name, inp, branches, proj_filters, scale):
        """Merge branches -> 1x1 linear conv -> scale -> add -> relu."""
        g.add_vertex(f"{name}_cat", MergeVertex(), *branches)
        g.add_layer(f"{name}_proj",
                    ConvolutionLayer(n_out=proj_filters, kernel=(1, 1),
                                     activation="identity"), f"{name}_cat")
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), f"{name}_proj")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        g.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_relu"

    def _block_a(self, g, name, inp):  # input 256 ch
        b1 = cbr(g, f"{name}_b1", inp, 32, (1, 1))
        b2 = cbr(g, f"{name}_b2a", inp, 32, (1, 1))
        b2 = cbr(g, f"{name}_b2b", b2, 32, (3, 3))
        b3 = cbr(g, f"{name}_b3a", inp, 32, (1, 1))
        b3 = cbr(g, f"{name}_b3b", b3, 32, (3, 3))
        b3 = cbr(g, f"{name}_b3c", b3, 32, (3, 3))
        return self._residual(g, name, inp, [b1, b2, b3], 256, 0.17)

    def _block_b(self, g, name, inp):  # input 896 ch
        b1 = cbr(g, f"{name}_b1", inp, 128, (1, 1))
        b2 = cbr(g, f"{name}_b2a", inp, 128, (1, 1))
        b2 = cbr(g, f"{name}_b2b", b2, 128, (1, 7))
        b2 = cbr(g, f"{name}_b2c", b2, 128, (7, 1))
        return self._residual(g, name, inp, [b1, b2], 896, 0.10)

    def _block_c(self, g, name, inp):  # input 1792 ch
        b1 = cbr(g, f"{name}_b1", inp, 192, (1, 1))
        b2 = cbr(g, f"{name}_b2a", inp, 192, (1, 1))
        b2 = cbr(g, f"{name}_b2b", b2, 192, (1, 3))
        b2 = cbr(g, f"{name}_b2c", b2, 192, (3, 1))
        return self._residual(g, name, inp, [b1, b2], 1792, 0.20)

    def _reduction_a(self, g, name, inp):  # 256 -> 896
        g.add_layer(f"{name}_pool", SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                                     padding="same",
                                                     pooling_type="max"), inp)
        b2 = cbr(g, f"{name}_b2", inp, 384, (3, 3), strides=(2, 2))
        b3 = cbr(g, f"{name}_b3a", inp, 192, (1, 1))
        b3 = cbr(g, f"{name}_b3b", b3, 192, (3, 3))
        b3 = cbr(g, f"{name}_b3c", b3, 256, (3, 3), strides=(2, 2))
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_pool", b2, b3)
        return f"{name}_cat"

    def _reduction_b(self, g, name, inp):  # 896 -> 1792
        g.add_layer(f"{name}_pool", SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                                     padding="same",
                                                     pooling_type="max"), inp)
        b2 = cbr(g, f"{name}_b2a", inp, 256, (1, 1))
        b2 = cbr(g, f"{name}_b2b", b2, 384, (3, 3), strides=(2, 2))
        b3 = cbr(g, f"{name}_b3a", inp, 256, (1, 1))
        b3 = cbr(g, f"{name}_b3b", b3, 256, (3, 3), strides=(2, 2))
        b4 = cbr(g, f"{name}_b4a", inp, 256, (1, 1))
        b4 = cbr(g, f"{name}_b4b", b4, 256, (3, 3))
        b4 = cbr(g, f"{name}_b4c", b4, 256, (3, 3), strides=(2, 2))
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_pool", b2, b3, b4)
        return f"{name}_cat"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(RMSProp(lr=self.lr))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        # stem: 3x conv, maxpool, 2x conv, conv stride 2 -> 256 ch
        prev = cbr(g, "stem1", "input", 32, (3, 3), strides=(2, 2))
        prev = cbr(g, "stem2", prev, 32, (3, 3))
        prev = cbr(g, "stem3", prev, 64, (3, 3))
        g.add_layer("stem_pool", SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                                  padding="same",
                                                  pooling_type="max"), prev)
        prev = cbr(g, "stem4", "stem_pool", 80, (1, 1))
        prev = cbr(g, "stem5", prev, 192, (3, 3))
        prev = cbr(g, "stem6", prev, 256, (3, 3), strides=(2, 2))
        for i in range(self.blocks_a):
            prev = self._block_a(g, f"a{i}", prev)
        prev = self._reduction_a(g, "ra", prev)
        for i in range(self.blocks_b):
            prev = self._block_b(g, f"b{i}", prev)
        prev = self._reduction_b(g, "rb", prev)
        for i in range(self.blocks_c):
            prev = self._block_c(g, f"c{i}", prev)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), prev)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "gap")
        g.add_layer("output",
                    CenterLossOutputLayer(n_out=self.num_classes,
                                          activation="softmax", loss="mcxent",
                                          alpha=0.9, lambda_=2e-4), "bottleneck")
        g.set_outputs("output")
        return g.build()
