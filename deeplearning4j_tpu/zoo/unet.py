"""U-Net.

Reference analog: org.deeplearning4j.zoo.model.UNet — encoder/decoder with
skip connections: double-conv blocks, 2x2 maxpool down, 2x up-convolution,
channel concat (MergeVertex) with the mirrored encoder block, final 1x1 conv
to a sigmoid segmentation map trained with per-pixel XENT (CnnLossLayer).
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    CnnLossLayer, ConvolutionLayer, SubsamplingLayer, Upsampling2DLayer,
)
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class UNet(ZooModel):
    height: int = 512
    width: int = 512
    channels: int = 3
    out_channels: int = 1  # segmentation classes (1 = binary sigmoid map)
    base_filters: int = 64
    depth: int = 4
    lr: float = 1e-4
    dtype: str = "bf16"

    def _double_conv(self, g, name, inp, filters):
        g.add_layer(f"{name}_c1", ConvolutionLayer(n_out=filters, kernel=(3, 3),
                                                   activation="relu"), inp)
        g.add_layer(f"{name}_c2", ConvolutionLayer(n_out=filters, kernel=(3, 3),
                                                   activation="relu"), f"{name}_c1")
        return f"{name}_c2"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Adam(lr=self.lr))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        skips = []
        prev = "input"
        f = self.base_filters
        for d in range(self.depth):
            prev = self._double_conv(g, f"enc{d}", prev, f * (2 ** d))
            skips.append(prev)
            g.add_layer(f"down{d}", SubsamplingLayer(kernel=(2, 2), strides=(2, 2),
                                                     padding="same",
                                                     pooling_type="max"), prev)
            prev = f"down{d}"
        prev = self._double_conv(g, "bottleneck", prev, f * (2 ** self.depth))
        for d in reversed(range(self.depth)):
            g.add_layer(f"up{d}", Upsampling2DLayer(size=(2, 2)), prev)
            g.add_layer(f"upc{d}", ConvolutionLayer(n_out=f * (2 ** d), kernel=(2, 2),
                                                    activation="relu"), f"up{d}")
            g.add_vertex(f"cat{d}", MergeVertex(), skips[d], f"upc{d}")
            prev = self._double_conv(g, f"dec{d}", f"cat{d}", f * (2 ** d))
        g.add_layer("head", ConvolutionLayer(n_out=self.out_channels, kernel=(1, 1),
                                             activation="identity"), prev)
        g.add_layer("output", CnnLossLayer(activation="sigmoid", loss="xent"), "head")
        g.set_outputs("output")
        return g.build()
