"""Xception.

Reference analog: org.deeplearning4j.zoo.model.Xception — depthwise-separable
conv architecture: entry flow (conv stem + 3 strided residual sepconv
blocks), middle flow (8 residual sepconv blocks at 728 channels), exit flow
(sepconv 1024/1536/2048 + global pool + softmax). Residual shortcuts are 1x1
strided convs via ElementWiseVertex add.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalizationLayer, ConvolutionLayer,
    GlobalPoolingLayer, OutputLayer, SeparableConvolution2DLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import Nesterovs
from deeplearning4j_tpu.zoo._blocks import cbr
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class Xception(ZooModel):
    height: int = 299
    width: int = 299
    channels: int = 3
    num_classes: int = 1000
    middle_blocks: int = 8
    lr: float = 0.045
    dtype: str = "bf16"

    def _sep_bn(self, g, name, inp, n_out, pre_relu=True):
        prev = inp
        if pre_relu:
            g.add_layer(f"{name}_prerelu", ActivationLayer(activation="relu"), prev)
            prev = f"{name}_prerelu"
        g.add_layer(f"{name}_sep",
                    SeparableConvolution2DLayer(n_out=n_out, kernel=(3, 3),
                                                activation="identity",
                                                has_bias=False), prev)
        g.add_layer(f"{name}_bn", BatchNormalizationLayer(), f"{name}_sep")
        return f"{name}_bn"

    def _entry_block(self, g, name, inp, n_out, first_relu=True):
        """Two sepconv-bn + strided maxpool, with strided 1x1 conv shortcut."""
        a = self._sep_bn(g, f"{name}_s1", inp, n_out, pre_relu=first_relu)
        b = self._sep_bn(g, f"{name}_s2", a, n_out)
        g.add_layer(f"{name}_pool",
                    SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                     padding="same", pooling_type="max"), b)
        g.add_layer(f"{name}_short",
                    ConvolutionLayer(n_out=n_out, kernel=(1, 1), strides=(2, 2),
                                     activation="identity", has_bias=False), inp)
        g.add_layer(f"{name}_shortbn", BatchNormalizationLayer(), f"{name}_short")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"),
                     f"{name}_pool", f"{name}_shortbn")
        return f"{name}_add"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(lr=self.lr, momentum=0.9))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        prev = cbr(g, "stem1", "input", 32, (3, 3), strides=(2, 2))
        prev = cbr(g, "stem2", prev, 64, (3, 3))
        prev = self._entry_block(g, "entry1", prev, 128, first_relu=False)
        prev = self._entry_block(g, "entry2", prev, 256)
        prev = self._entry_block(g, "entry3", prev, 728)
        for i in range(self.middle_blocks):
            a = self._sep_bn(g, f"mid{i}_1", prev, 728)
            b = self._sep_bn(g, f"mid{i}_2", a, 728)
            c = self._sep_bn(g, f"mid{i}_3", b, 728)
            g.add_vertex(f"mid{i}_add", ElementWiseVertex(op="add"), c, prev)
            prev = f"mid{i}_add"
        # exit flow
        a = self._sep_bn(g, "exit_s1", prev, 728)
        b = self._sep_bn(g, "exit_s2", a, 1024)
        g.add_layer("exit_pool",
                    SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                     padding="same", pooling_type="max"), b)
        g.add_layer("exit_short",
                    ConvolutionLayer(n_out=1024, kernel=(1, 1), strides=(2, 2),
                                     activation="identity", has_bias=False), prev)
        g.add_layer("exit_shortbn", BatchNormalizationLayer(), "exit_short")
        g.add_vertex("exit_add", ElementWiseVertex(op="add"),
                     "exit_pool", "exit_shortbn")
        c = self._sep_bn(g, "exit_s3", "exit_add", 1536)
        g.add_layer("exit_r3", ActivationLayer(activation="relu"), c)
        d = self._sep_bn(g, "exit_s4", "exit_r3", 2048, pre_relu=False)
        g.add_layer("exit_r4", ActivationLayer(activation="relu"), d)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), "exit_r4")
        g.add_layer("output", OutputLayer(n_out=self.num_classes,
                                          activation="softmax", loss="mcxent"),
                    "gap")
        g.set_outputs("output")
        return g.build()
