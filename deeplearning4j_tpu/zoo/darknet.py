"""Darknet19, TinyYOLO, YOLO2.

Reference analog: org.deeplearning4j.zoo.model.{Darknet19, TinyYOLO, YOLO2} —
conv/bn/leaky-relu backbones; YOLO2 adds the passthrough (reorg) route:
a 1x1 conv on the higher-resolution feature map, space-to-depth, channel
concat with the deep path, then the detection head ending in
Yolo2OutputLayer with bounding-box priors.

TPU-first: NHWC, bf16-capable, whole net traces to one XLA program; the
space-to-depth reorg is a free layout op under XLA.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, GlobalPoolingLayer, LossLayer, SpaceToDepthLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
from deeplearning4j_tpu.optimize.updaters import Adam, Nesterovs
from deeplearning4j_tpu.zoo._blocks import cbr
from deeplearning4j_tpu.zoo.base import ZooModel

# Darknet-19 conv plan: (filters, kernel) per block, "M" = 2x2/2 maxpool
_DARKNET19 = [
    (32, 3), "M", (64, 3), "M",
    (128, 3), (64, 1), (128, 3), "M",
    (256, 3), (128, 1), (256, 3), "M",
    (512, 3), (256, 1), (512, 3), (256, 1), (512, 3), "M",
    (1024, 3), (512, 1), (1024, 3), (512, 1), (1024, 3),
]


def _darknet_trunk(g, inp, plan, prefix="dn"):
    prev, idx = inp, 0
    taps = {}
    for item in plan:
        if item == "M":
            g.add_layer(f"{prefix}_pool{idx}",
                        SubsamplingLayer(kernel=(2, 2), strides=(2, 2),
                                         padding="same", pooling_type="max"), prev)
            prev = f"{prefix}_pool{idx}"
        else:
            f, k = item
            prev = cbr(g, f"{prefix}{idx}", prev, f, (k, k), activation="leakyrelu")
        taps[idx] = prev
        idx += 1
    return prev, taps


@dataclasses.dataclass
class Darknet19(ZooModel):
    """org.deeplearning4j.zoo.model.Darknet19 — ImageNet classifier."""

    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    lr: float = 0.001
    dtype: str = "bf16"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Nesterovs(lr=self.lr, momentum=0.9))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        prev, _ = _darknet_trunk(g, "input", _DARKNET19)
        g.add_layer("head_conv",
                    ConvolutionLayer(n_out=self.num_classes, kernel=(1, 1),
                                     activation="identity"), prev)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), "head_conv")
        g.add_layer("output", LossLayer(activation="softmax", loss="mcxent"), "gap")
        g.set_outputs("output")
        return g.build()


# TinyYOLO default priors (PASCAL VOC, grid units) — matches the reference's
# TinyYOLO.DEFAULT_PRIOR_BOXES
_TINY_PRIORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11),
                (16.62, 10.52))
_YOLO2_PRIORS = ((0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
                 (7.88282, 3.52778), (9.77052, 9.16828))


@dataclasses.dataclass
class TinyYOLO(ZooModel):
    """org.deeplearning4j.zoo.model.TinyYOLO — tiny-yolov2 detector."""

    height: int = 416
    width: int = 416
    channels: int = 3
    n_classes: int = 20
    anchors: tuple = _TINY_PRIORS
    lr: float = 1e-3
    dtype: str = "bf16"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Adam(lr=self.lr))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        prev = "input"
        for i, f in enumerate([16, 32, 64, 128, 256]):
            prev = cbr(g, f"c{i}", prev, f, (3, 3), activation="leakyrelu")
            g.add_layer(f"p{i}", SubsamplingLayer(kernel=(2, 2), strides=(2, 2),
                                                  padding="same",
                                                  pooling_type="max"), prev)
            prev = f"p{i}"
        prev = cbr(g, "c5", prev, 512, (3, 3), activation="leakyrelu")
        prev = cbr(g, "c6", prev, 1024, (3, 3), activation="leakyrelu")
        prev = cbr(g, "c7", prev, 1024, (3, 3), activation="leakyrelu")
        n_filters = len(self.anchors) * (5 + self.n_classes)
        g.add_layer("det", ConvolutionLayer(n_out=n_filters, kernel=(1, 1),
                                            activation="identity"), prev)
        g.add_layer("output", Yolo2OutputLayer(anchors=tuple(self.anchors),
                                               n_classes=self.n_classes), "det")
        g.set_outputs("output")
        return g.build()


@dataclasses.dataclass
class YOLO2(ZooModel):
    """org.deeplearning4j.zoo.model.YOLO2 — Darknet19 trunk + passthrough."""

    height: int = 608
    width: int = 608
    channels: int = 3
    n_classes: int = 80
    anchors: tuple = _YOLO2_PRIORS
    lr: float = 1e-3
    dtype: str = "bf16"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Adam(lr=self.lr))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        prev, taps = _darknet_trunk(g, "input", _DARKNET19)
        # deep path: two more 3x3x1024 convs
        d = cbr(g, "e0", prev, 1024, (3, 3), activation="leakyrelu")
        d = cbr(g, "e1", d, 1024, (3, 3), activation="leakyrelu")
        # passthrough from the last 512-channel map before the final maxpool
        # (plan index 16 = conv output at 2x spatial resolution)
        pass_src = taps[16]
        pt = cbr(g, "pt", pass_src, 64, (1, 1), activation="leakyrelu")
        g.add_layer("reorg", SpaceToDepthLayer(block=2), pt)
        g.add_vertex("merge", MergeVertex(), "reorg", d)
        h = cbr(g, "e2", "merge", 1024, (3, 3), activation="leakyrelu")
        n_filters = len(self.anchors) * (5 + self.n_classes)
        g.add_layer("det", ConvolutionLayer(n_out=n_filters, kernel=(1, 1),
                                            activation="identity"), h)
        g.add_layer("output", Yolo2OutputLayer(anchors=tuple(self.anchors),
                                               n_classes=self.n_classes), "det")
        g.set_outputs("output")
        return g.build()
