"""SimpleCNN (org.deeplearning4j.zoo.model.SimpleCNN)."""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    BatchNormalizationLayer, ConvolutionLayer, DenseLayer, DropoutLayer,
    OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import AdaDelta
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class SimpleCNN(ZooModel):
    height: int = 48
    width: int = 48
    channels: int = 3
    num_classes: int = 10
    dtype: str = "float32"

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(AdaDelta())
            .data_type(self.dtype)
            .list()
        )
        for width in (16, 32, 64):
            b = (
                b.layer(ConvolutionLayer(n_out=width, kernel=(3, 3), activation="identity"))
                .layer(BatchNormalizationLayer())
                .layer(ConvolutionLayer(n_out=width, kernel=(3, 3), activation="relu"))
                .layer(SubsamplingLayer(kernel=(2, 2), strides=(2, 2), pooling_type="max"))
            )
        return (
            b.layer(DropoutLayer(rate=0.5))
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )
