"""Character-RNN LSTM models — BASELINE.json config #3.

Reference analog: org.deeplearning4j.zoo.model.TextGenerationLSTM and the
dl4j-examples GravesLSTMCharModellingExample (bidirectional Graves LSTM
char-RNN). On GPU the reference leaned on CudnnLSTMHelper; our scan-based
lstm_layer op (ops/recurrent.py) is the TPU equivalent, with the input
projection batched onto the MXU.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    GravesBidirectionalLSTMLayer, GravesLSTMLayer, LSTMLayer, RnnOutputLayer,
)
from deeplearning4j_tpu.optimize.updaters import Adam, RMSProp
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class TextGenerationLSTM(ZooModel):
    """org.deeplearning4j.zoo.model.TextGenerationLSTM: LSTM(256)x2 + RnnOutput."""

    vocab_size: int = 77
    units: int = 256
    timesteps: int = 64
    lr: float = 1e-3
    dtype: str = "float32"

    def conf(self):
        return (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(RMSProp(lr=self.lr))
            .data_type(self.dtype)
            .gradient_clipping(5.0)
            .list()
            .layer(LSTMLayer(n_out=self.units))
            .layer(LSTMLayer(n_out=self.units))
            .layer(RnnOutputLayer(n_out=self.vocab_size, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(self.vocab_size, self.timesteps))
            .build()
        )


@dataclasses.dataclass
class BidirectionalGravesLSTMCharRnn(ZooModel):
    """The BASELINE config-#3 topology: bidirectional Graves (peephole) LSTM
    stack + per-timestep softmax, one-hot char input."""

    vocab_size: int = 77
    units: int = 200
    timesteps: int = 64
    layers: int = 2
    lr: float = 1e-3
    dtype: str = "float32"

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Adam(lr=self.lr))
            .data_type(self.dtype)
            .gradient_clipping(5.0)
            .list()
        )
        for _ in range(self.layers):
            b = b.layer(GravesBidirectionalLSTMLayer(n_out=self.units))
        return (
            b.layer(RnnOutputLayer(n_out=self.vocab_size, activation="softmax",
                                   loss="mcxent"))
            .set_input_type(InputType.recurrent(self.vocab_size, self.timesteps))
            .build()
        )
