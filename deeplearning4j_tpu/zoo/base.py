"""ZooModel base.

Reference analog: org.deeplearning4j.zoo.ZooModel — init() builds an
untrained model; initPretrained() restores weights (from a local checkpoint
path here, since there is no egress).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ZooModel:
    seed: int = 123

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + initialize the untrained model (ZooModel.init)."""
        from deeplearning4j_tpu.nn.conf.builders import (
            ComputationGraphConfiguration, MultiLayerConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        c = self.conf()
        if isinstance(c, ComputationGraphConfiguration):
            return ComputationGraph(c).init(self.seed)
        return MultiLayerNetwork(c).init(self.seed)

    def init_pretrained(self, checkpoint_path: str):
        """ZooModel.initPretrained analog: restore weights from a local zip."""
        from deeplearning4j_tpu.util.serialization import restore_model

        return restore_model(checkpoint_path)
