"""BERT — BASELINE.json config #4.

Reference analog: the reference reaches BERT only via SameDiff TF-import
(nd4j samediff/bert fine-tune config, org.nd4j.imports). Here BERT-base is a
first-class zoo model: embedding + learned positions + N pre/post-norm
transformer encoder blocks + pooled classification head — all tracing to one
XLA program. The TF-import path (modelimport) can load checkpoint weights
into this topology.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    EmbeddingSequenceLayer, LastTimeStepLayer, LayerNormalizationLayer,
    OutputLayer, TransformerEncoderLayer,
)
from deeplearning4j_tpu.nn.layers.attention import PositionalEmbeddingLayer
from deeplearning4j_tpu.nn.layers.conv import GlobalPoolingLayer
from deeplearning4j_tpu.optimize.schedules import WarmupCosineSchedule
from deeplearning4j_tpu.optimize.updaters import AdamW
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class Bert(ZooModel):
    """Configurable BERT encoder for sequence classification fine-tuning."""

    vocab_size: int = 30522
    max_len: int = 128
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    num_classes: int = 2
    dropout: float = 0.1
    lr: float = 2e-5
    warmup: int = 1000
    total_steps: int = 100000
    dtype: str = "bf16"

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(AdamW(lr=WarmupCosineSchedule(peak_value=self.lr,
                                                   warmup_steps=self.warmup,
                                                   total_steps=self.total_steps)))
            .data_type(self.dtype)
            .gradient_clipping(1.0)
            .list()
            .layer(EmbeddingSequenceLayer(n_in=self.vocab_size, n_out=self.d_model))
            .layer(PositionalEmbeddingLayer(max_len=self.max_len))
            .layer(LayerNormalizationLayer())
        )
        for _ in range(self.n_layers):
            b = b.layer(TransformerEncoderLayer(
                d_model=self.d_model, n_heads=self.n_heads, d_ff=self.d_ff,
                dropout_rate=self.dropout))
        return (
            b.layer(LayerNormalizationLayer())
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.recurrent(self.vocab_size, self.max_len))
            .build()
        )


@dataclasses.dataclass
class BertBase(Bert):
    """BERT-base hyperparameters (the samediff/bert fine-tune scale)."""
