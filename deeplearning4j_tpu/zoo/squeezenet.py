"""SqueezeNet v1.1.

Reference analog: org.deeplearning4j.zoo.model.SqueezeNet — fire modules
(1x1 squeeze, then parallel 1x1/3x3 expand concatenated on channels) via
MergeVertex; head = dropout, 1x1 conv to classes, global avg pool, softmax.
"""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, DropoutLayer, GlobalPoolingLayer, LossLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import Adam
from deeplearning4j_tpu.zoo.base import ZooModel


@dataclasses.dataclass
class SqueezeNet(ZooModel):
    height: int = 227
    width: int = 227
    channels: int = 3
    num_classes: int = 1000
    lr: float = 1e-3
    dtype: str = "bf16"

    def _fire(self, g, name, inp, squeeze, expand):
        g.add_layer(f"{name}_sq",
                    ConvolutionLayer(n_out=squeeze, kernel=(1, 1),
                                     activation="relu"), inp)
        g.add_layer(f"{name}_e1",
                    ConvolutionLayer(n_out=expand, kernel=(1, 1),
                                     activation="relu"), f"{name}_sq")
        g.add_layer(f"{name}_e3",
                    ConvolutionLayer(n_out=expand, kernel=(3, 3),
                                     activation="relu"), f"{name}_sq")
        g.add_vertex(f"{name}_cat", MergeVertex(), f"{name}_e1", f"{name}_e3")
        return f"{name}_cat"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(Adam(lr=self.lr))
             .data_type(self.dtype)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(input=InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("conv1", ConvolutionLayer(n_out=64, kernel=(3, 3),
                                              strides=(2, 2), activation="relu"),
                    "input")
        g.add_layer("pool1", SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                              padding="same",
                                              pooling_type="max"), "conv1")
        prev = self._fire(g, "fire2", "pool1", 16, 64)
        prev = self._fire(g, "fire3", prev, 16, 64)
        g.add_layer("pool3", SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                              padding="same",
                                              pooling_type="max"), prev)
        prev = self._fire(g, "fire4", "pool3", 32, 128)
        prev = self._fire(g, "fire5", prev, 32, 128)
        g.add_layer("pool5", SubsamplingLayer(kernel=(3, 3), strides=(2, 2),
                                              padding="same",
                                              pooling_type="max"), prev)
        prev = self._fire(g, "fire6", "pool5", 48, 192)
        prev = self._fire(g, "fire7", prev, 48, 192)
        prev = self._fire(g, "fire8", prev, 64, 256)
        prev = self._fire(g, "fire9", prev, 64, 256)
        g.add_layer("drop", DropoutLayer(rate=0.5), prev)
        g.add_layer("conv10", ConvolutionLayer(n_out=self.num_classes,
                                               kernel=(1, 1),
                                               activation="relu"), "drop")
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), "conv10")
        g.add_layer("output", LossLayer(activation="softmax", loss="mcxent"), "gap")
        g.set_outputs("output")
        return g.build()
