"""VGG16 / VGG19 (org.deeplearning4j.zoo.model.VGG16 / VGG19)."""

from __future__ import annotations

import dataclasses

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.optimize.updaters import Nesterovs
from deeplearning4j_tpu.zoo.base import ZooModel

_VGG16_BLOCKS = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
_VGG19_BLOCKS = [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]


@dataclasses.dataclass
class VGG16(ZooModel):
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    lr: float = 1e-2
    dtype: str = "float32"

    _blocks = _VGG16_BLOCKS

    def conf(self):
        b = (
            NeuralNetConfiguration.builder()
            .seed(self.seed)
            .updater(Nesterovs(lr=self.lr, momentum=0.9))
            .data_type(self.dtype)
            .list()
        )
        for width, reps in self._blocks:
            for _ in range(reps):
                b = b.layer(ConvolutionLayer(n_out=width, kernel=(3, 3), padding="same",
                                             activation="relu"))
            b = b.layer(SubsamplingLayer(kernel=(2, 2), strides=(2, 2), pooling_type="max"))
        return (
            b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
            .layer(OutputLayer(n_out=self.num_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(self.height, self.width, self.channels))
            .build()
        )


@dataclasses.dataclass
class VGG19(VGG16):
    _blocks = _VGG19_BLOCKS
