"""Vantage-point tree.

Reference analog: org.deeplearning4j.clustering.vptree.VPTree — metric-tree
k-NN used by BarnesHutTsne and the nearest-neighbors server. Host-side numpy
(tree search is pointer-chasing, not MXU work); distance options match the
reference ("euclidean", "cosine", "manhattan").
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np

# "cosine" is accepted but handled by normalizing + euclidean search in the
# constructor (cosine itself breaks the triangle inequality VP pruning needs)
_DISTANCES = {
    "euclidean": lambda a, b: np.linalg.norm(a - b, axis=-1),
    "manhattan": lambda a, b: np.abs(a - b).sum(axis=-1),
    "cosine": None,
}


class _Node:
    __slots__ = ("index", "radius", "inside", "outside")

    def __init__(self, index, radius=0.0, inside=None, outside=None):
        self.index = index
        self.radius = radius
        self.inside = inside
        self.outside = outside


class VPTree:
    def __init__(self, points: np.ndarray, distance: str = "euclidean",
                 seed: int = 0):
        self.points = np.asarray(points, np.float64)
        if distance not in _DISTANCES:
            raise ValueError(f"unknown distance {distance}")
        self.distance_name = distance
        # cosine distance breaks the triangle inequality VP pruning relies
        # on; search in euclidean space over normalized vectors instead
        # (||a-b||^2 = 2(1 - cos)) and convert distances back on return.
        if distance == "cosine":
            norms = np.maximum(np.linalg.norm(self.points, axis=1,
                                              keepdims=True), 1e-12)
            self.points = self.points / norms
            self._dist = _DISTANCES["euclidean"]
        else:
            self._dist = _DISTANCES[distance]
        self._rng = np.random.default_rng(seed)
        self.root = self._build(list(range(len(self.points))))

    def _build(self, idx: List[int]) -> Optional[_Node]:
        if not idx:
            return None
        if len(idx) == 1:
            return _Node(idx[0])
        vp = idx[self._rng.integers(len(idx))]
        rest = [i for i in idx if i != vp]
        d = self._dist(self.points[rest], self.points[vp])
        median = float(np.median(d))
        inside = [i for i, di in zip(rest, d) if di <= median]
        outside = [i for i, di in zip(rest, d) if di > median]
        return _Node(vp, median, self._build(inside), self._build(outside))

    def knn(self, query: np.ndarray, k: int = 1) -> Tuple[List[int], List[float]]:
        """k nearest neighbors: (indices, distances), nearest first
        (VPTree.search analog)."""
        query = np.asarray(query, np.float64)
        if self.distance_name == "cosine":
            query = query / max(np.linalg.norm(query), 1e-12)
        heap: List[Tuple[float, int]] = []  # max-heap via negated distance
        tau = [np.inf]

        def search(node: Optional[_Node]):
            if node is None:
                return
            d = float(self._dist(self.points[node.index], query))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d <= node.radius:
                search(node.inside)
                if d + tau[0] > node.radius:
                    search(node.outside)
            else:
                search(node.outside)
                if d - tau[0] <= node.radius:
                    search(node.inside)

        search(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        if self.distance_name == "cosine":
            return [i for _, i in out], [d * d / 2.0 for d, _ in out]
        return [i for _, i in out], [d for d, _ in out]
