"""k-d tree.

Reference analog: org.deeplearning4j.clustering.kdtree.KDTree (insert/
nearest/knn over axis-aligned splits, euclidean metric).
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index, axis, left=None, right=None):
        self.index = index
        self.axis = axis
        self.left = left
        self.right = right


class KDTree:
    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)
        self.dims = self.points.shape[1]
        self.root = self._build(list(range(len(self.points))), 0)

    def _build(self, idx: List[int], depth: int) -> Optional[_KDNode]:
        if not idx:
            return None
        axis = depth % self.dims
        idx = sorted(idx, key=lambda i: self.points[i, axis])
        mid = len(idx) // 2
        return _KDNode(idx[mid], axis,
                       self._build(idx[:mid], depth + 1),
                       self._build(idx[mid + 1:], depth + 1))

    def nearest(self, query: np.ndarray) -> Tuple[int, float]:
        idx, dist = self.knn(query, 1)
        return idx[0], dist[0]

    def knn(self, query: np.ndarray, k: int = 1) -> Tuple[List[int], List[float]]:
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []

        def search(node: Optional[_KDNode]):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - query))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = query[node.axis] - self.points[node.index, node.axis]
            near, far = (node.left, node.right) if diff <= 0 else (node.right, node.left)
            search(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                search(far)

        search(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in out], [d for d, _ in out]
