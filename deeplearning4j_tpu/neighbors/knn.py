"""Brute-force k-NN on device.

Reference analog: the nearest-neighbors server's exhaustive path
(deeplearning4j-nearestneighbors-server). TPU-first: one jitted
[Q, D] x [D, N] distance computation + top-k — the MXU makes exhaustive
search the fast path for N into the millions, replacing tree traversal.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def _knn(points, queries, k, metric):
    if metric == "cosine":
        p = points / jnp.maximum(jnp.linalg.norm(points, axis=1, keepdims=True), 1e-12)
        q = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-12)
        d = 1.0 - q @ p.T
    elif metric == "euclidean":
        # ||q - p||^2 = ||q||^2 - 2 q·p + ||p||^2 (one matmul)
        qq = (queries * queries).sum(1, keepdims=True)
        pp = (points * points).sum(1)
        d = jnp.sqrt(jnp.maximum(qq - 2.0 * queries @ points.T + pp, 0.0))
    elif metric == "manhattan":
        d = jnp.abs(queries[:, None, :] - points[None, :, :]).sum(-1)
    else:
        raise ValueError(f"unknown metric {metric}")
    neg_d, idx = jax.lax.top_k(-d, k)
    return idx, -neg_d


def knn_search(points, queries, k: int = 1, metric: str = "euclidean"):
    """Returns (indices [Q, k], distances [Q, k]), nearest first."""
    points = jnp.asarray(np.asarray(points, np.float32))
    queries = jnp.asarray(np.asarray(queries, np.float32))
    if queries.ndim == 1:
        queries = queries[None]
    idx, d = _knn(points, queries, k, metric)
    return np.asarray(idx), np.asarray(d)
