"""Nearest-neighbor search.

Reference analog: deeplearning4j-nearestneighbors-parent —
org.deeplearning4j.clustering.vptree.VPTree, org.deeplearning4j.clustering.
kdtree.KDTree, and the brute-force path used by the k-NN server. TPU-first
addition: a jitted brute-force search (one [Q, N] distance matmul on the
MXU) which on accelerators beats tree traversal for all but huge N — trees
remain for host-side/streaming use, matching the reference's API.
"""

from deeplearning4j_tpu.neighbors.vptree import VPTree
from deeplearning4j_tpu.neighbors.kdtree import KDTree
from deeplearning4j_tpu.neighbors.knn import knn_search

__all__ = ["VPTree", "KDTree", "knn_search"]
