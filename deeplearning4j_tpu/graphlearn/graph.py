"""Adjacency-list graph + random walks.

Reference analog: org.deeplearning4j.graph.graph.Graph and
org.deeplearning4j.graph.iterator.RandomWalkIterator.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class Graph:
    def __init__(self, n_vertices: int, directed: bool = False):
        self.n = n_vertices
        self.directed = directed
        self.adj: List[List[int]] = [[] for _ in range(n_vertices)]

    @classmethod
    def from_edges(cls, edges: Sequence[Tuple[int, int]],
                   n_vertices: Optional[int] = None,
                   directed: bool = False) -> "Graph":
        n = n_vertices or (max(max(a, b) for a, b in edges) + 1)
        g = cls(n, directed)
        for a, b in edges:
            g.add_edge(a, b)
        return g

    def add_edge(self, a: int, b: int):
        self.adj[a].append(b)
        if not self.directed:
            self.adj[b].append(a)

    def degree(self, v: int) -> int:
        return len(self.adj[v])

    def random_walks(self, walk_length: int, walks_per_vertex: int = 1,
                     seed: int = 0) -> List[List[int]]:
        """Uniform random walks from every vertex
        (RandomWalkIterator semantics; walks stop early at sinks)."""
        rng = np.random.default_rng(seed)
        walks = []
        for _ in range(walks_per_vertex):
            order = rng.permutation(self.n)
            for start in order:
                walk = [int(start)]
                v = int(start)
                for _ in range(walk_length - 1):
                    nbrs = self.adj[v]
                    if not nbrs:
                        break
                    v = int(nbrs[rng.integers(len(nbrs))])
                    walk.append(v)
                walks.append(walk)
        return walks
