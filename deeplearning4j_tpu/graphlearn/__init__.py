"""Graph (network) representation learning.

Reference analog: deeplearning4j-graph — org.deeplearning4j.graph.models.
deepwalk.DeepWalk, org.deeplearning4j.graph.graph.Graph, random-walk
iterators. ("graphlearn" to avoid clashing with nn.graph, the
ComputationGraph module.)
"""

from deeplearning4j_tpu.graphlearn.graph import Graph
from deeplearning4j_tpu.graphlearn.deepwalk import DeepWalk

__all__ = ["Graph", "DeepWalk"]
