"""DeepWalk — node embeddings from truncated random walks.

Reference analog: org.deeplearning4j.graph.models.deepwalk.DeepWalk —
random walks fed into skip-gram (the reference uses hierarchical softmax;
here negative sampling, reusing the Word2Vec jitted step — the TPU-first
batched variant of the same objective).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.graphlearn.graph import Graph
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class DeepWalk:
    def __init__(self, vector_size: int = 64, window: int = 5,
                 walk_length: int = 20, walks_per_vertex: int = 10,
                 negative: int = 5, epochs: int = 3,
                 learning_rate: float = 0.01, seed: int = 42):
        self.vector_size = vector_size
        self.window = window
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.negative = negative
        self.epochs = epochs
        self.lr = learning_rate
        self.seed = seed
        self._w2v: Optional[Word2Vec] = None
        self.n_vertices = 0

    def fit(self, graph: Graph) -> "DeepWalk":
        walks = graph.random_walks(self.walk_length, self.walks_per_vertex,
                                   seed=self.seed)
        sentences = [[str(v) for v in walk] for walk in walks]
        self._w2v = Word2Vec(vector_size=self.vector_size, window=self.window,
                             negative=self.negative, epochs=self.epochs,
                             learning_rate=self.lr, batch_size=256,
                             seed=self.seed)
        # walks are already token lists; Word2Vec passes lists through untokenized
        self._w2v.fit(sentences)
        self.n_vertices = graph.n
        return self

    def get_vertex_vector(self, v: int) -> Optional[np.ndarray]:
        return self._w2v.get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._w2v.similarity(str(a), str(b))

    def vertices_nearest(self, v: int, top: int = 10):
        return [int(w) for w in self._w2v.words_nearest(str(v), top)]
