"""StatsListener — the dashboard's data producer.

Reference analog: org.deeplearning4j.ui.stats.StatsListener — per-iteration
score, timing, parameter/gradient/update statistics (mean magnitude,
histograms), and system/memory info pushed into a StatsStorage. Host-side
observation of the jitted step's outputs; array statistics are computed on
device in one tiny jitted reduction then fetched.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import StatsStorage


def _tree_stats(tree, prefix: str) -> Dict[str, float]:
    import jax

    out = {}
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return out
    total, count = 0.0, 0
    for leaf in leaves:
        a = np.asarray(leaf, np.float32)
        total += float(np.abs(a).sum())
        count += a.size
    out[f"{prefix}_mean_magnitude"] = total / max(count, 1)
    return out


def _named_layers(model):
    """[(name, params_dict)] for MLN (indexed) or ComputationGraph (named)."""
    params = model.params
    if isinstance(params, dict):
        return [(k, v) for k, v in params.items() if v]
    return [(f"{i}_{type(l).__name__}", p)
            for i, (l, p) in enumerate(zip(model.layers, params)) if p]


def _flat(p) -> np.ndarray:
    import jax

    leaves = [np.asarray(x, np.float32).ravel()
              for x in jax.tree_util.tree_leaves(p)]
    return np.concatenate(leaves) if leaves else np.zeros(0, np.float32)


def _histogram(a: np.ndarray, bins: int = 40):
    # drop non-finite entries: a diverged model (NaN/inf weights) must not
    # crash the monitoring listener (np.histogram raises on non-finite range)
    a = a[np.isfinite(a)]
    if a.size == 0:
        return None
    lo, hi = float(a.min()), float(a.max())
    if hi <= lo:
        hi = lo + 1e-12
    counts, _ = np.histogram(a, bins=bins, range=(lo, hi))
    return {"min": lo, "max": hi, "counts": counts.tolist()}


class StatsListener(TrainingListener):
    """Collects per-iteration stats into a StatsStorage.

    ``update_frequency`` mirrors the reference's listenerFrequency: array
    statistics (param magnitudes) are sampled every N iterations; score and
    timing are recorded every iteration.
    """

    # samples param stats AT each iteration (deferred delivery would read
    # later weights), and its iteration timing assumes per-step callbacks
    needs_eager_score = True

    def __init__(self, storage: StatsStorage, session_id: str = "default",
                 update_frequency: int = 10, collect_param_stats: bool = True,
                 collect_histograms: bool = True,
                 collect_system_stats: bool = True):
        self.storage = storage
        self.session_id = session_id
        self.update_frequency = max(1, update_frequency)
        self.collect_param_stats = collect_param_stats
        # host RSS + device memory scalar series (the reference UI's
        # system page)
        self.collect_system_stats = collect_system_stats
        # per-layer weight + update histograms (the reference UI's model
        # page): updates are param DELTAS between successive samples — the
        # same quantity the reference charts as "updates" (lr*gradient
        # accumulated over the sampling window), computed host-side so the
        # jitted train step is untouched
        self.collect_histograms = collect_histograms
        self._last_time: Optional[float] = None
        self._prev_flat: Dict[str, np.ndarray] = {}

    def iteration_done(self, model, iteration: int, epoch: int, score: float):
        now = time.perf_counter()
        rec: Dict = {
            "session": self.session_id,
            "iteration": int(iteration),
            "epoch": int(epoch),
            "score": float(score),
            "timestamp": time.time(),
        }
        if self._last_time is not None:
            dt = now - self._last_time
            rec["iteration_time_ms"] = dt * 1e3
            if dt > 0:
                rec["iterations_per_sec"] = 1.0 / dt
        self._last_time = now
        if iteration % self.update_frequency == 0:
            if self.collect_system_stats:
                from deeplearning4j_tpu.common.sysmetrics import system_metrics

                rec.update(system_metrics())
            if self.collect_param_stats:
                rec.update(_tree_stats(model.params, "params"))
            if self.collect_histograms:
                hists: Dict = {}
                for name, p in _named_layers(model):
                    flat = _flat(p)
                    entry = {"w": _histogram(flat)}
                    prev = self._prev_flat.get(name)
                    if prev is not None and prev.shape == flat.shape:
                        entry["u"] = _histogram(flat - prev)
                    self._prev_flat[name] = flat
                    hists[name] = entry
                rec["histograms"] = hists
        self.storage.put(rec)

    def on_epoch_end(self, model, epoch: int):
        self.storage.put({"session": self.session_id, "epoch_end": int(epoch),
                          "iteration": -1, "timestamp": time.time()})
