"""StatsListener — the dashboard's data producer.

Reference analog: org.deeplearning4j.ui.stats.StatsListener — per-iteration
score, timing, parameter/gradient/update statistics (mean magnitude,
histograms), and system/memory info pushed into a StatsStorage. Host-side
observation of the jitted step's outputs; array statistics are computed on
device in one tiny jitted reduction then fetched.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import StatsStorage


def _tree_stats(tree, prefix: str) -> Dict[str, float]:
    import jax

    out = {}
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return out
    total, count = 0.0, 0
    for leaf in leaves:
        a = np.asarray(leaf, np.float32)
        total += float(np.abs(a).sum())
        count += a.size
    out[f"{prefix}_mean_magnitude"] = total / max(count, 1)
    return out


class StatsListener(TrainingListener):
    """Collects per-iteration stats into a StatsStorage.

    ``update_frequency`` mirrors the reference's listenerFrequency: array
    statistics (param magnitudes) are sampled every N iterations; score and
    timing are recorded every iteration.
    """

    def __init__(self, storage: StatsStorage, session_id: str = "default",
                 update_frequency: int = 10, collect_param_stats: bool = True):
        self.storage = storage
        self.session_id = session_id
        self.update_frequency = max(1, update_frequency)
        self.collect_param_stats = collect_param_stats
        self._last_time: Optional[float] = None

    def iteration_done(self, model, iteration: int, epoch: int, score: float):
        now = time.perf_counter()
        rec: Dict = {
            "session": self.session_id,
            "iteration": int(iteration),
            "epoch": int(epoch),
            "score": float(score),
            "timestamp": time.time(),
        }
        if self._last_time is not None:
            rec["iteration_time_ms"] = (now - self._last_time) * 1e3
        self._last_time = now
        if self.collect_param_stats and iteration % self.update_frequency == 0:
            rec.update(_tree_stats(model.params, "params"))
        self.storage.put(rec)

    def on_epoch_end(self, model, epoch: int):
        self.storage.put({"session": self.session_id, "epoch_end": int(epoch),
                          "iteration": -1, "timestamp": time.time()})
