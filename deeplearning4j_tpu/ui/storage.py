"""Stats storage backends.

Reference analog: org.deeplearning4j.ui.storage.{InMemoryStatsStorage,
FileStatsStorage} implementing the StatsStorage API the UI reads. Records
are flat dicts; FileStatsStorage appends JSONL (replacing mapdb).
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Dict, List, Optional


# bookkeeping fields that are not chartable scalar series
NON_SCALAR_KEYS = ("iteration", "epoch", "timestamp", "epoch_end",
                   "histograms")


class StatsStorage:
    def put(self, record: Dict) -> None:
        raise NotImplementedError

    def records(self, session_id: Optional[str] = None) -> List[Dict]:
        raise NotImplementedError

    def session_ids(self) -> List[str]:
        return sorted({r.get("session", "default") for r in self.records()})

    def scalars(self, key: str, session_id: Optional[str] = None):
        """(iteration, value) series for one scalar key."""
        out = [(r["iteration"], r[key]) for r in self.records(session_id)
               if key in r and r[key] is not None]
        return sorted(out)


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._records: List[Dict] = []
        self._lock = threading.Lock()

    def put(self, record: Dict) -> None:
        with self._lock:
            self._records.append(dict(record))

    def records(self, session_id=None) -> List[Dict]:
        with self._lock:
            rs = list(self._records)
        if session_id is not None:
            rs = [r for r in rs if r.get("session", "default") == session_id]
        return rs


class FileStatsStorage(StatsStorage):
    """Append-only JSONL file store.

    ``records`` keeps an in-process parse cache keyed by file offset: each
    call reads and parses only the bytes appended since the previous call,
    so the UI's 2-second /data poll stays O(new records) over a long
    training run instead of re-parsing the whole history every poll. An
    externally truncated/rewritten file (offset shrank) invalidates the
    cache and triggers a full re-read."""

    def __init__(self, path: str | Path):
        self._path = Path(path)
        self._lock = threading.Lock()
        self._path.parent.mkdir(parents=True, exist_ok=True)
        if not self._path.exists():
            self._path.touch()
        self._cache: List[Dict] = []
        self._cache_offset = 0
        self._tail = b""          # trailing partial line (no newline yet)

    def put(self, record: Dict) -> None:
        line = json.dumps(record)
        with self._lock:
            with open(self._path, "a") as f:
                f.write(line + "\n")

    def _read_from(self, offset: int, size: int):
        """Parse records in [offset, size); returns (records, new_tail).
        Raises on a complete-but-invalid JSON line."""
        with open(self._path, "rb") as f:
            f.seek(offset)
            chunk = (self._tail if offset == self._cache_offset else b"") \
                + f.read(size - offset)
        lines = chunk.split(b"\n")
        tail = lines.pop()                         # b"" when chunk ends in \n
        return [json.loads(l) for l in lines if l.strip()], tail

    def records(self, session_id=None) -> List[Dict]:
        with self._lock:
            size = self._path.stat().st_size
            if size < self._cache_offset:          # truncated/rotated
                self._cache, self._cache_offset, self._tail = [], 0, b""
            if size > self._cache_offset:
                try:
                    parsed, tail = self._read_from(self._cache_offset, size)
                    self._cache.extend(parsed)
                except ValueError:
                    # offset landed mid-record: the file was externally
                    # REWRITTEN to an equal-or-larger size. Recover with one
                    # full re-read; a genuinely corrupt file still raises
                    # here (no silent record drops).
                    self._cache, self._tail = [], b""
                    parsed, tail = self._read_from(0, size)
                    self._cache = parsed
                self._cache_offset = size
                self._tail = tail
            rs = list(self._cache)
        if session_id is not None:
            rs = [r for r in rs if r.get("session", "default") == session_id]
        return rs

    def export_csv(self, directory: str | Path) -> List[Path]:
        """One CSV per scalar key (TensorBoard-style scalars layout)."""
        directory = Path(directory)
        directory.mkdir(parents=True, exist_ok=True)
        keys = set()
        for r in self.records():
            keys.update(k for k, v in r.items()
                        if isinstance(v, (int, float))
                        and k not in NON_SCALAR_KEYS)
        written = []
        for k in sorted(keys):
            p = directory / f"{k}.csv"
            with open(p, "w") as f:
                f.write("iteration,value\n")
                for it, v in self.scalars(k):
                    f.write(f"{it},{v}\n")
            written.append(p)
        return written
