"""UI server: live dashboard + static report rendering.

Reference analog: org.deeplearning4j.ui.api.UIServer (Play/Vert.x web
dashboard with loss charts and per-layer parameter/update histograms).
Dependency-free: "/" serves a vanilla-JS page that polls the "/data" JSON
endpoint every couple of seconds and redraws loss curves plus per-layer
weight/update histogram time series (latest distribution as bars, history
as a heatmap) on canvases — live while training runs, the
attach-storage-then-browse workflow (UIServer.getInstance().attach(...)).
"/report" keeps the static inline-SVG snapshot; "/metrics" exposes the
process-wide monitoring registry in Prometheus text format (same body the
serving servers expose — one scrape config covers training and serving).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.storage import NON_SCALAR_KEYS, StatsStorage


def _svg_line_chart(series: List[Tuple[float, float]], title: str,
                    width: int = 640, height: int = 240) -> str:
    if not series:
        return f"<p>{title}: no data</p>"
    xs = [p[0] for p in series]
    ys = [p[1] for p in series]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    if y1 == y0:
        y1 = y0 + 1
    pad = 30
    W, H = width - 2 * pad, height - 2 * pad

    def px(x):
        return pad + (x - x0) / (x1 - x0 or 1) * W

    def py(y):
        return pad + (1 - (y - y0) / (y1 - y0)) * H

    pts = " ".join(f"{px(x):.1f},{py(y):.1f}" for x, y in series)
    return (
        f'<h3>{title}</h3>'
        f'<svg width="{width}" height="{height}" '
        f'style="background:#fafafa;border:1px solid #ddd">'
        f'<polyline fill="none" stroke="#1f77b4" stroke-width="1.5" points="{pts}"/>'
        f'<text x="{pad}" y="{pad - 8}" font-size="11">max {y1:.5g}</text>'
        f'<text x="{pad}" y="{height - 8}" font-size="11">min {y0:.5g}</text>'
        f"</svg>"
    )


def render_report(storage: StatsStorage, session_id: Optional[str] = None) -> str:
    """Full HTML dashboard for one (or every) session."""
    sessions = ([session_id] if session_id else storage.session_ids())
    parts = ["<html><head><title>deeplearning4j_tpu training UI</title></head>"
             "<body><h1>Training dashboard</h1>"]
    for sid in sessions:
        parts.append(f"<h2>session: {sid}</h2>")
        recs = storage.records(sid)
        keys = sorted({k for r in recs for k, v in r.items()
                       if isinstance(v, (int, float))
                       and k not in NON_SCALAR_KEYS})
        for k in keys:
            parts.append(_svg_line_chart(storage.scalars(k, sid), k))
        parts.append(f"<p>{len(recs)} records</p>")
    parts.append("</body></html>")
    return "".join(parts)


def _finite(v):
    return isinstance(v, (int, float)) and -float("inf") < v < float("inf")


def collect_data(storages: List[StatsStorage], max_points: int = 400,
                 max_hist: int = 80) -> dict:
    """The /data JSON payload: scalar series + per-layer histogram series.

    Non-finite scalars are dropped: json.dumps would emit bare NaN, which
    JSON.parse rejects — one diverged step must not freeze the dashboard.
    Series are built in ONE pass over the records (storage.scalars would
    re-read a FileStatsStorage once per key on this 2s polling path)."""
    sessions: dict = {}
    for storage in storages:
        for sid in storage.session_ids():
            recs = storage.records(sid)
            series: dict = {}
            for r in recs:
                for k, v in r.items():
                    if k not in NON_SCALAR_KEYS and _finite(v):
                        series.setdefault(k, []).append(
                            (r["iteration"], v))
            series = {k: sorted(pts)[-max_points:]
                      for k, pts in sorted(series.items())}
            hist_recs = [r for r in recs if "histograms" in r][-max_hist:]
            hists: dict = {}
            for r in hist_recs:
                for layer, entry in r["histograms"].items():
                    slot = hists.setdefault(layer, {"iters": [], "w": [],
                                                    "u": []})
                    slot["iters"].append(r["iteration"])
                    slot["w"].append(entry.get("w"))
                    slot["u"].append(entry.get("u"))
            sessions[sid] = {"series": series, "histograms": hists,
                             "records": len(recs)}
    return {"sessions": sessions}


_DASHBOARD_HTML = """<!doctype html>
<html><head><title>deeplearning4j_tpu training UI</title><style>
body{font-family:sans-serif;margin:16px;background:#fff}
h1{font-size:20px} h2{font-size:16px;margin:18px 0 4px} h3{font-size:13px;margin:8px 0 2px}
canvas{background:#fafafa;border:1px solid #ddd;margin-right:8px}
.row{display:flex;flex-wrap:wrap;align-items:flex-start}
#status{color:#888;font-size:12px}
</style></head><body>
<h1>Training dashboard <span id="status"></span></h1>
<div id="root"></div>
<script>
function line(cv, pts, color) {
  const c = cv.getContext('2d'); c.clearRect(0,0,cv.width,cv.height);
  if (!pts.length) return;
  const xs = pts.map(p=>p[0]), ys = pts.map(p=>p[1]);
  const x0=Math.min(...xs), x1=Math.max(...xs)||1;
  const y0=Math.min(...ys), y1=Math.max(...ys);
  const P=26, W=cv.width-2*P, H=cv.height-2*P;
  c.strokeStyle=color; c.beginPath();
  pts.forEach((p,i)=>{
    const x=P+(p[0]-x0)/((x1-x0)||1)*W, y=P+(1-(p[1]-y0)/((y1-y0)||1))*H;
    i?c.lineTo(x,y):c.moveTo(x,y);});
  c.stroke();
  c.fillStyle='#444'; c.font='10px sans-serif';
  c.fillText('max '+y1.toPrecision(4), P, 12);
  c.fillText('min '+y0.toPrecision(4), P, cv.height-4);
}
function bars(cv, h) {
  const c=cv.getContext('2d'); c.clearRect(0,0,cv.width,cv.height);
  if (!h) return;
  const n=h.counts.length, m=Math.max(...h.counts)||1, W=cv.width/n;
  c.fillStyle='#1f77b4';
  h.counts.forEach((v,i)=>{const bh=v/m*(cv.height-14);
    c.fillRect(i*W, cv.height-bh, W-1, bh);});
  c.fillStyle='#444'; c.font='10px sans-serif';
  c.fillText(h.min.toPrecision(3), 2, 10);
  c.fillText(h.max.toPrecision(3), cv.width-44, 10);
}
function heat(cv, snaps) {
  const c=cv.getContext('2d'); c.clearRect(0,0,cv.width,cv.height);
  const hs=snaps.filter(x=>x);
  if (!hs.length) return;
  const rows=hs[0].counts.length, W=cv.width/hs.length, H=cv.height/rows;
  hs.forEach((h,t)=>{const m=Math.max(...h.counts)||1;
    h.counts.forEach((v,b)=>{
      const a=v/m; c.fillStyle='rgba(31,119,180,'+a.toFixed(3)+')';
      c.fillRect(t*W,(rows-1-b)*H,Math.ceil(W),Math.ceil(H));});});
}
let built={};
function build(root,data){
  for (const [sid,s] of Object.entries(data.sessions)){
    let div=built[sid];
    if(!div){
      div=document.createElement('div'); built[sid]=div; root.appendChild(div);
      div.innerHTML='<h2>session: '+sid+'</h2>';
      div.charts={};
    }
    for (const [k,pts] of Object.entries(s.series)){
      let cv=div.charts[k];
      if(!cv){
        const h=document.createElement('h3'); h.textContent=k; div.appendChild(h);
        cv=document.createElement('canvas'); cv.width=560; cv.height=170;
        div.appendChild(cv); div.charts[k]=cv;
      }
      line(cv, pts, '#1f77b4');
    }
    for (const [layer,hh] of Object.entries(s.histograms)){
      for (const kind of ['w','u']){
        if (!hh[kind].some(x=>x)) continue;
        const key='hist_'+layer+'_'+kind;
        let row=div.charts[key];
        if(!row){
          const h=document.createElement('h3');
          h.textContent=layer+(kind==='w'?' weights':' updates')+
            ' (latest | history)';
          div.appendChild(h);
          row=document.createElement('div'); row.className='row';
          const b=document.createElement('canvas'); b.width=280; b.height=120;
          const m=document.createElement('canvas'); m.width=280; m.height=120;
          row.appendChild(b); row.appendChild(m); div.appendChild(row);
          row.bars=b; row.heat=m; div.charts[key]=row;
        }
        bars(row.bars, hh[kind][hh[kind].length-1]);
        heat(row.heat, hh[kind]);
      }
    }
  }
}
async function tick(){
  try{
    const r=await fetch('/data'); const data=await r.json();
    build(document.getElementById('root'), data);
    document.getElementById('status').textContent=
      'live, updated '+new Date().toLocaleTimeString();
  }catch(e){
    document.getElementById('status').textContent='poll failed: '+e;
  }
}
tick(); setInterval(tick, 2000);
</script></body></html>
"""


class UIServer:
    """Minimal dashboard server (UIServer.getInstance().attach(storage))."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._storages: List[StatsStorage] = []
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def attach(self, storage: StatsStorage) -> "UIServer":
        self._storages.append(storage)
        return self

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> "UIServer":
        storages = self._storages

        class Handler(BaseHTTPRequestHandler):
            def _send(self, data: bytes, ctype: str):
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                path = urlparse(self.path).path
                if path in ("/", "/index.html"):
                    self._send(_DASHBOARD_HTML.encode(),
                               "text/html; charset=utf-8")
                elif path == "/data":
                    q = parse_qs(urlparse(self.path).query)

                    def qint(name, default, lo=1, hi=100000):
                        try:
                            return min(max(int(q.get(name, [default])[0]),
                                           lo), hi)
                        except ValueError:
                            return default
                    payload = collect_data(storages,
                                           max_points=qint("points", 400),
                                           max_hist=qint("hist", 80))
                    self._send(json.dumps(payload).encode(),
                               "application/json")
                elif path == "/report":
                    body = "".join(render_report(s) for s in storages) or (
                        "<html><body>no storage attached</body></html>")
                    self._send(body.encode(), "text/html; charset=utf-8")
                elif path == "/metrics":
                    from deeplearning4j_tpu import monitoring

                    self._send(monitoring.metrics_text().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
