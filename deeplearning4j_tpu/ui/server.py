"""UI server + report rendering.

Reference analog: org.deeplearning4j.ui.api.UIServer (Play/Vert.x web
dashboard with loss charts). Here: dependency-free inline-SVG HTML report
over a StatsStorage, served by a stdlib ThreadingHTTPServer — same
attach-storage-then-browse workflow (UIServer.getInstance().attach(storage)).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from deeplearning4j_tpu.ui.storage import NON_SCALAR_KEYS, StatsStorage


def _svg_line_chart(series: List[Tuple[float, float]], title: str,
                    width: int = 640, height: int = 240) -> str:
    if not series:
        return f"<p>{title}: no data</p>"
    xs = [p[0] for p in series]
    ys = [p[1] for p in series]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    if y1 == y0:
        y1 = y0 + 1
    pad = 30
    W, H = width - 2 * pad, height - 2 * pad

    def px(x):
        return pad + (x - x0) / (x1 - x0 or 1) * W

    def py(y):
        return pad + (1 - (y - y0) / (y1 - y0)) * H

    pts = " ".join(f"{px(x):.1f},{py(y):.1f}" for x, y in series)
    return (
        f'<h3>{title}</h3>'
        f'<svg width="{width}" height="{height}" '
        f'style="background:#fafafa;border:1px solid #ddd">'
        f'<polyline fill="none" stroke="#1f77b4" stroke-width="1.5" points="{pts}"/>'
        f'<text x="{pad}" y="{pad - 8}" font-size="11">max {y1:.5g}</text>'
        f'<text x="{pad}" y="{height - 8}" font-size="11">min {y0:.5g}</text>'
        f"</svg>"
    )


def render_report(storage: StatsStorage, session_id: Optional[str] = None) -> str:
    """Full HTML dashboard for one (or every) session."""
    sessions = ([session_id] if session_id else storage.session_ids())
    parts = ["<html><head><title>deeplearning4j_tpu training UI</title></head>"
             "<body><h1>Training dashboard</h1>"]
    for sid in sessions:
        parts.append(f"<h2>session: {sid}</h2>")
        recs = storage.records(sid)
        keys = sorted({k for r in recs for k, v in r.items()
                       if isinstance(v, (int, float))
                       and k not in NON_SCALAR_KEYS})
        for k in keys:
            parts.append(_svg_line_chart(storage.scalars(k, sid), k))
        parts.append(f"<p>{len(recs)} records</p>")
    parts.append("</body></html>")
    return "".join(parts)


class UIServer:
    """Minimal dashboard server (UIServer.getInstance().attach(storage))."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._storages: List[StatsStorage] = []
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def attach(self, storage: StatsStorage) -> "UIServer":
        self._storages.append(storage)
        return self

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> "UIServer":
        storages = self._storages

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.split("?")[0] not in ("/", "/index.html"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = "".join(render_report(s) for s in storages) or (
                    "<html><body>no storage attached</body></html>")
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
