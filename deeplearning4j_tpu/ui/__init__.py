"""Training UI / stats subsystem.

Reference analog: deeplearning4j-ui-parent — StatsListener -> StatsStorage
(mapdb-backed FileStatsStorage / InMemoryStatsStorage) -> UIServer web
dashboard (SURVEY.md §5 "Metrics/observability"). TPU-first rendering is a
dependency-free HTML report with inline SVG charts plus CSV scalar export
(TensorBoard-compatible layout), served by a stdlib http server.
"""

from deeplearning4j_tpu.ui.storage import FileStatsStorage, InMemoryStatsStorage
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.server import UIServer, render_report

__all__ = ["StatsListener", "InMemoryStatsStorage", "FileStatsStorage",
           "UIServer", "render_report"]
