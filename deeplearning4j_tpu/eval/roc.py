"""ROC / AUC evaluation.

Reference analog: org.nd4j.evaluation.classification.ROC (thresholded
streaming mode with ``thresholdSteps``, exact mode when 0) and ROCMultiClass.
We implement the thresholded streaming mode: per-threshold TP/FP/TN/FN
counters accumulated per batch, AUROC via trapezoid on the resulting curve —
identical methodology, bounded memory.
"""

from __future__ import annotations

import numpy as np


class ROC:
    def __init__(self, threshold_steps: int = 200):
        self.steps = threshold_steps
        self.thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)
        self.tp = np.zeros(threshold_steps + 1, np.int64)
        self.fp = np.zeros(threshold_steps + 1, np.int64)
        self.pos = 0
        self.neg = 0

    def eval(self, labels, predictions, mask=None):
        """labels/predictions: [B] or [B,1] or two-column one-hot (class 1 = positive)."""
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim >= 2 and labels.shape[-1] == 2:
            labels = labels[..., 1]
            preds = preds[..., 1]
        labels = labels.reshape(-1) >= 0.5
        preds = preds.reshape(-1)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        self.pos += int(labels.sum())
        self.neg += int((~labels).sum())
        # predictions >= threshold -> predicted positive
        pred_pos = preds[None, :] >= self.thresholds[:, None]
        self.tp += (pred_pos & labels[None, :]).sum(axis=1)
        self.fp += (pred_pos & ~labels[None, :]).sum(axis=1)

    def get_roc_curve(self):
        tpr = self.tp / max(self.pos, 1)
        fpr = self.fp / max(self.neg, 1)
        return fpr, tpr

    def calculate_auc(self) -> float:
        fpr, tpr = self.get_roc_curve()
        order = np.argsort(fpr)
        return float(np.trapezoid(tpr[order], fpr[order]))

    def calculate_auprc(self) -> float:
        prec = self.tp / np.maximum(self.tp + self.fp, 1)
        rec = self.tp / max(self.pos, 1)
        order = np.argsort(rec)
        return float(np.trapezoid(prec[order], rec[order]))


class ROCMultiClass:
    """One-vs-all ROC per class (org.nd4j.evaluation.classification.ROCMultiClass)."""

    def __init__(self, threshold_steps: int = 200):
        self.steps = threshold_steps
        self.rocs: list[ROC] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        preds = np.asarray(predictions).reshape(labels.shape)
        if not self.rocs:
            self.rocs = [ROC(self.steps) for _ in range(labels.shape[-1])]
        for c, roc in enumerate(self.rocs):
            roc.pos += int((labels[:, c] >= 0.5).sum())
            roc.neg += int((labels[:, c] < 0.5).sum())
            lab = labels[:, c] >= 0.5
            pred_pos = preds[:, c][None, :] >= roc.thresholds[:, None]
            roc.tp += (pred_pos & lab[None, :]).sum(axis=1)
            roc.fp += (pred_pos & ~lab[None, :]).sum(axis=1)

    def calculate_auc(self, c: int) -> float:
        return self.rocs[c].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self.rocs]))
