"""Evaluation metrics.

Reference analog: org.nd4j.evaluation — Evaluation (classification), ROC /
ROCMultiClass / ROCBinary, RegressionEvaluation, EvaluationBinary,
ConfusionMatrix.
"""

from deeplearning4j_tpu.eval.evaluation import (Evaluation, ConfusionMatrix,
    EvaluationBinary, EvaluationCalibration)
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass

__all__ = [
    "Evaluation", "ConfusionMatrix", "EvaluationBinary", "EvaluationCalibration",
    "RegressionEvaluation", "ROC", "ROCMultiClass",
]
