"""Regression evaluation.

Reference analog: org.nd4j.evaluation.regression.RegressionEvaluation —
per-column MSE, MAE, RMSE, RSE, PC (Pearson), R^2.
"""

from __future__ import annotations

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: int | None = None):
        self.n = 0
        self.sum_err2 = None
        self.sum_abs = None
        self.sum_label = None
        self.sum_label2 = None
        self.sum_pred = None
        self.sum_pred2 = None
        self.sum_lp = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        preds = np.asarray(predictions, dtype=np.float64)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, preds = labels[m], preds[m]
        if self.sum_err2 is None:
            c = labels.shape[-1]
            z = lambda: np.zeros(c, np.float64)
            self.sum_err2, self.sum_abs = z(), z()
            self.sum_label, self.sum_label2 = z(), z()
            self.sum_pred, self.sum_pred2, self.sum_lp = z(), z(), z()
        e = preds - labels
        self.n += labels.shape[0]
        self.sum_err2 += (e * e).sum(0)
        self.sum_abs += np.abs(e).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label2 += (labels * labels).sum(0)
        self.sum_pred += preds.sum(0)
        self.sum_pred2 += (preds * preds).sum(0)
        self.sum_lp += (labels * preds).sum(0)

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self.sum_err2[col] / self.n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.sum_err2[col] / self.n))

    def relative_squared_error(self, col: int = 0) -> float:
        mean_label = self.sum_label[col] / self.n
        ss_tot = self.sum_label2[col] - self.n * mean_label**2
        return float(self.sum_err2[col] / ss_tot) if ss_tot else float("inf")

    def pearson_correlation(self, col: int = 0) -> float:
        n = self.n
        num = n * self.sum_lp[col] - self.sum_label[col] * self.sum_pred[col]
        d1 = n * self.sum_label2[col] - self.sum_label[col] ** 2
        d2 = n * self.sum_pred2[col] - self.sum_pred[col] ** 2
        den = np.sqrt(d1 * d2)
        return float(num / den) if den else 0.0

    def r_squared(self, col: int = 0) -> float:
        return 1.0 - self.relative_squared_error(col)

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self.sum_err2 / self.n))

    def stats(self) -> str:
        cols = len(self.sum_err2)
        lines = [f"Columns: {cols}, examples: {self.n}"]
        for c in range(cols):
            lines.append(
                f"col {c}: MSE={self.mean_squared_error(c):.6f} "
                f"MAE={self.mean_absolute_error(c):.6f} "
                f"RMSE={self.root_mean_squared_error(c):.6f} "
                f"R2={self.r_squared(c):.4f}"
            )
        return "\n".join(lines)
