"""Classification evaluation.

Reference analog: org.nd4j.evaluation.classification.Evaluation — accuracy,
per-class precision/recall/F1 (+ macro/micro averages), confusion matrix,
top-N accuracy, Matthews correlation; org.nd4j.evaluation.classification.
EvaluationBinary for per-output binary metrics.

Accumulation is streaming (eval(labels, predictions) per batch) exactly like
the reference; the per-batch reduction to a confusion matrix runs on device,
only the small [C, C] matrix syncs to host.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ConfusionMatrix:
    """org.nd4j.evaluation.classification.ConfusionMatrix analog."""

    def __init__(self, n_classes: int):
        self.n_classes = n_classes
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def add_matrix(self, m):
        self.matrix += np.asarray(m, dtype=np.int64)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def __str__(self):
        return str(self.matrix)


def _to_class_indices(a, n_classes=None):
    a = np.asarray(a)
    if a.ndim >= 2 and a.shape[-1] > 1:
        return np.argmax(a, axis=-1).reshape(-1)
    flat = a.reshape(-1)
    if np.issubdtype(flat.dtype, np.floating) and flat.size \
            and not np.all(flat == np.round(flat)):
        # single-column PROBABILITIES (a sigmoid head): threshold at 0.5
        # — int-casting would floor every p < 1.0 to class 0
        return (flat >= 0.5).astype(np.int64)
    return flat.astype(np.int64)


class Evaluation:
    """Streaming multi-class evaluation (org.nd4j.evaluation.classification.Evaluation)."""

    def __init__(self, n_classes: Optional[int] = None, labels: Optional[list] = None):
        self.labels = labels
        self.n_classes = n_classes or (len(labels) if labels else None)
        self.cm: Optional[ConfusionMatrix] = None
        self._topn_correct = 0
        self._topn_total = 0
        self.top_n = 1

    def _ensure(self, n):
        if self.cm is None:
            self.n_classes = self.n_classes or n
            self.cm = ConfusionMatrix(self.n_classes)
        elif n > self.n_classes:
            # sparse-label streams can reveal a larger id in a LATER batch
            # (e.g. a [B,1] head whose first batch held only class 0):
            # grow the matrix instead of crashing np.add.at
            grown = ConfusionMatrix(n)
            grown.matrix[:self.n_classes, :self.n_classes] = self.cm.matrix
            self.cm = grown
            self.n_classes = n

    def eval(self, labels, predictions, mask=None):
        """Accumulate a batch. labels: one-hot [B, C] (or [B, T, C]) OR
        integer class ids [B] / [B, T] (the sparse_mcxent convention, r4);
        predictions: probabilities with a trailing class axis; sequence
        shapes flatten with the optional mask."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        # sparse (integer-id) labels: one fewer dim than the predictions
        sparse = (predictions.ndim >= 2
                  and labels.ndim == predictions.ndim - 1)
        if predictions.ndim == 3:  # time series: flatten with mask
            if mask is not None:
                m = np.asarray(mask).reshape(-1).astype(bool)
            else:
                m = np.ones(predictions.shape[0] * predictions.shape[1],
                            dtype=bool)
            if sparse:
                labels = labels.reshape(-1)[m]
            else:
                labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        elif mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            labels, predictions = labels[m], predictions[m]
        if sparse:
            # size by the prediction head, but never smaller than the ids
            # actually seen (a [B, 1] single-output head with 0/1 ids, or
            # an off-by-one vocab, must not crash the confusion matrix)
            n = int(max(predictions.shape[-1],
                        labels.max() + 1 if labels.size else 1))
        elif labels.ndim >= 2:
            n = labels.shape[-1]
        else:
            n = int(max(labels.max(), predictions.max()) + 1)
        self._ensure(n)
        actual = (labels.astype(np.int64) if sparse
                  else _to_class_indices(labels))
        # top-N bookkeeping needs the probability matrix
        if predictions.ndim >= 2 and predictions.shape[-1] > 1 and self.top_n > 1:
            order = np.argsort(-predictions, axis=-1)[:, : self.top_n]
            self._topn_correct += int((order == actual[:, None]).any(axis=1).sum())
            self._topn_total += len(actual)
        pred = _to_class_indices(predictions)
        np.add.at(self.cm.matrix, (actual, pred), 1)

    # ---- metrics ----
    @property
    def _m(self):
        if self.cm is None:
            raise ValueError("no batches evaluated")
        return self.cm.matrix

    def num_examples(self) -> int:
        return int(self._m.sum())

    def accuracy(self) -> float:
        m = self._m
        tot = m.sum()
        return float(np.trace(m) / tot) if tot else 0.0

    def top_n_accuracy(self) -> float:
        return self._topn_correct / self._topn_total if self._topn_total else 0.0

    def true_positives(self, c: int) -> int:
        return int(self._m[c, c])

    def false_positives(self, c: int) -> int:
        return int(self._m[:, c].sum() - self._m[c, c])

    def false_negatives(self, c: int) -> int:
        return int(self._m[c, :].sum() - self._m[c, c])

    def precision(self, c: Optional[int] = None) -> float:
        if c is not None:
            tp, fp = self.true_positives(c), self.false_positives(c)
            return tp / (tp + fp) if tp + fp else 0.0
        vals = [self.precision(i) for i in range(self.n_classes)
                if self._m[:, i].sum() + self._m[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, c: Optional[int] = None) -> float:
        if c is not None:
            tp, fn = self.true_positives(c), self.false_negatives(c)
            return tp / (tp + fn) if tp + fn else 0.0
        vals = [self.recall(i) for i in range(self.n_classes)
                if self._m[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, c: Optional[int] = None) -> float:
        p, r = self.precision(c), self.recall(c)
        return 2 * p * r / (p + r) if p + r else 0.0

    def matthews_correlation(self, c: int) -> float:
        tp = self.true_positives(c)
        fp = self.false_positives(c)
        fn = self.false_negatives(c)
        tn = self.num_examples() - tp - fp - fn
        denom = np.sqrt(float(tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self) -> str:
        lines = [
            f"# of classes: {self.n_classes}",
            f"Examples: {self.num_examples()}",
            f"Accuracy: {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f}",
            f"Recall: {self.recall():.4f}",
            f"F1: {self.f1():.4f}",
            "",
            "Confusion matrix (rows=actual, cols=predicted):",
            str(self.cm),
        ]
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output binary metrics (org.nd4j.evaluation.classification.EvaluationBinary)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        preds = (np.asarray(predictions).reshape(labels.shape) >= self.threshold)
        lab = labels >= 0.5
        if self.tp is None:
            n = labels.shape[-1]
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)
        self.tp += (preds & lab).sum(0)
        self.fp += (preds & ~lab).sum(0)
        self.tn += (~preds & ~lab).sum(0)
        self.fn += (~preds & lab).sum(0)

    def accuracy(self, i: int) -> float:
        tot = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / tot) if tot else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if p + r else 0.0


class EvaluationCalibration:
    """Reliability / calibration evaluation
    (org.nd4j.evaluation.classification.EvaluationCalibration): bins
    predicted probability for the positive/argmax class against observed
    accuracy, plus residual histograms."""

    def __init__(self, n_bins: int = 10):
        self.n_bins = n_bins
        self._conf_sum = np.zeros(n_bins)
        self._acc_sum = np.zeros(n_bins)
        self._counts = np.zeros(n_bins, dtype=np.int64)
        self._residual_counts = np.zeros(n_bins, dtype=np.int64)

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        conf = preds.max(axis=-1)
        correct = (preds.argmax(-1) == labels.argmax(-1)).astype(np.float64)
        if mask is not None:
            m = np.asarray(mask).reshape(-1).astype(bool)
            conf, correct = conf.reshape(-1)[m], correct.reshape(-1)[m]
        bins = np.clip((conf * self.n_bins).astype(int), 0, self.n_bins - 1)
        np.add.at(self._conf_sum, bins, conf)
        np.add.at(self._acc_sum, bins, correct)
        np.add.at(self._counts, bins, 1)
        # residual plot: |label - p| averaged over classes per example
        resid = np.abs(labels.reshape(-1, labels.shape[-1])
                       - preds.reshape(-1, preds.shape[-1])).mean(-1)
        if mask is not None:
            resid = resid[np.asarray(mask).reshape(-1).astype(bool)]
        rbins = np.clip((resid * self.n_bins).astype(int), 0, self.n_bins - 1)
        np.add.at(self._residual_counts, rbins, 1)
        return self

    def reliability_curve(self):
        """(mean_confidence[b], accuracy[b], count[b]) per non-empty bin."""
        nz = self._counts > 0
        return (self._conf_sum[nz] / self._counts[nz],
                self._acc_sum[nz] / self._counts[nz], self._counts[nz])

    def residual_plot(self):
        """Histogram counts of mean-absolute residual |label - p| per
        example, binned over [0, 1] (getResidualPlot analog)."""
        edges = np.linspace(0.0, 1.0, self.n_bins + 1)
        return edges, self._residual_counts.copy()

    def expected_calibration_error(self) -> float:
        conf, acc, counts = self.reliability_curve()
        if counts.sum() == 0:
            return float("nan")
        w = counts / counts.sum()
        return float((w * np.abs(conf - acc)).sum())
