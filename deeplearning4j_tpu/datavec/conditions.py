"""Conditions — serializable predicates over records.

Reference analog: org.datavec.api.transform.condition (ColumnCondition with
ConditionOp, BooleanCondition AND/OR/NOT combinators). Conditions drive
ConditionFilter and conditional replace transforms, and round-trip through
the TransformProcess JSON form.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, List, Sequence


def try_float(v: Any) -> "float | None":
    """float(v) or None if unparseable/NaN. Shared by conditions, analysis
    and reducers so invalid-value semantics can't drift between them."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return None if math.isnan(f) else f


def sample_stdev(nums: Sequence[float]) -> float:
    """n-1 sample standard deviation (reference: StandardDeviation)."""
    n = len(nums)
    if n < 2:
        return 0.0
    m = sum(nums) / n
    return math.sqrt(sum((x - m) ** 2 for x in nums) / (n - 1))


def _is_invalid(v: Any, col=None) -> bool:
    """Type-aware validity (reference: per-type analysis quality checks).

    Numeric/time columns: unparseable or NaN is invalid. Categorical:
    values outside the category list. String: only None/empty. Without
    column metadata, falls back to the numeric rule.
    """
    if v is None or v == "":
        return True
    if col is not None:
        from deeplearning4j_tpu.datavec.schema import ColumnType
        if col.type == ColumnType.STRING:
            return False
        if col.type == ColumnType.CATEGORICAL:
            return col.categories is not None and v not in col.categories
    return try_float(v) is None


class Condition:
    def check(self, schema, record: list) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def spec(self) -> dict:  # pragma: no cover - abstract
        raise NotImplementedError

    # -- combinators (BooleanCondition analog)
    def __and__(self, other: "Condition") -> "Condition":
        return BooleanCondition("and", [self, other])

    def __or__(self, other: "Condition") -> "Condition":
        return BooleanCondition("or", [self, other])

    def __invert__(self) -> "Condition":
        return BooleanCondition("not", [self])


_OPS = {
    "lt": lambda v, t: float(v) < t,
    "lte": lambda v, t: float(v) <= t,
    "gt": lambda v, t: float(v) > t,
    "gte": lambda v, t: float(v) >= t,
    "eq": lambda v, t: v == t or (try_float(v) is not None
                                  and try_float(v) == try_float(t)),
    "neq": lambda v, t: not _OPS["eq"](v, t),
    "in_set": lambda v, t: v in t,
    "not_in_set": lambda v, t: v not in t,
}


@dataclasses.dataclass
class ColumnCondition(Condition):
    """ConditionOp applied to one column (NumericalColumnCondition /
    CategoricalColumnCondition / StringColumnCondition collapse into one
    class here — the op table is value-typed, not column-typed)."""

    column: str
    op: str
    value: Any = None

    def __post_init__(self):
        if self.op not in _OPS and self.op != "is_invalid":
            raise ValueError(f"unknown condition op {self.op!r}; "
                             f"one of {sorted(_OPS) + ['is_invalid']}")

    def check(self, schema, record: list) -> bool:
        v = record[schema.index_of(self.column)]
        if self.op == "is_invalid":
            return _is_invalid(v, schema.column(self.column))
        if self.op in ("lt", "lte", "gt", "gte") and try_float(v) is None:
            return False
        value = self.value
        if isinstance(value, (list, tuple)) and self.op in ("in_set", "not_in_set"):
            value = list(value)
        return _OPS[self.op](v, value)

    def spec(self) -> dict:
        v = self.value
        if isinstance(v, (set, frozenset, tuple)):
            v = sorted(v) if not isinstance(v, tuple) else list(v)
        return {"kind": "column", "column": self.column, "op": self.op,
                "value": v}


@dataclasses.dataclass
class BooleanCondition(Condition):
    """AND/OR/NOT over sub-conditions."""

    kind: str
    conditions: List[Condition]

    def check(self, schema, record: list) -> bool:
        if self.kind == "and":
            return all(c.check(schema, record) for c in self.conditions)
        if self.kind == "or":
            return any(c.check(schema, record) for c in self.conditions)
        if self.kind == "not":
            return not self.conditions[0].check(schema, record)
        raise ValueError(f"unknown boolean kind {self.kind}")

    def spec(self) -> dict:
        return {"kind": self.kind,
                "conditions": [c.spec() for c in self.conditions]}


def condition_from_spec(spec: dict) -> Condition:
    kind = spec["kind"]
    if kind == "column":
        return ColumnCondition(spec["column"], spec["op"], spec.get("value"))
    return BooleanCondition(kind, [condition_from_spec(s)
                                   for s in spec["conditions"]])


# convenience constructors mirroring the reference's static factories
def less_than(column: str, value: float) -> ColumnCondition:
    return ColumnCondition(column, "lt", value)


def greater_than(column: str, value: float) -> ColumnCondition:
    return ColumnCondition(column, "gt", value)


def equal_to(column: str, value: Any) -> ColumnCondition:
    return ColumnCondition(column, "eq", value)


def in_set(column: str, values: Sequence[Any]) -> ColumnCondition:
    return ColumnCondition(column, "in_set", list(values))


def is_invalid(column: str) -> ColumnCondition:
    return ColumnCondition(column, "is_invalid")
