"""Reducer — group-by aggregation over records.

Reference analog: org.datavec.api.transform.reduce.Reducer (+ Builder) with
ReduceOp (MIN/MAX/SUM/MEAN/STDEV/COUNT/COUNT_UNIQUE/TAKE_FIRST/TAKE_LAST).
Output column naming follows the reference: ``op(column)`` for aggregated
columns; key columns keep their name and type.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from deeplearning4j_tpu.datavec.conditions import sample_stdev, try_float
from deeplearning4j_tpu.datavec.schema import ColumnMeta, ColumnType, Schema

_NUMERIC_OPS = ("min", "max", "sum", "mean", "stdev")
_ALL_OPS = _NUMERIC_OPS + ("count", "count_unique", "take_first", "take_last")


def _apply(op: str, values: list):
    if op == "count":
        return len(values)
    if op == "count_unique":
        return len(set(values))
    if op == "take_first":
        return values[0]
    if op == "take_last":
        return values[-1]
    # invalid/empty values are skipped, matching analyze()'s counting
    # (shared try_float semantics); all-invalid groups reduce to NaN
    nums = [f for f in (try_float(v) for v in values) if f is not None]
    if not nums:
        return float("nan")
    if op == "min":
        return min(nums)
    if op == "max":
        return max(nums)
    if op == "sum":
        return sum(nums)
    if op == "mean":
        return sum(nums) / len(nums)
    if op == "stdev":
        return sample_stdev(nums)
    raise ValueError(f"unknown reduce op {op}")


def _out_meta(op: str, col: ColumnMeta) -> ColumnMeta:
    name = f"{op}({col.name})"
    if op in ("count", "count_unique"):
        return ColumnMeta(name, ColumnType.INTEGER)
    if op in _NUMERIC_OPS:
        return ColumnMeta(name, ColumnType.DOUBLE)
    return ColumnMeta(name, col.type, col.categories)


class Reducer:
    """Group-by-key aggregation; build with ``Reducer.builder(*keys)``."""

    def __init__(self, keys: List[str], default_op: str,
                 column_ops: Dict[str, str]):
        for op in [default_op] + list(column_ops.values()):
            if op not in _ALL_OPS:
                raise ValueError(f"unknown reduce op {op}; one of {_ALL_OPS}")
        self.keys = keys
        self.default_op = default_op
        self.column_ops = dict(column_ops)

    def _op_for(self, name: str) -> str:
        return self.column_ops.get(name, self.default_op)

    def output_schema(self, schema: Schema) -> Schema:
        cols = []
        for c in schema.columns:
            if c.name in self.keys:
                cols.append(c)
            else:
                cols.append(_out_meta(self._op_for(c.name), c))
        return Schema(cols)

    def reduce(self, schema: Schema, records: Sequence[list]) -> List[list]:
        ki = [schema.index_of(k) for k in self.keys]
        groups: dict = {}
        for r in records:
            groups.setdefault(tuple(r[i] for i in ki), []).append(r)
        out = []
        for rows in groups.values():
            rec = []
            for i, c in enumerate(schema.columns):
                if c.name in self.keys:
                    rec.append(rows[0][i])
                else:
                    rec.append(_apply(self._op_for(c.name),
                                      [r[i] for r in rows]))
            out.append(rec)
        return out

    # ------------------------------------------------------------------ json
    def spec(self) -> dict:
        return {"keys": self.keys, "default_op": self.default_op,
                "column_ops": self.column_ops}

    @staticmethod
    def from_spec(spec: dict) -> "Reducer":
        return Reducer(spec["keys"], spec["default_op"], spec["column_ops"])

    # --------------------------------------------------------------- builder
    class Builder:
        def __init__(self, *keys: str):
            if not keys:
                raise ValueError("at least one key column required")
            self._keys = list(keys)
            self._default = "take_first"
            self._ops: Dict[str, str] = {}

        def default_op(self, op: str) -> "Reducer.Builder":
            self._default = op
            return self

        def _cols(self, op: str, names) -> "Reducer.Builder":
            for n in names:
                self._ops[n] = op
            return self

        def min_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("min", names)

        def max_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("max", names)

        def sum_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("sum", names)

        def mean_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("mean", names)

        def stdev_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("stdev", names)

        def count_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("count", names)

        def count_unique_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("count_unique", names)

        def take_first_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("take_first", names)

        def take_last_columns(self, *names: str) -> "Reducer.Builder":
            return self._cols("take_last", names)

        def build(self) -> "Reducer":
            return Reducer(self._keys, self._default, self._ops)

    @staticmethod
    def builder(*keys: str) -> "Reducer.Builder":
        return Reducer.Builder(*keys)
