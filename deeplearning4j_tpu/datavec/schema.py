"""Schema — typed column descriptions for tabular records.

Reference analog: org.datavec.api.transform.schema.Schema (+ Builder).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional, Sequence


class ColumnType(enum.Enum):
    STRING = "string"
    INTEGER = "integer"
    DOUBLE = "double"
    CATEGORICAL = "categorical"
    TIME = "time"


@dataclasses.dataclass
class ColumnMeta:
    name: str
    type: ColumnType
    categories: Optional[List[str]] = None  # for CATEGORICAL


class Schema:
    """Immutable-ish column schema with a DL4J-style Builder."""

    def __init__(self, columns: Sequence[ColumnMeta]):
        self.columns = list(columns)
        self._index = {c.name: i for i, c in enumerate(self.columns)}
        if len(self._index) != len(self.columns):
            raise ValueError("duplicate column names")

    # --------------------------------------------------------------- queries
    @property
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> ColumnMeta:
        return self.columns[self._index[name]]

    def index_of(self, name: str) -> int:
        return self._index[name]

    def __len__(self):
        return len(self.columns)

    def __repr__(self):
        cols = ", ".join(f"{c.name}:{c.type.value}" for c in self.columns)
        return f"Schema({cols})"

    # ------------------------------------------------------------------ json
    def to_dict(self) -> dict:
        return {"columns": [
            {"name": c.name, "type": c.type.value,
             **({"categories": c.categories} if c.categories else {})}
            for c in self.columns]}

    @staticmethod
    def from_dict(d: dict) -> "Schema":
        return Schema([ColumnMeta(c["name"], ColumnType(c["type"]),
                                  c.get("categories"))
                       for c in d["columns"]])

    # --------------------------------------------------------------- builder
    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def add_column_string(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, ColumnType.STRING))
            return self

        def add_column_integer(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, ColumnType.INTEGER))
            return self

        def add_column_double(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, ColumnType.DOUBLE))
            return self

        def add_column_categorical(self, name: str, *categories: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, ColumnType.CATEGORICAL,
                                         list(categories)))
            return self

        def add_column_time(self, name: str) -> "Schema.Builder":
            self._cols.append(ColumnMeta(name, ColumnType.TIME))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)

    @staticmethod
    def builder() -> "Schema.Builder":
        return Schema.Builder()
