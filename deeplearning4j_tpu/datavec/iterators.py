"""RecordReader -> DataSet bridge.

Reference analog: org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator
(and SequenceRecordReaderDataSetIterator) — converts Writable records into
(features, one-hot labels) minibatches.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datavec.records import RecordReader


class RecordReaderDataSetIterator:
    """Batches records into DataSets.

    ``label_index``: which record element is the label (appended last by
    ImageRecordReader; a column index for CSV); ``num_classes`` one-hot
    encodes integer labels; ``regression`` keeps labels as floats.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: int = -1, num_classes: Optional[int] = None,
                 regression: bool = False):
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        if not regression and num_classes is None:
            # per-batch inference would give inconsistent one-hot widths
            raise ValueError("classification requires num_classes (the "
                             "reference's numPossibleLabels)")

    def reset(self):
        self.reader.reset()

    def __iter__(self):
        self.reset()
        return self

    def __next__(self) -> DataSet:
        feats, labels = [], []
        while len(feats) < self.batch_size and self.reader.has_next():
            r = self.reader.next_record()
            li = self.label_index if self.label_index >= 0 else len(r) + self.label_index
            label = r[li]
            fvals = [v for i, v in enumerate(r) if i != li]
            if len(fvals) == 1 and isinstance(fvals[0], np.ndarray):
                feats.append(fvals[0])
            else:
                feats.append(np.asarray(fvals, np.float32))
            labels.append(label)
        if not feats:
            raise StopIteration
        x = np.stack(feats)
        if self.regression:
            y = np.asarray(labels, np.float32).reshape(len(labels), -1)
        else:
            y = np.eye(self.num_classes,
                       dtype=np.float32)[np.asarray(labels, np.int64)]
        return DataSet(x, y)


class SequenceRecordReaderDataSetIterator:
    """Sequence records -> padded [B, T, F] DataSets with masks.

    Reference analog: org.deeplearning4j.datasets.datavec
    .SequenceRecordReaderDataSetIterator (single-reader mode: each sequence
    step carries features + the label at ``label_index``). Variable-length
    sequences are right-padded to the longest in the batch, with
    features/labels masks marking valid steps — the reference's
    ALIGN_END/ALIGN_START collapses to the standard right-pad + mask here
    (align="end" left-pads instead).
    """

    def __init__(self, reader, batch_size: int, label_index: int = -1,
                 num_classes: Optional[int] = None, regression: bool = False,
                 align: str = "start"):
        if not regression and num_classes is None:
            raise ValueError("classification requires num_classes")
        if align not in ("start", "end"):
            raise ValueError("align must be 'start' or 'end'")
        self.reader = reader
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.align = align

    def reset(self):
        self.reader.reset()

    def __iter__(self):
        self.reset()
        return self

    def _split(self, seq):
        feats, labels = [], []
        for r in seq:
            li = (self.label_index if self.label_index >= 0
                  else len(r) + self.label_index)
            labels.append(r[li])
            feats.append([float(v) for i, v in enumerate(r) if i != li])
        return np.asarray(feats, np.float32), labels

    def __next__(self) -> DataSet:
        seqs = []
        while len(seqs) < self.batch_size and self.reader.has_next():
            seqs.append(self.reader.next_record())
        if not seqs:
            raise StopIteration
        parts = [self._split(s) for s in seqs]
        tmax = max(f.shape[0] for f, _ in parts)
        nf = parts[0][0].shape[1]
        b = len(parts)
        x = np.zeros((b, tmax, nf), np.float32)
        mask = np.zeros((b, tmax), np.float32)
        if self.regression:
            y = np.zeros((b, tmax, 1), np.float32)
        else:
            y = np.zeros((b, tmax, self.num_classes), np.float32)
        for j, (f, labels) in enumerate(parts):
            t = f.shape[0]
            sl = slice(tmax - t, tmax) if self.align == "end" else slice(0, t)
            x[j, sl] = f
            mask[j, sl] = 1.0
            if self.regression:
                y[j, sl, 0] = np.asarray(labels, np.float32)
            else:
                y[j, sl] = np.eye(self.num_classes, dtype=np.float32)[
                    np.asarray(labels, np.int64)]
        return DataSet(x, y, features_mask=mask, labels_mask=mask.copy())
