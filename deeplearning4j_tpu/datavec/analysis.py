"""Data analysis — per-column statistics over a dataset.

Reference analog: org.datavec.local.transforms.AnalyzeLocal.analyze ->
org.datavec.api.transform.analysis.DataAnalysis (NumericalColumnAnalysis,
CategoricalAnalysis, StringAnalysis). Used to drive normalization ranges
and sanity-check ETL, same as the reference.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.datavec.conditions import sample_stdev, try_float
from deeplearning4j_tpu.datavec.schema import ColumnType, Schema


@dataclasses.dataclass
class NumericalColumnAnalysis:
    count: int
    count_invalid: int
    min: float
    max: float
    mean: float
    stdev: float

    def __repr__(self):
        return (f"numeric(count={self.count}, invalid={self.count_invalid}, "
                f"min={self.min:.6g}, max={self.max:.6g}, "
                f"mean={self.mean:.6g}, stdev={self.stdev:.6g})")


@dataclasses.dataclass
class CategoricalColumnAnalysis:
    count: int
    counts: Dict[str, int]  # category -> occurrences

    def __repr__(self):
        return f"categorical(count={self.count}, counts={self.counts})"


@dataclasses.dataclass
class StringColumnAnalysis:
    count: int
    count_unique: int
    min_length: int
    max_length: int
    mean_length: float

    def __repr__(self):
        return (f"string(count={self.count}, unique={self.count_unique}, "
                f"len=[{self.min_length},{self.max_length}], "
                f"mean_len={self.mean_length:.3g})")


class DataAnalysis:
    def __init__(self, schema: Schema, analyses: Dict[str, object]):
        self.schema = schema
        self._analyses = analyses

    def column_analysis(self, name: str):
        return self._analyses[name]

    def __repr__(self):
        lines = ["DataAnalysis:"]
        for c in self.schema.columns:
            lines.append(f"  {c.name}: {self._analyses[c.name]!r}")
        return "\n".join(lines)


def _numeric(values: list) -> NumericalColumnAnalysis:
    parsed = [try_float(v) for v in values]
    nums = [f for f in parsed if f is not None]
    invalid = len(parsed) - len(nums)
    if not nums:
        return NumericalColumnAnalysis(0, invalid, math.nan, math.nan,
                                       math.nan, math.nan)
    return NumericalColumnAnalysis(len(nums), invalid, min(nums), max(nums),
                                   sum(nums) / len(nums), sample_stdev(nums))


def analyze(schema: Schema, records: Sequence[list],
            sequences: bool = False) -> DataAnalysis:
    """AnalyzeLocal.analyze analog. ``records`` may be flat records or (with
    ``sequences=True``) a list of sequences, which are flattened first."""
    if sequences:
        records = [r for seq in records for r in seq]
    analyses = {}
    for i, c in enumerate(schema.columns):
        values = [r[i] for r in records]
        if c.type in (ColumnType.INTEGER, ColumnType.DOUBLE, ColumnType.TIME):
            analyses[c.name] = _numeric(values)
        elif c.type == ColumnType.CATEGORICAL:
            counts: Dict[str, int] = {}
            for v in values:
                counts[v] = counts.get(v, 0) + 1
            analyses[c.name] = CategoricalColumnAnalysis(len(values), counts)
        else:
            lens = [len(str(v)) for v in values]
            analyses[c.name] = StringColumnAnalysis(
                len(values), len(set(map(str, values))),
                min(lens) if lens else 0, max(lens) if lens else 0,
                sum(lens) / len(lens) if lens else 0.0)
    return DataAnalysis(schema, analyses)
