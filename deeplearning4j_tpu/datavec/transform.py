"""TransformProcess — schema-aware record transformations.

Reference analog: org.datavec.api.transform.TransformProcess (+ Builder) and
the local executor (org.datavec.local.transforms.LocalTransformExecutor).
Each step maps (schema, records) -> (schema, records); the Builder tracks the
evolving schema exactly like the reference (getFinalSchema), and the
declarative steps round-trip through JSON like the reference's Jackson form
(toJson/fromJson). Sequence steps follow the reference model: after
convert_to_sequence the executor carries List[sequence] (a sequence is a
list of records); per-record transforms then apply elementwise inside each
sequence, exactly like the reference's sequence-mode execution.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.datavec.conditions import (
    Condition, condition_from_spec, _is_invalid)
from deeplearning4j_tpu.datavec.schema import ColumnMeta, ColumnType, Schema


@dataclasses.dataclass
class _Step:
    name: str
    schema_fn: Callable[[Schema], Schema]
    # per-record map: (schema, record) -> record | None (None = filtered out)
    record_fn: Optional[Callable[[Schema, list], Optional[list]]] = None
    # whole-dataset step: (schema, items) -> items
    global_fn: Optional[Callable[[Schema, list], list]] = None
    # whole-sequence step (sequence mode only): (schema, seq) -> seq | None
    sequence_fn: Optional[Callable[[Schema, list], Optional[list]]] = None
    seq_after: Optional[bool] = None  # toggles sequence mode after this step
    # required mode for global steps: True = sequences, False = flat records
    expects_seq: Optional[bool] = None
    spec: Optional[dict] = None       # JSON form; None = not serializable


class TransformProcess:
    def __init__(self, initial: Schema, steps: List[_Step]):
        self.initial_schema = initial
        self.steps = steps

    # -------------------------------------------------------------- executor
    def final_schema(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.schema_fn(s)
        return s

    def execute(self, records: Sequence[list], sequences: bool = False
                ) -> List[list]:
        """LocalTransformExecutor.execute / executeSequence analog.

        ``records``: flat records (or sequences when ``sequences=True``,
        e.g. from CSVSequenceRecordReader). Returns flat records, unless the
        process ends in sequence mode, in which case a list of sequences.
        """
        items = [list(r) for r in records]
        schema = self.initial_schema
        seq = sequences
        for st in self.steps:
            if st.global_fn is not None:
                if st.expects_seq is not None and st.expects_seq != seq:
                    want = "sequence" if st.expects_seq else "flat-record"
                    raise ValueError(
                        f"step {st.name} requires {want} mode (currently "
                        f"{'sequence' if seq else 'flat-record'}); "
                        f"{'call convert_to_sequence first' if st.expects_seq else 'call convert_from_sequence first'}")
                items = st.global_fn(schema, items)
            elif st.sequence_fn is not None:
                if not seq:
                    raise ValueError(
                        f"step {st.name} requires sequence mode; call "
                        f"convert_to_sequence first (reference: sequence "
                        f"transforms only apply to sequence data)")
                items = [s2 for s in items
                         if (s2 := st.sequence_fn(schema, s)) is not None and s2]
            elif seq:
                new_items = []
                for s in items:
                    s2 = [r2 for r in s
                          if (r2 := st.record_fn(schema, r)) is not None]
                    if s2:
                        new_items.append(s2)
                items = new_items
            else:
                items = [r2 for r in items
                         if (r2 := st.record_fn(schema, r)) is not None]
            schema = st.schema_fn(schema)
            if st.seq_after is not None:
                seq = st.seq_after
        return items

    # ------------------------------------------------------------------ json
    def to_json(self) -> str:
        """Serializable form (reference: TransformProcess.toJson).

        Steps built from raw Python callables (``filter``, ``double_map``)
        have no declarative form and are rejected loudly, matching the
        reference's stance that JSON-round-trippable processes only use
        declarative transforms.
        """
        bad = [st.name for st in self.steps if st.spec is None]
        if bad:
            raise ValueError(
                f"steps {bad} use raw callables and cannot be serialized; "
                f"use declarative builder methods (conditions, math ops) "
                f"for JSON round-trip")
        return json.dumps({"schema": self.initial_schema.to_dict(),
                           "steps": [st.spec for st in self.steps]}, indent=1)

    @staticmethod
    def from_json(js: str) -> "TransformProcess":
        d = json.loads(js)
        b = TransformProcess.Builder(Schema.from_dict(d["schema"]))
        for spec in d["steps"]:
            spec = dict(spec)
            op = spec.pop("op")
            args = spec.pop("args", [])
            kwargs = spec
            if op in ("condition_filter", "conditional_replace_value"):
                # first arg (or 'condition' kwarg) is a serialized condition
                if "condition" in kwargs:
                    kwargs["condition"] = condition_from_spec(kwargs["condition"])
                else:
                    args = [condition_from_spec(args[0])] + list(args[1:])
            elif op == "reduce":
                from deeplearning4j_tpu.datavec.reduce import Reducer
                kwargs["reducer"] = Reducer.from_spec(kwargs["reducer"])
            getattr(b, op)(*args, **kwargs)
        return b.build()

    # --------------------------------------------------------------- builder
    class Builder:
        def __init__(self, schema: Schema):
            self._initial = schema
            self._steps: List[_Step] = []

        def _declarative(self, op: str, *args, **kwargs) -> dict:
            return {"op": op, "args": list(args), **kwargs}

        # -- column removal/selection
        def remove_columns(self, *names: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                return Schema([c for c in s.columns if c.name not in names])

            def record_fn(s: Schema, r: list):
                drop = {s.index_of(n) for n in names}
                return [v for i, v in enumerate(r) if i not in drop]

            self._steps.append(_Step(f"remove{names}", schema_fn, record_fn,
                                     spec=self._declarative("remove_columns",
                                                            *names)))
            return self

        def remove_all_columns_except(self, *names: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                return Schema([c for c in s.columns if c.name in names])

            def record_fn(s: Schema, r: list):
                keep = {s.index_of(n) for n in names}
                return [v for i, v in enumerate(r) if i in keep]

            self._steps.append(_Step(f"keep{names}", schema_fn, record_fn,
                                     spec=self._declarative(
                                         "remove_all_columns_except", *names)))
            return self

        def rename_column(self, old: str, new: str) -> "TransformProcess.Builder":
            """RenameColumnsTransform analog."""

            def schema_fn(s: Schema) -> Schema:
                return Schema([ColumnMeta(new, c.type, c.categories)
                               if c.name == old else c for c in s.columns])

            self._steps.append(_Step(f"rename({old}->{new})", schema_fn,
                                     lambda s, r: r,
                                     spec=self._declarative("rename_column",
                                                            old, new)))
            return self

        def duplicate_column(self, name: str, new_name: str
                             ) -> "TransformProcess.Builder":
            """DuplicateColumnsTransform analog (copy appended after source)."""

            def schema_fn(s: Schema) -> Schema:
                cols = []
                for c in s.columns:
                    cols.append(c)
                    if c.name == name:
                        cols.append(ColumnMeta(new_name, c.type, c.categories))
                return Schema(cols)

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                return r[:i + 1] + [r[i]] + r[i + 1:]

            self._steps.append(_Step(f"dup({name})", schema_fn, record_fn,
                                     spec=self._declarative("duplicate_column",
                                                            name, new_name)))
            return self

        def add_constant_column(self, name: str, col_type: str, value
                                ) -> "TransformProcess.Builder":
            """AddConstantColumnTransform analog."""
            ct = ColumnType(col_type)

            def schema_fn(s: Schema) -> Schema:
                return Schema(s.columns + [ColumnMeta(name, ct)])

            self._steps.append(_Step(f"const({name})", schema_fn,
                                     lambda s, r: r + [value],
                                     spec=self._declarative(
                                         "add_constant_column", name,
                                         col_type, value)))
            return self

        # -- filters
        def filter(self, predicate: Callable[[Schema, list], bool]
                   ) -> "TransformProcess.Builder":
            """Keep records where predicate(schema, record) is True
            (FilterOp analog; raw-callable form — not JSON-serializable)."""

            def record_fn(s: Schema, r: list):
                return r if predicate(s, r) else None

            self._steps.append(_Step("filter", lambda s: s, record_fn))
            return self

        def condition_filter(self, condition: Condition
                             ) -> "TransformProcess.Builder":
            """ConditionFilter analog: REMOVES records matching the
            condition (reference semantics: filter out where satisfied)."""

            def record_fn(s: Schema, r: list):
                return None if condition.check(s, r) else r

            self._steps.append(_Step("condition_filter", lambda s: s, record_fn,
                                     spec=self._declarative(
                                         "condition_filter", condition.spec())))
            return self

        # -- conditional / invalid-value replacement
        def conditional_replace_value(self, column: str, value,
                                      condition: Condition
                                      ) -> "TransformProcess.Builder":
            """ConditionalReplaceValueTransform analog."""

            def record_fn(s: Schema, r: list):
                if condition.check(s, r):
                    r = list(r)
                    r[s.index_of(column)] = value
                return r

            self._steps.append(_Step(f"condreplace({column})", lambda s: s,
                                     record_fn,
                                     spec=self._declarative(
                                         "conditional_replace_value", column,
                                         value, condition=condition.spec())))
            return self

        def replace_invalid_with(self, column: str, value
                                 ) -> "TransformProcess.Builder":
            """ReplaceInvalidWithIntegerTransform / ReplaceEmpty analog:
            NaN / empty / unparseable values become ``value``."""

            def record_fn(s: Schema, r: list):
                i = s.index_of(column)
                if _is_invalid(r[i], s.column(column)):
                    r = list(r)
                    r[i] = value
                return r

            self._steps.append(_Step(f"replinvalid({column})", lambda s: s,
                                     record_fn,
                                     spec=self._declarative(
                                         "replace_invalid_with", column,
                                         value)))
            return self

        # -- categorical
        def categorical_to_integer(self, name: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                cols = [ColumnMeta(c.name, ColumnType.INTEGER) if c.name == name
                        else c for c in s.columns]
                return Schema(cols)

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                cats = s.column(name).categories
                r = list(r)
                r[i] = cats.index(r[i])
                return r

            self._steps.append(_Step(f"cat2int({name})", schema_fn, record_fn,
                                     spec=self._declarative(
                                         "categorical_to_integer", name)))
            return self

        def integer_to_categorical(self, name: str, *categories: str
                                   ) -> "TransformProcess.Builder":
            """IntegerToCategoricalTransform analog (index -> category)."""

            def schema_fn(s: Schema) -> Schema:
                cols = [ColumnMeta(c.name, ColumnType.CATEGORICAL,
                                   list(categories))
                        if c.name == name else c for c in s.columns]
                return Schema(cols)

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = categories[int(r[i])]
                return r

            self._steps.append(_Step(f"int2cat({name})", schema_fn, record_fn,
                                     spec=self._declarative(
                                         "integer_to_categorical", name,
                                         *categories)))
            return self

        def categorical_to_one_hot(self, name: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                cats = s.column(name).categories
                cols = []
                for c in s.columns:
                    if c.name == name:
                        cols.extend(ColumnMeta(f"{name}[{cat}]", ColumnType.INTEGER)
                                    for cat in cats)
                    else:
                        cols.append(c)
                return Schema(cols)

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                cats = s.column(name).categories
                onehot = [1 if r[i] == cat else 0 for cat in cats]
                return r[:i] + onehot + r[i + 1:]

            self._steps.append(_Step(f"onehot({name})", schema_fn, record_fn,
                                     spec=self._declarative(
                                         "categorical_to_one_hot", name)))
            return self

        def string_to_categorical(self, name: str, *categories: str
                                  ) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                cols = [ColumnMeta(c.name, ColumnType.CATEGORICAL, list(categories))
                        if c.name == name else c for c in s.columns]
                return Schema(cols)

            self._steps.append(_Step(f"str2cat({name})", schema_fn,
                                     lambda s, r: r,
                                     spec=self._declarative(
                                         "string_to_categorical", name,
                                         *categories)))
            return self

        # -- string transforms
        def append_string(self, name: str, suffix: str
                          ) -> "TransformProcess.Builder":
            """AppendStringColumnTransform analog."""

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = str(r[i]) + suffix
                return r

            self._steps.append(_Step(f"append({name})", lambda s: s, record_fn,
                                     spec=self._declarative("append_string",
                                                            name, suffix)))
            return self

        def change_case(self, name: str, case: str = "lower"
                        ) -> "TransformProcess.Builder":
            """ChangeCaseStringTransform analog (case: lower|upper)."""
            if case not in ("lower", "upper"):
                raise ValueError("case must be 'lower' or 'upper'")

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = str(r[i]).lower() if case == "lower" else str(r[i]).upper()
                return r

            self._steps.append(_Step(f"case({name})", lambda s: s, record_fn,
                                     spec=self._declarative("change_case",
                                                            name, case)))
            return self

        def replace_string(self, name: str, old: str, new: str
                           ) -> "TransformProcess.Builder":
            """ReplaceStringTransform analog (substring replacement)."""

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = str(r[i]).replace(old, new)
                return r

            self._steps.append(_Step(f"replace({name})", lambda s: s, record_fn,
                                     spec=self._declarative("replace_string",
                                                            name, old, new)))
            return self

        def concat_columns(self, new_name: str, delimiter: str, *names: str
                           ) -> "TransformProcess.Builder":
            """ConcatenateStringColumns analog: new string column appended."""

            def schema_fn(s: Schema) -> Schema:
                return Schema(s.columns + [ColumnMeta(new_name,
                                                      ColumnType.STRING)])

            def record_fn(s: Schema, r: list):
                vals = [str(r[s.index_of(n)]) for n in names]
                return r + [delimiter.join(vals)]

            self._steps.append(_Step(f"concat({new_name})", schema_fn,
                                     record_fn,
                                     spec=self._declarative(
                                         "concat_columns", new_name,
                                         delimiter, *names)))
            return self

        # -- numeric math
        def double_math_op(self, name: str, op: str, value: float
                           ) -> "TransformProcess.Builder":
            ops = {"add": lambda x: x + value, "subtract": lambda x: x - value,
                   "multiply": lambda x: x * value, "divide": lambda x: x / value,
                   "pow": lambda x: x ** value}
            if op.lower() not in ops:
                raise ValueError(f"unknown math op {op}")
            f = ops[op.lower()]

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = f(float(r[i]))
                return r

            self._steps.append(_Step(f"math({name},{op})", lambda s: s,
                                     record_fn,
                                     spec=self._declarative(
                                         "double_math_op", name, op, value)))
            return self

        # reference spells the integer variant separately (IntegerMathOp);
        # keep the name for API parity, preserving int-ness
        def integer_math_op(self, name: str, op: str, value: int
                            ) -> "TransformProcess.Builder":
            # divide/modulus follow Java int semantics (truncate toward
            # zero; remainder sign follows the dividend), matching the
            # reference IntegerMathOp on negative operands
            ops = {"add": lambda x: x + value, "subtract": lambda x: x - value,
                   "multiply": lambda x: x * value,
                   "divide": lambda x: int(x / value),
                   "modulus": lambda x: x - int(x / value) * value}
            if op.lower() not in ops:
                raise ValueError(f"unknown math op {op}")
            f = ops[op.lower()]

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = f(int(r[i]))
                return r

            self._steps.append(_Step(f"imath({name},{op})", lambda s: s,
                                     record_fn,
                                     spec=self._declarative(
                                         "integer_math_op", name, op, value)))
            return self

        def double_columns_math_op(self, new_name: str, op: str, *names: str
                                   ) -> "TransformProcess.Builder":
            """DoubleColumnsMathOpTransform analog: new column from a
            row-wise op over existing columns (add/subtract/multiply/divide
            — subtract/divide are binary)."""
            if op.lower() in ("subtract", "divide") and len(names) != 2:
                raise ValueError(f"{op} requires exactly 2 columns")

            def apply(vals):
                o = op.lower()
                if o == "add":
                    return sum(vals)
                if o == "multiply":
                    out = 1.0
                    for v in vals:
                        out *= v
                    return out
                if o == "subtract":
                    return vals[0] - vals[1]
                if o == "divide":
                    return vals[0] / vals[1]
                raise ValueError(f"unknown math op {op}")

            def schema_fn(s: Schema) -> Schema:
                return Schema(s.columns + [ColumnMeta(new_name,
                                                      ColumnType.DOUBLE)])

            def record_fn(s: Schema, r: list):
                vals = [float(r[s.index_of(n)]) for n in names]
                return r + [apply(vals)]

            self._steps.append(_Step(f"colmath({new_name})", schema_fn,
                                     record_fn,
                                     spec=self._declarative(
                                         "double_columns_math_op", new_name,
                                         op, *names)))
            return self

        def double_map(self, name: str, fn: Callable[[float], float]
                       ) -> "TransformProcess.Builder":
            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = fn(float(r[i]))
                return r

            self._steps.append(_Step(f"map({name})", lambda s: s, record_fn))
            return self

        # -- normalization over the dataset requires two passes; expose a
        #    fit-style helper mirroring the reference's analysis + transform
        def normalize_min_max(self, name: str, lo: float, hi: float
                              ) -> "TransformProcess.Builder":
            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                span = (hi - lo) or 1.0
                r[i] = (float(r[i]) - lo) / span
                return r

            self._steps.append(_Step(f"minmax({name})", lambda s: s, record_fn,
                                     spec=self._declarative(
                                         "normalize_min_max", name, lo, hi)))
            return self

        # -- time
        def string_to_time(self, name: str, fmt: str
                           ) -> "TransformProcess.Builder":
            """StringToTimeTransform analog: parse with ``fmt``
            (strptime syntax) -> epoch milliseconds, column becomes TIME."""

            def schema_fn(s: Schema) -> Schema:
                return Schema([ColumnMeta(c.name, ColumnType.TIME)
                               if c.name == name else c for c in s.columns])

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                dt = _dt.datetime.strptime(str(r[i]), fmt)
                dt = dt.replace(tzinfo=_dt.timezone.utc)
                r[i] = int(dt.timestamp() * 1000)
                return r

            self._steps.append(_Step(f"str2time({name})", schema_fn, record_fn,
                                     spec=self._declarative("string_to_time",
                                                            name, fmt)))
            return self

        def derive_column_from_time(self, source: str, new_name: str,
                                    field: str) -> "TransformProcess.Builder":
            """DeriveColumnsFromTimeTransform analog. ``field``: one of
            hour_of_day | day_of_week | day_of_month | month | year."""
            # day_of_week is Joda-convention Monday=1..Sunday=7 (the
            # reference's DateTimeFieldType.dayOfWeek), not Python's 0-based
            fields = {"hour_of_day": lambda d: d.hour,
                      "day_of_week": lambda d: d.weekday() + 1,
                      "day_of_month": lambda d: d.day,
                      "month": lambda d: d.month,
                      "year": lambda d: d.year}
            if field not in fields:
                raise ValueError(f"unknown time field {field}; "
                                 f"one of {sorted(fields)}")
            f = fields[field]

            def schema_fn(s: Schema) -> Schema:
                return Schema(s.columns + [ColumnMeta(new_name,
                                                      ColumnType.INTEGER)])

            def record_fn(s: Schema, r: list):
                ms = int(r[s.index_of(source)])
                d = _dt.datetime.fromtimestamp(ms / 1000.0, _dt.timezone.utc)
                return r + [f(d)]

            self._steps.append(_Step(f"timefield({new_name})", schema_fn,
                                     record_fn,
                                     spec=self._declarative(
                                         "derive_column_from_time", source,
                                         new_name, field)))
            return self

        # -- group-by reduction (org.datavec.api.transform.reduce.Reducer)
        def reduce(self, reducer) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                return reducer.output_schema(s)

            def global_fn(s: Schema, items: list) -> list:
                return reducer.reduce(s, items)

            self._steps.append(_Step("reduce", schema_fn, global_fn=global_fn,
                                     expects_seq=False,
                                     spec={"op": "reduce",
                                           "reducer": reducer.spec()}))
            return self

        # -- sequence steps (org.datavec.api.transform.sequence)
        def convert_to_sequence(self, key_column: str, sort_column: str
                                ) -> "TransformProcess.Builder":
            """ConvertToSequence analog: group records by ``key_column``,
            order each group by ``sort_column`` ascending
            (NumericalColumnComparator). Output items become sequences."""

            def global_fn(s: Schema, items: list) -> list:
                ki = s.index_of(key_column)
                si = s.index_of(sort_column)
                groups: dict = {}
                for r in items:
                    groups.setdefault(r[ki], []).append(r)
                return [sorted(g, key=lambda r: float(r[si]))
                        for g in groups.values()]

            self._steps.append(_Step("to_sequence", lambda s: s,
                                     global_fn=global_fn, seq_after=True,
                                     expects_seq=False,
                                     spec=self._declarative(
                                         "convert_to_sequence", key_column,
                                         sort_column)))
            return self

        def convert_from_sequence(self) -> "TransformProcess.Builder":
            """ConvertFromSequence analog: flatten sequences to records."""

            def global_fn(s: Schema, items: list) -> list:
                return [r for seq in items for r in seq]

            self._steps.append(_Step("from_sequence", lambda s: s,
                                     global_fn=global_fn, seq_after=False,
                                     expects_seq=True,
                                     spec=self._declarative(
                                         "convert_from_sequence")))
            return self

        def offset_sequence(self, columns: Sequence[str], offset: int
                            ) -> "TransformProcess.Builder":
            """OffsetSequenceTransform (TrimSequence mode) analog: the named
            columns are shifted ``offset`` steps relative to the others
            (positive = value comes from ``offset`` steps earlier), and the
            |offset| boundary rows that lose alignment are trimmed. The
            classic use is next-step prediction targets (offset -1 on the
            label column)."""
            cols = list(columns)
            if offset == 0:
                raise ValueError("offset must be nonzero")

            def sequence_fn(s: Schema, seq: list):
                idx = [s.index_of(c) for c in cols]
                n = len(seq)
                k = abs(offset)
                if n <= k:
                    return None
                out = []
                for t in range(k, n) if offset > 0 else range(0, n - k):
                    r = list(seq[t])
                    src = seq[t - offset]
                    for i in idx:
                        r[i] = src[i]
                    out.append(r)
                return out

            self._steps.append(_Step(f"offset({cols},{offset})", lambda s: s,
                                     sequence_fn=sequence_fn,
                                     spec=self._declarative(
                                         "offset_sequence", cols, offset)))
            return self

        def trim_sequence(self, n: int, from_first: bool = True
                          ) -> "TransformProcess.Builder":
            """SequenceTrimTransform analog: drop ``n`` steps from the
            start (``from_first=True``) or end of every sequence."""

            def sequence_fn(s: Schema, seq: list):
                out = seq[n:] if from_first else seq[:len(seq) - n]
                return out or None

            self._steps.append(_Step(f"trim({n})", lambda s: s,
                                     sequence_fn=sequence_fn,
                                     spec=self._declarative(
                                         "trim_sequence", n, from_first)))
            return self

        def split_sequence_by_length(self, max_length: int
                                     ) -> "TransformProcess.Builder":
            """SequenceSplit (SplitMaxLengthSequence) analog: sequences
            longer than ``max_length`` split into consecutive chunks."""

            def global_fn(s: Schema, items: list) -> list:
                out = []
                for seq in items:
                    for i in range(0, len(seq), max_length):
                        out.append(seq[i:i + max_length])
                return out

            self._steps.append(_Step(f"split({max_length})", lambda s: s,
                                     global_fn=global_fn, expects_seq=True,
                                     spec=self._declarative(
                                         "split_sequence_by_length",
                                         max_length)))
            return self

        def build(self) -> "TransformProcess":
            return TransformProcess(self._initial, list(self._steps))

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)
