"""TransformProcess — schema-aware record transformations.

Reference analog: org.datavec.api.transform.TransformProcess (+ Builder) and
the local executor (org.datavec.local.transforms.LocalTransformExecutor).
Each step maps (schema, records) -> (schema, records); the Builder tracks the
evolving schema exactly like the reference (getFinalSchema).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.datavec.schema import ColumnMeta, ColumnType, Schema


@dataclasses.dataclass
class _Step:
    name: str
    schema_fn: Callable[[Schema], Schema]
    record_fn: Callable[[Schema, list], Optional[list]]  # None = filtered out


class TransformProcess:
    def __init__(self, initial: Schema, steps: List[_Step]):
        self.initial_schema = initial
        self.steps = steps

    # -------------------------------------------------------------- executor
    def final_schema(self) -> Schema:
        s = self.initial_schema
        for st in self.steps:
            s = st.schema_fn(s)
        return s

    def execute(self, records: Sequence[list]) -> List[list]:
        """LocalTransformExecutor.execute analog."""
        out = [list(r) for r in records]
        schema = self.initial_schema
        for st in self.steps:
            new = []
            for r in out:
                r2 = st.record_fn(schema, r)
                if r2 is not None:
                    new.append(r2)
            out = new
            schema = st.schema_fn(schema)
        return out

    # --------------------------------------------------------------- builder
    class Builder:
        def __init__(self, schema: Schema):
            self._initial = schema
            self._steps: List[_Step] = []

        # -- column removal/selection
        def remove_columns(self, *names: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                return Schema([c for c in s.columns if c.name not in names])

            def record_fn(s: Schema, r: list):
                drop = {s.index_of(n) for n in names}
                return [v for i, v in enumerate(r) if i not in drop]

            self._steps.append(_Step(f"remove{names}", schema_fn, record_fn))
            return self

        def remove_all_columns_except(self, *names: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                return Schema([c for c in s.columns if c.name in names])

            def record_fn(s: Schema, r: list):
                keep = {s.index_of(n) for n in names}
                return [v for i, v in enumerate(r) if i in keep]

            self._steps.append(_Step(f"keep{names}", schema_fn, record_fn))
            return self

        # -- filters
        def filter(self, predicate: Callable[[Schema, list], bool]
                   ) -> "TransformProcess.Builder":
            """Keep records where predicate(schema, record) is True
            (FilterOp / ConditionFilter analog)."""

            def record_fn(s: Schema, r: list):
                return r if predicate(s, r) else None

            self._steps.append(_Step("filter", lambda s: s, record_fn))
            return self

        # -- categorical
        def categorical_to_integer(self, name: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                cols = [ColumnMeta(c.name, ColumnType.INTEGER) if c.name == name
                        else c for c in s.columns]
                return Schema(cols)

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                cats = s.column(name).categories
                r = list(r)
                r[i] = cats.index(r[i])
                return r

            self._steps.append(_Step(f"cat2int({name})", schema_fn, record_fn))
            return self

        def categorical_to_one_hot(self, name: str) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                cats = s.column(name).categories
                cols = []
                for c in s.columns:
                    if c.name == name:
                        cols.extend(ColumnMeta(f"{name}[{cat}]", ColumnType.INTEGER)
                                    for cat in cats)
                    else:
                        cols.append(c)
                return Schema(cols)

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                cats = s.column(name).categories
                onehot = [1 if r[i] == cat else 0 for cat in cats]
                return r[:i] + onehot + r[i + 1:]

            self._steps.append(_Step(f"onehot({name})", schema_fn, record_fn))
            return self

        def string_to_categorical(self, name: str, *categories: str
                                  ) -> "TransformProcess.Builder":
            def schema_fn(s: Schema) -> Schema:
                cols = [ColumnMeta(c.name, ColumnType.CATEGORICAL, list(categories))
                        if c.name == name else c for c in s.columns]
                return Schema(cols)

            self._steps.append(_Step(f"str2cat({name})", schema_fn,
                                     lambda s, r: r))
            return self

        # -- numeric math (DoubleMathOp analog)
        def double_math_op(self, name: str, op: str, value: float
                           ) -> "TransformProcess.Builder":
            ops = {"add": lambda x: x + value, "subtract": lambda x: x - value,
                   "multiply": lambda x: x * value, "divide": lambda x: x / value,
                   "pow": lambda x: x ** value}
            if op.lower() not in ops:
                raise ValueError(f"unknown math op {op}")
            f = ops[op.lower()]

            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = f(float(r[i]))
                return r

            self._steps.append(_Step(f"math({name},{op})", lambda s: s, record_fn))
            return self

        def double_map(self, name: str, fn: Callable[[float], float]
                       ) -> "TransformProcess.Builder":
            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                r[i] = fn(float(r[i]))
                return r

            self._steps.append(_Step(f"map({name})", lambda s: s, record_fn))
            return self

        # -- normalization over the dataset requires two passes; expose a
        #    fit-style helper mirroring the reference's analysis + transform
        def normalize_min_max(self, name: str, lo: float, hi: float
                              ) -> "TransformProcess.Builder":
            def record_fn(s: Schema, r: list):
                i = s.index_of(name)
                r = list(r)
                span = (hi - lo) or 1.0
                r[i] = (float(r[i]) - lo) / span
                return r

            self._steps.append(_Step(f"minmax({name})", lambda s: s, record_fn))
            return self

        def build(self) -> "TransformProcess":
            return TransformProcess(self._initial, list(self._steps))

    @staticmethod
    def builder(schema: Schema) -> "TransformProcess.Builder":
        return TransformProcess.Builder(schema)
