"""DataVec-equivalent ETL.

Reference analog: the `datavec/` module family (SURVEY.md §1 L3) —
RecordReader implementations (org.datavec.api.records.reader.impl.*),
Schema + TransformProcess (org.datavec.api.transform.**) and the
local executor. TPU-first: ETL stays host-side numpy (the device only sees
ready batches), composing with the async device-prefetch iterators in
deeplearning4j_tpu.datasets.
"""

from deeplearning4j_tpu.datavec.schema import ColumnType, Schema
from deeplearning4j_tpu.datavec.records import (
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    ImageRecordReader, LineRecordReader, RecordReader,
)
from deeplearning4j_tpu.datavec.transform import TransformProcess
from deeplearning4j_tpu.datavec.iterators import RecordReaderDataSetIterator

__all__ = [
    "ColumnType", "Schema", "RecordReader", "CSVRecordReader",
    "CSVSequenceRecordReader", "LineRecordReader", "CollectionRecordReader",
    "ImageRecordReader", "TransformProcess", "RecordReaderDataSetIterator",
]
