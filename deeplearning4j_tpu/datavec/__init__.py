"""DataVec-equivalent ETL.

Reference analog: the `datavec/` module family (SURVEY.md §1 L3) —
RecordReader implementations (org.datavec.api.records.reader.impl.*),
Schema + TransformProcess + conditions/reducers/joins/analysis
(org.datavec.api.transform.**) and the local executor. TPU-first: ETL stays
host-side numpy (the device only sees ready batches), composing with the
async device-prefetch iterators in deeplearning4j_tpu.datasets.
"""

from deeplearning4j_tpu.datavec.schema import ColumnType, Schema
from deeplearning4j_tpu.datavec.records import (
    CollectionRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    ImageRecordReader, LineRecordReader, RecordReader,
)
from deeplearning4j_tpu.datavec.conditions import (
    BooleanCondition, ColumnCondition, Condition, equal_to, greater_than,
    in_set, is_invalid, less_than,
)
from deeplearning4j_tpu.datavec.transform import TransformProcess
from deeplearning4j_tpu.datavec.reduce import Reducer
from deeplearning4j_tpu.datavec.join import Join
from deeplearning4j_tpu.datavec.analysis import DataAnalysis, analyze
from deeplearning4j_tpu.datavec.iterators import (
    RecordReaderDataSetIterator, SequenceRecordReaderDataSetIterator,
)

__all__ = [
    "ColumnType", "Schema", "RecordReader", "CSVRecordReader",
    "CSVSequenceRecordReader", "LineRecordReader", "CollectionRecordReader",
    "ImageRecordReader", "TransformProcess", "RecordReaderDataSetIterator",
    "SequenceRecordReaderDataSetIterator",
    "Condition", "ColumnCondition", "BooleanCondition",
    "less_than", "greater_than", "equal_to", "in_set", "is_invalid",
    "Reducer", "Join", "DataAnalysis", "analyze",
]
