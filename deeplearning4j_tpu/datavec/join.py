"""Join — relational joins between two record sets.

Reference analog: org.datavec.api.transform.join.Join (+ Builder; executed
by LocalTransformExecutor.executeJoin). Join types: Inner, LeftOuter,
RightOuter, FullOuter; missing side fills with None (the reference's
NullWritable).
"""

from __future__ import annotations

from typing import List, Sequence

from deeplearning4j_tpu.datavec.schema import Schema

_TYPES = ("inner", "left_outer", "right_outer", "full_outer")


class Join:
    def __init__(self, join_type: str, left: Schema, right: Schema,
                 keys: List[str]):
        if join_type not in _TYPES:
            raise ValueError(f"join type must be one of {_TYPES}")
        for k in keys:
            left.index_of(k), right.index_of(k)  # raises KeyError if absent
        self.join_type = join_type
        self.left_schema = left
        self.right_schema = right
        self.keys = list(keys)

    def output_schema(self) -> Schema:
        # key columns once (from left), then left non-key, then right non-key
        cols = [self.left_schema.column(k) for k in self.keys]
        cols += [c for c in self.left_schema.columns if c.name not in self.keys]
        cols += [c for c in self.right_schema.columns
                 if c.name not in self.keys]
        return Schema(cols)

    def execute(self, left: Sequence[list], right: Sequence[list]
                ) -> List[list]:
        lk = [self.left_schema.index_of(k) for k in self.keys]
        rk = [self.right_schema.index_of(k) for k in self.keys]
        lnk = [i for i, c in enumerate(self.left_schema.columns)
               if c.name not in self.keys]
        rnk = [i for i, c in enumerate(self.right_schema.columns)
               if c.name not in self.keys]

        rindex: dict = {}
        for r in right:
            rindex.setdefault(tuple(r[i] for i in rk), []).append(r)

        out = []
        matched_right = set()
        for l in left:
            key = tuple(l[i] for i in lk)
            matches = rindex.get(key, [])
            if matches:
                matched_right.add(key)
                for r in matches:
                    out.append(list(key) + [l[i] for i in lnk]
                               + [r[i] for i in rnk])
            elif self.join_type in ("left_outer", "full_outer"):
                out.append(list(key) + [l[i] for i in lnk]
                           + [None] * len(rnk))
        if self.join_type in ("right_outer", "full_outer"):
            for key, rows in rindex.items():
                if key not in matched_right:
                    for r in rows:
                        out.append(list(key) + [None] * len(lnk)
                                   + [r[i] for i in rnk])
        return out

    # --------------------------------------------------------------- builder
    class Builder:
        def __init__(self, join_type: str = "inner"):
            self._type = join_type
            self._left = None
            self._right = None
            self._keys: List[str] = []

        def set_schemas(self, left: Schema, right: Schema) -> "Join.Builder":
            self._left, self._right = left, right
            return self

        def set_keys(self, *keys: str) -> "Join.Builder":
            self._keys = list(keys)
            return self

        def build(self) -> "Join":
            if self._left is None or self._right is None or not self._keys:
                raise ValueError("set_schemas and set_keys are required")
            return Join(self._type, self._left, self._right, self._keys)

    @staticmethod
    def builder(join_type: str = "inner") -> "Join.Builder":
        return Join.Builder(join_type)
