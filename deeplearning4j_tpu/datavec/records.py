"""RecordReader implementations.

Reference analog: org.datavec.api.records.reader.RecordReader and
impls (CSVRecordReader, LineRecordReader, CollectionRecordReader,
CSVSequenceRecordReader) plus org.datavec.image.recordreader.ImageRecordReader.

A record is a list of Python values (the Writable-list analog); a sequence
record is a list of records. Readers are restartable iterators over
host-side data — ETL stays on host, the device sees finished batches only.
"""

from __future__ import annotations

import csv
import io
import os
from pathlib import Path
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np


class RecordReader:
    """Iterator contract (hasNext/next/reset of the reference)."""

    def __iter__(self) -> Iterator[list]:
        self.reset()
        return self

    def __next__(self) -> list:
        if not self.has_next():
            raise StopIteration
        return self.next_record()

    # --- to implement ---
    def reset(self):
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self) -> list:
        raise NotImplementedError


class CollectionRecordReader(RecordReader):
    """In-memory records (org.datavec...impl.collection.CollectionRecordReader)."""

    def __init__(self, records: Sequence[list]):
        self._records = list(records)
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._records)

    def next_record(self):
        r = self._records[self._pos]
        self._pos += 1
        return list(r)


class LineRecordReader(RecordReader):
    """One record per text line (org.datavec...impl.LineRecordReader)."""

    def __init__(self, path: str | Path):
        self._path = Path(path)
        self._lines: Optional[List[str]] = None
        self._pos = 0

    def reset(self):
        self._lines = self._path.read_text().splitlines()
        self._pos = 0

    def has_next(self):
        if self._lines is None:
            self.reset()
        return self._pos < len(self._lines)

    def next_record(self):
        line = self._lines[self._pos]
        self._pos += 1
        return [line]


class CSVRecordReader(RecordReader):
    """CSV rows as records (org.datavec...impl.csv.CSVRecordReader).

    ``skip_lines`` mirrors the reference's skipNumLines (headers);
    values parse to int/float where possible, else stay strings.
    """

    def __init__(self, path: str | Path = None, skip_lines: int = 0,
                 delimiter: str = ",", text: Optional[str] = None):
        self._path = Path(path) if path is not None else None
        self._text = text
        self._skip = skip_lines
        self._delim = delimiter
        self._rows: Optional[List[list]] = None
        self._pos = 0

    @staticmethod
    def _parse(v: str):
        for cast in (int, float):
            try:
                return cast(v)
            except ValueError:
                continue
        return v

    def reset(self):
        raw = self._text if self._text is not None else self._path.read_text()
        rows = list(csv.reader(io.StringIO(raw), delimiter=self._delim))
        self._rows = [[self._parse(v) for v in r] for r in rows[self._skip:] if r]
        self._pos = 0

    def numeric_array(self):
        """Whole file as a float32 [rows, cols] array.

        Fast path: the multi-threaded native CSV parser (native/
        dl4jtpu_native.cpp dl4j_csv_parse — the reference keeps its ETL hot
        path native the same way); falls back to the Python rows."""
        if self._path is not None and self._skip in (0, 1):
            from deeplearning4j_tpu.native import native_csv_parse

            arr = native_csv_parse(self._path, delimiter=self._delim,
                                   skip_header=self._skip == 1)
            if arr is not None:
                return arr
        if self._rows is None:
            self.reset()
        return np.asarray(self._rows, dtype=np.float32)

    def has_next(self):
        if self._rows is None:
            self.reset()
        return self._pos < len(self._rows)

    def next_record(self):
        r = self._rows[self._pos]
        self._pos += 1
        return list(r)


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence (org.datavec...impl.csv.CSVSequenceRecordReader).

    Iterates over files in a directory (sorted); each record is a list of
    per-timestep records.
    """

    def __init__(self, directory: str | Path, skip_lines: int = 0,
                 delimiter: str = ",", glob: str = "*.csv"):
        self._dir = Path(directory)
        self._skip = skip_lines
        self._delim = delimiter
        self._glob = glob
        self._files: Optional[List[Path]] = None
        self._pos = 0

    def reset(self):
        self._files = sorted(self._dir.glob(self._glob))
        self._pos = 0

    def has_next(self):
        if self._files is None:
            self.reset()
        return self._pos < len(self._files)

    def next_record(self):
        f = self._files[self._pos]
        self._pos += 1
        inner = CSVRecordReader(f, skip_lines=self._skip, delimiter=self._delim)
        return list(inner)


class ImageRecordReader(RecordReader):
    """Images from class-subdirectory trees
    (org.datavec.image.recordreader.ImageRecordReader with
    ParentPathLabelGenerator semantics).

    Files are ``.npy`` arrays ([H, W, C] or [H, W]) — the no-egress sandbox
    has no image codec library, so the decode stage is numpy-native; the
    label is appended as the final record element (class index from the
    sorted parent-directory names), exactly like the reference appends the
    label writable.
    """

    def __init__(self, root: str | Path, height: Optional[int] = None,
                 width: Optional[int] = None, channels: int = 3):
        if (height is None) != (width is None):
            raise ValueError("give both height and width, or neither")
        self._root = Path(root)
        self._h, self._w, self._c = height, width, channels
        self._files: Optional[List[Path]] = None
        self._labels: List[str] = []
        self._pos = 0

    @property
    def labels(self) -> List[str]:
        if self._files is None:
            self.reset()
        return self._labels

    def reset(self):
        self._labels = sorted(p.name for p in self._root.iterdir() if p.is_dir())
        self._files = sorted(self._root.glob("*/*.npy"))
        self._pos = 0

    def has_next(self):
        if self._files is None:
            self.reset()
        return self._pos < len(self._files)

    def _resize(self, img: np.ndarray) -> np.ndarray:
        if img.ndim == 2:
            img = img[..., None]
        if img.shape[-1] == 1 and self._c > 1:
            img = np.repeat(img, self._c, axis=-1)
        if self._h and img.shape[:2] != (self._h, self._w):
            # nearest-neighbor resize, dependency-free
            ys = (np.arange(self._h) * img.shape[0] / self._h).astype(int)
            xs = (np.arange(self._w) * img.shape[1] / self._w).astype(int)
            img = img[ys][:, xs]
        return img.astype(np.float32)

    def next_record(self):
        f = self._files[self._pos]
        self._pos += 1
        img = self._resize(np.load(f))
        label = self._labels.index(f.parent.name)
        return [img, label]
