"""Multi-host distribution + fault tolerance.

Reference analog (SURVEY.md §2.4, §5): the Spark TrainingMaster / Aeron
VoidParameterServer stack — worker membership, heartbeat/mesh repair
(MeshOrganizer), RDD-lineage retry. TPU-native, the transport disappears
entirely: jax.distributed + XLA collectives over ICI/DCN own communication,
so what remains of "fault tolerance" is (a) coordinated multi-host init from
environment and (b) checkpoint-based restart — a crashed job relaunches,
re-initializes, restores the latest step, and continues (the elastic story
the reference implements with Spark retries).
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from typing import Callable, Optional

import jax


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           retry=None) -> dict:
    """jax.distributed.initialize wrapper, env-driven like the reference's
    VoidParameterServer config (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID;
    on TPU pods the args auto-detect from the metadata server).

    The coordinator connect runs under a :class:`faults.RetryPolicy`
    (``retry`` overrides the default 5-attempt exponential backoff): a
    coordinator that is still coming up after a pod relaunch refuses a few
    connects before accepting — one-shot init turned that into a dead job.
    Fault class ``coord_connect`` injects exactly that refusal.

    Returns a summary dict; a no-op single-process summary when no
    coordinator is configured.
    """
    from deeplearning4j_tpu import faults

    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address is not None or num_processes is not None:
        try:
            # pre-0.5 jax needs the CPU cross-process transport selected
            # explicitly before backend init; newer jax defaults to gloo
            # (no-op elsewhere: the option only affects the CPU backend)
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass

        def _connect():
            plan = faults.active()
            if plan is not None and plan.fires("coord_connect"):
                raise faults.CoordinatorConnectFault(
                    f"injected connection refusal to coordinator "
                    f"{coordinator_address}")
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)

        policy = retry or faults.RetryPolicy(
            max_attempts=5, base_delay_s=0.2, max_delay_s=5.0,
            deadline_s=120.0)
        policy.call(_connect, component="distributed")
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


class FaultTolerantTrainer:
    """Checkpoint-restart training loop.

    Wraps any model exposing fit_batch/params with a TrainingCheckpointer:
    on construction it restores the newest checkpoint if one exists (the
    relaunch path), and during training it saves every ``save_every`` steps.
    A crash at any point loses at most ``save_every`` steps — the same
    guarantee the reference gets from Spark's retry + param-averaging
    master, without a parameter server.

        trainer = FaultTolerantTrainer(model, ckpt_dir, save_every=50)
        trainer.fit(iterator, epochs=3)    # safe to kill + rerun
    """

    def __init__(self, model, checkpoint_dir: str, save_every: int = 100,
                 keep_last: int = 3, on_restore: Optional[Callable] = None,
                 max_restarts_without_progress: int = 3):
        from deeplearning4j_tpu.util.checkpoints import TrainingCheckpointer

        self.model = model
        # r5: a parallel facade (ParallelWrapper / TensorParallel) trains,
        # but its .model owns params/opt_state/step_count — train through
        # the facade, checkpoint the owner. The unwrap is deliberately
        # narrow (isinstance, not duck-typed .model) so an unrelated
        # object with a .model attribute is checkpointed as itself.
        # Under jax.distributed EVERY process constructs the trainer and
        # calls save/restore at the same steps; orbax coordinates the
        # multi-process write and its committed step directories make the
        # recovery point atomic.
        from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
        from deeplearning4j_tpu.parallel.tensor_parallel import TensorParallel

        self._target = (model.model
                        if isinstance(model, (ParallelWrapper, TensorParallel))
                        else model)
        self.save_every = max(1, save_every)
        self.checkpoint_dir = str(checkpoint_dir)
        self.checkpointer = TrainingCheckpointer(checkpoint_dir,
                                                 keep_last=keep_last)
        self.restored_step = self.checkpointer.restore_latest(self._target)
        self._check_crash_loop(max_restarts_without_progress)
        if self.restored_step is not None and on_restore:
            on_restore(self.restored_step)
        # set whenever no fit() loop is mid-step: the preemption drain's
        # emergency save waits on it so it never serializes arrays a
        # concurrent (donating) train step is about to delete
        self._parked = threading.Event()
        self._parked.set()

    # --------------------------------------------------- crash-loop bound
    def _crashloop_path(self) -> str:
        return os.path.join(self.checkpoint_dir, ".crashloop.json")

    def _check_crash_loop(self, bound: int) -> None:
        """A relaunch that restores the SAME step as the previous relaunch
        made no progress — the crash is deterministic (bad batch, poisoned
        state), and restarting forever burns the pod. Bound it: after
        ``bound`` restarts at one step, fail loud instead of looping.
        State lives in a marker file so it survives the process boundary
        the way the crashes do."""
        if self.restored_step is None or bound <= 0:
            return
        path = self._crashloop_path()
        count = 1
        try:
            with open(path) as f:
                prev = json.load(f)
            if int(prev.get("step", -1)) == int(self.restored_step):
                count = int(prev.get("count", 0)) + 1
        except (OSError, ValueError):
            pass
        if jax.process_index() == 0:
            try:
                with open(path, "w") as f:
                    json.dump({"step": int(self.restored_step),
                               "count": count}, f)
            except OSError:
                pass
        if count > bound:
            from deeplearning4j_tpu import monitoring

            mon = monitoring.recovery_monitor()
            if mon is not None:
                mon.recovery_total.labels(component="trainer",
                                          outcome="crash_loop").inc()
            raise RuntimeError(
                f"crash loop detected: {count} consecutive relaunches "
                f"restored step {self.restored_step} without progressing "
                f"past it (bound {bound}). The failure is likely "
                f"deterministic — inspect the step, the data at it, and "
                f"{path} before relaunching (delete the file to override).")

    # ------------------------------------------------------- preemption
    def register_lifecycle(self, manager) -> "FaultTolerantTrainer":
        """Register the emergency checkpoint with a
        :class:`~deeplearning4j_tpu.serving.lifecycle.LifecycleManager`:
        on SIGTERM (or an injected ``preempt`` fault) the drain saves the
        current step inside the grace budget, so the relaunch loses zero
        steps instead of up to ``save_every``."""
        manager.register_checkpoint(self._emergency_save)
        return self

    def _emergency_save(self) -> None:
        self._parked.wait(timeout=30.0)
        self.checkpointer.save(self._target.step_count, self._target)
        self.checkpointer.wait()
        from deeplearning4j_tpu import monitoring

        mon = monitoring.recovery_monitor()
        if mon is not None:
            mon.recovery_total.labels(component="trainer",
                                      outcome="preempt_save").inc()

    @staticmethod
    def _preempting() -> bool:
        """A managed preemption drain is in progress (the fit loop exits
        between batches so the emergency save captures settled state)."""
        from deeplearning4j_tpu.serving import lifecycle

        mgr = lifecycle.manager()
        return mgr is not None and mgr.reason is not None

    def fit_batch(self, ds) -> float:
        from deeplearning4j_tpu import faults

        plan = faults.active()
        if plan is not None and plan.fires("preempt",
                                           step=self._target.step_count):
            # in-process SIGTERM equivalent: managed -> the lifecycle
            # drain starts (this call returns and the fit loop exits at
            # the next batch boundary); unmanaged -> PreemptionFault
            # propagates into fit()'s save-on-exception path
            from deeplearning4j_tpu.serving import lifecycle

            lifecycle.deliver_preemption(source="trainer",
                                         step=self._target.step_count)
            if self._preempting():
                # managed: the grace budget pays for the checkpoint, not
                # another train step — the drain saves the current one
                return float("nan")
        loss = self.model.fit_batch(ds)
        step = self._target.step_count
        if step % self.save_every == 0:
            self.checkpointer.save(step, self._target)
        return loss

    def fit(self, data, epochs: int = 1):
        self._parked.clear()
        try:
            for _ in range(epochs):
                for ds in data:
                    self.fit_batch(ds)
                    if self._preempting():
                        # the drain's checkpoint callback (see
                        # register_lifecycle) saves this step
                        return self.model
                if hasattr(data, "reset"):
                    data.reset()
                self._target.epoch_count += 1
        except Exception:
            # save-on-exception: capture the last good in-memory state so
            # the relaunch resumes from HERE, not save_every steps back.
            # Best effort — the original failure always propagates.
            try:
                self.checkpointer.save(self._target.step_count, self._target)
                self.checkpointer.wait()
                from deeplearning4j_tpu import monitoring

                mon = monitoring.recovery_monitor()
                if mon is not None:
                    mon.recovery_total.labels(
                        component="trainer", outcome="save_on_error").inc()
            except Exception as save_err:  # noqa: BLE001 — never mask the
                # original failure with a checkpoint error
                warnings.warn(f"save-on-exception failed: {save_err}")
            raise
        finally:
            self._parked.set()
        self.checkpointer.save(self._target.step_count, self._target)
        self.checkpointer.wait()
        return self.model

    def close(self):
        self.checkpointer.close()
