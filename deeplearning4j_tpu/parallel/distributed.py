"""Multi-host distribution + fault tolerance.

Reference analog (SURVEY.md §2.4, §5): the Spark TrainingMaster / Aeron
VoidParameterServer stack — worker membership, heartbeat/mesh repair
(MeshOrganizer), RDD-lineage retry. TPU-native, the transport disappears
entirely: jax.distributed + XLA collectives over ICI/DCN own communication,
so what remains of "fault tolerance" is (a) coordinated multi-host init from
environment and (b) checkpoint-based restart — a crashed job relaunches,
re-initializes, restores the latest step, and continues (the elastic story
the reference implements with Spark retries).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import jax


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> dict:
    """jax.distributed.initialize wrapper, env-driven like the reference's
    VoidParameterServer config (COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID;
    on TPU pods the args auto-detect from the metadata server).

    Returns a summary dict; a no-op single-process summary when no
    coordinator is configured.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address is not None or num_processes is not None:
        try:
            # pre-0.5 jax needs the CPU cross-process transport selected
            # explicitly before backend init; newer jax defaults to gloo
            # (no-op elsewhere: the option only affects the CPU backend)
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


class FaultTolerantTrainer:
    """Checkpoint-restart training loop.

    Wraps any model exposing fit_batch/params with a TrainingCheckpointer:
    on construction it restores the newest checkpoint if one exists (the
    relaunch path), and during training it saves every ``save_every`` steps.
    A crash at any point loses at most ``save_every`` steps — the same
    guarantee the reference gets from Spark's retry + param-averaging
    master, without a parameter server.

        trainer = FaultTolerantTrainer(model, ckpt_dir, save_every=50)
        trainer.fit(iterator, epochs=3)    # safe to kill + rerun
    """

    def __init__(self, model, checkpoint_dir: str, save_every: int = 100,
                 keep_last: int = 3, on_restore: Optional[Callable] = None):
        from deeplearning4j_tpu.util.checkpoints import TrainingCheckpointer

        self.model = model
        # r5: a parallel facade (ParallelWrapper / TensorParallel) trains,
        # but its .model owns params/opt_state/step_count — train through
        # the facade, checkpoint the owner. The unwrap is deliberately
        # narrow (isinstance, not duck-typed .model) so an unrelated
        # object with a .model attribute is checkpointed as itself.
        # Under jax.distributed EVERY process constructs the trainer and
        # calls save/restore at the same steps; orbax coordinates the
        # multi-process write and its committed step directories make the
        # recovery point atomic.
        from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
        from deeplearning4j_tpu.parallel.tensor_parallel import TensorParallel

        self._target = (model.model
                        if isinstance(model, (ParallelWrapper, TensorParallel))
                        else model)
        self.save_every = max(1, save_every)
        self.checkpointer = TrainingCheckpointer(checkpoint_dir,
                                                 keep_last=keep_last)
        self.restored_step = self.checkpointer.restore_latest(self._target)
        if self.restored_step is not None and on_restore:
            on_restore(self.restored_step)

    def fit_batch(self, ds) -> float:
        loss = self.model.fit_batch(ds)
        step = self._target.step_count
        if step % self.save_every == 0:
            self.checkpointer.save(step, self._target)
        return loss

    def fit(self, data, epochs: int = 1):
        for _ in range(epochs):
            for ds in data:
                self.fit_batch(ds)
            if hasattr(data, "reset"):
                data.reset()
            self._target.epoch_count += 1
        self.checkpointer.save(self._target.step_count, self._target)
        self.checkpointer.wait()
        return self.model

    def close(self):
        self.checkpointer.close()
