"""Sequence / context parallelism — ring attention.

Reference analog: NONE — the reference's only long-sequence mechanism is
truncated BPTT on one device (MultiLayerConfiguration tBPTTLength; SURVEY.md
§5 "Long-context"). This is net-new capability, designed TPU-first: the
sequence axis is sharded over the mesh's "seq" axis; each device holds a
query block and rotates K/V blocks around the ICI ring with ppermute while
accumulating attention online (flash-attention-style running max/denominator),
so peak memory is O(T/n) and the T^2 work is evenly spread.

Two local cores, selected per shape:
- the Pallas flash kernel path (``_ring_flash``): each ring step runs the
  blocked flash forward on its current K/V block and merges (o, lse) pairs
  online; its custom_vjp re-rotates K/V around the ring while dk/dv partial
  gradients travel WITH their blocks, so backward memory is O(T/n * D) per
  device too — long-context *training* stays sub-quadratic end to end.
- a plain-XLA einsum path for small/unaligned shapes (materializes the local
  [Tq, Tk] tile per step; fine at toy scale, and exercised by the same
  parity tests).

Also provides Ulysses-style head-scatter attention (all_to_all swapping the
shard axis from sequence to heads), the bandwidth-cheaper alternative when
n_heads >= n_devices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel._compat import pvary as _pvary, shard_map


def _ring_attention_local(q, k, v, kmask=None, *, axis, causal, scale):
    """Per-device body. q/k/v local blocks [B, H, Tq, D] / [B, H, Tk, D];
    ``kmask`` an optional key-padding shard [B, Tk] (>0 = visible) that
    rotates around the ring WITH its K/V block (r4)."""
    axis_size = lax.psum(1, axis)
    my_idx = lax.axis_index(axis)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    neg = jnp.finfo(jnp.float32).min

    q32 = q.astype(jnp.float32) * scale
    # The accumulators become device-varying inside the loop (they depend on
    # my_idx via the causal mask and on the rotating K/V); mark them varying
    # up front so the fori_loop carry types are stable.
    m0 = _pvary(jnp.full((B, H, Tq, 1), neg, jnp.float32), (axis,))
    l0 = _pvary(jnp.zeros((B, H, Tq, 1), jnp.float32), (axis,))
    o0 = _pvary(jnp.zeros((B, H, Tq, D), jnp.float32), (axis,))
    qpos = my_idx * Tq + jnp.arange(Tq)
    # kmask is a TRACE-time branch: without a mask the carry omits the mask
    # shard entirely (no dead ppermute per ring step)
    has_km = kmask is not None

    def body(i, carry):
        if has_km:
            m, l, o, k, v, km = carry
        else:
            m, l, o, k, v = carry
        src = (my_idx - i) % axis_size  # which global block we currently hold
        logits = jnp.einsum("bhqd,bhkd->bhqk", q32, k.astype(jnp.float32))
        if causal:
            kpos = src * Tk + jnp.arange(Tk)
            mask = qpos[:, None] >= kpos[None, :]
            logits = jnp.where(mask, logits, neg)
        if has_km:
            logits = jnp.where(km[:, None, None, :] > 0, logits, neg)
        m_new = jnp.maximum(m, logits.max(-1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(-1, keepdims=True)
        o = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k = lax.ppermute(k, axis, perm)
        v = lax.ppermute(v, axis, perm)
        if has_km:
            km = lax.ppermute(km, axis, perm)
            return m_new, l, o, k, v, km
        return m_new, l, o, k, v

    carry0 = (m0, l0, o0, k, v)
    if has_km:
        carry0 = carry0 + (kmask.astype(jnp.float32),)
    out = lax.fori_loop(0, axis_size, body, carry0)
    l, o = out[1], out[2]
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


# --------------------------------------------------------------------------
# flash-kernel ring core (sub-quadratic fwd AND bwd)
# --------------------------------------------------------------------------


def _rotate(x, axis, axis_size):
    return lax.ppermute(x, axis, [(j, (j + 1) % axis_size) for j in range(axis_size)])


def _merge_lse(o, lse, o_i, lse_i):
    """Combine two softmax partial results normalized with their own lse.

    The flash forward kernel emits lse=+inf for fully-masked rows (so its
    backward's exp(s - lse) is exactly 0). For the MERGE contract +inf is
    poison — logaddexp(x, +inf)=+inf would zero both weights and discard the
    other side's accumulated rows — so normalize the sentinel to -inf ("this
    side contributes nothing") before merging. Relevant for cross-attention
    or unequal q/k lengths where a ring step can see fully-masked rows."""
    lse = jnp.where(jnp.isposinf(lse), -jnp.inf, lse)
    lse_i = jnp.where(jnp.isposinf(lse_i), -jnp.inf, lse_i)
    lse_new = jnp.logaddexp(lse, lse_i)
    w_old = jnp.where(jnp.isfinite(lse), jnp.exp(lse - lse_new), 0.0)
    w_new = jnp.where(jnp.isfinite(lse_i), jnp.exp(lse_i - lse_new), 0.0)
    return o * w_old + o_i.astype(jnp.float32) * w_new, lse_new


def _ring_flash_fwd_impl(q, k, v, kmask, axis, causal, scale, block_q,
                         block_k):
    from deeplearning4j_tpu.ops.pallas.flash_attention import flash_block_fwd

    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    B, H, Tq, D = q.shape
    o = jnp.zeros((B, H, Tq, D), jnp.float32)
    lse = jnp.full((B, H, Tq, 1), -jnp.inf, jnp.float32)
    o, lse = _pvary(o, (axis,)), _pvary(lse, (axis,))
    k_cur, v_cur = k, v
    km_cur = None if kmask is None else kmask.astype(jnp.float32)
    blk = functools.partial(flash_block_fwd, scale=scale,
                            block_q=block_q, block_k=block_k, vma=(axis,))
    for i in range(n):
        if i == 0:
            # the diagonal block: start-aligned causal mask is exact here
            o_i, lse_i = blk(q, k_cur, v_cur, causal=causal, kmask=km_cur)
        elif causal:
            src = (my - i) % n  # which global K/V block we currently hold
            o_i, lse_i = lax.cond(
                src < my,
                lambda kv: blk(q, kv[0], kv[1], causal=False, kmask=kv[2]),
                lambda kv: (jnp.zeros((B, H, Tq, D), q.dtype),
                            jnp.full((B, H, Tq, 1), -jnp.inf, jnp.float32)),
                (k_cur, v_cur, km_cur))
        else:
            o_i, lse_i = blk(q, k_cur, v_cur, causal=False, kmask=km_cur)
        # a fully-masked step emits lse=+inf; _merge_lse normalizes it to
        # "contributes nothing", so padded-out blocks drop out exactly
        o, lse = _merge_lse(o, lse, o_i, lse_i)
        if i < n - 1:
            k_cur = _rotate(k_cur, axis, n)
            v_cur = _rotate(v_cur, axis, n)
            if km_cur is not None:
                km_cur = _rotate(km_cur, axis, n)
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _ring_flash(q, k, v, kmask, axis, causal, scale, block_q, block_k):
    return _ring_flash_fwd_impl(q, k, v, kmask, axis, causal, scale,
                                block_q, block_k)[0]


def _ring_flash_vjp_fwd(q, k, v, kmask, axis, causal, scale, block_q,
                        block_k):
    o, lse = _ring_flash_fwd_impl(q, k, v, kmask, axis, causal, scale,
                                  block_q, block_k)
    return o, (q, k, v, kmask, o, lse)


def _ring_flash_vjp_bwd(axis, causal, scale, block_q, block_k, res, do):
    """True ring backward: K/V (and the key-padding mask shard) re-rotate
    while each block's dk/dv partial travels WITH it; after n steps every
    carry is home with contributions from every device. Per-device memory
    stays O(Tq/n * D)."""
    from deeplearning4j_tpu.ops.pallas.flash_attention import flash_block_bwd

    q, k, v, kmask, o, lse = res
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(
        axis=-1, keepdims=True)
    dq = _pvary(jnp.zeros(q.shape, jnp.float32), (axis,))
    dk_carry = _pvary(jnp.zeros(k.shape, jnp.float32), (axis,))
    dv_carry = _pvary(jnp.zeros(v.shape, jnp.float32), (axis,))
    k_cur, v_cur = k, v
    km_cur = None if kmask is None else kmask.astype(jnp.float32)
    # bwd kernels want large tiles, bounded by VMEM (see bwd_tiles)
    from deeplearning4j_tpu.ops.pallas.flash_attention import bwd_tiles

    bwq, bwk = bwd_tiles(block_q, block_k, q.shape[-1])
    blk = functools.partial(flash_block_bwd, scale=scale,
                            block_q=bwq, block_k=bwk, vma=(axis,))
    for i in range(n):
        if i == 0:
            dq_i, dk_i, dv_i = blk(q, k_cur, v_cur, do, lse, delta,
                                   causal=causal, kmask=km_cur)
        elif causal:
            src = (my - i) % n
            dq_i, dk_i, dv_i = lax.cond(
                src < my,
                lambda kv: blk(q, kv[0], kv[1], do, lse, delta,
                               causal=False, kmask=kv[2]),
                lambda kv: (jnp.zeros(q.shape, jnp.float32),
                            jnp.zeros(k.shape, jnp.float32),
                            jnp.zeros(v.shape, jnp.float32)),
                (k_cur, v_cur, km_cur))
        else:
            dq_i, dk_i, dv_i = blk(q, k_cur, v_cur, do, lse, delta,
                                   causal=False, kmask=km_cur)
        dq = dq + dq_i
        dk_carry = dk_carry + dk_i
        dv_carry = dv_carry + dv_i
        # the carries rotate every step INCLUDING the last — that final hop
        # lands each block's accumulated gradient back on its home device;
        # k/v themselves are dead after the last compute, so skip their hop
        if i < n - 1:
            k_cur = _rotate(k_cur, axis, n)
            v_cur = _rotate(v_cur, axis, n)
            if km_cur is not None:
                km_cur = _rotate(km_cur, axis, n)
        dk_carry = _rotate(dk_carry, axis, n)
        dv_carry = _rotate(dv_carry, axis, n)
    dkm = None if kmask is None else jnp.zeros_like(kmask)
    return (dq.astype(q.dtype), dk_carry.astype(k.dtype),
            dv_carry.astype(v.dtype), dkm)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def _ring_flash_local(q, k, v, kmask=None, *, axis, causal, scale,
                      block_q=512, block_k=1024):
    return _ring_flash(q, k, v, kmask, axis, causal, scale,
                       min(block_q, q.shape[2]), min(block_k, k.shape[2]))


def _flash_core_ok(head_dim: int, t_local: int) -> bool:
    """Mosaic wants lane-aligned head_dim; sublane-aligned local seq."""
    return head_dim % 128 == 0 and t_local % 8 == 0 and t_local >= 8


def _select_ring_core(head_dim: int, t_local: int):
    """(local_fn, check_vma) for the ring attention core — single decision
    point shared by ring_attention and sequence_parallel_encoder. The Pallas
    core needs the VMA checker off (pallas_call in interpret mode can't
    satisfy it yet — jax hlo_interpreter dynamic_slice limitation); the
    einsum path keeps full checking."""
    if _flash_core_ok(head_dim, t_local):
        return _ring_flash_local, False
    return _ring_attention_local, True


def ring_attention(q, k, v, mesh, *, axis: str = "seq", causal: bool = False,
                   scale: float | None = None, impl: str | None = None,
                   mask=None):
    """Ring attention over a mesh axis.

    q/k/v: [B, H, T, D] with T sharded over ``axis`` (logically; pass the
    full array — shard_map splits it). Returns [B, H, T, D] sharded the same.

    impl: None (auto: flash kernel core when shapes are TPU-aligned),
    "flash", or "einsum".

    mask (r4): optional key-padding mask [B, T] (>0 = key visible), sharded
    over ``axis`` like the keys; each shard travels the ring WITH its K/V
    block, so padded-batch long-context training works without ever
    materializing a [T, T] mask. Rows whose keys are ALL masked follow the
    local core's convention (flash core: exact zeros; einsum core: uniform
    attention, matching the plain XLA lowering)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    size = mesh.shape[axis]
    if impl is None:
        local, check_vma = _select_ring_core(q.shape[-1], q.shape[2] // size)
    elif impl == "flash":
        if not _flash_core_ok(q.shape[-1], q.shape[2] // size):
            raise ValueError(
                "ring_attention(impl='flash') needs head_dim % 128 == 0 and "
                f"local seq % 8 == 0; got head_dim={q.shape[-1]}, "
                f"T_local={q.shape[2] // size} — use impl='einsum' or pad")
        local, check_vma = _ring_flash_local, False
    else:
        local, check_vma = _ring_attention_local, True
    body = functools.partial(local, axis=axis, causal=causal, scale=scale)
    if mask is None:
        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P(None, None, axis, None),) * 3,
            out_specs=P(None, None, axis, None),
            check_vma=check_vma,
        )
        return fn(q, k, v)
    if tuple(mask.shape) != (q.shape[0], k.shape[2]):
        raise ValueError(f"ring_attention mask must be a key-padding mask "
                         f"[B, T] = {(q.shape[0], k.shape[2])}; got "
                         f"{tuple(mask.shape)}")
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3 + (P(None, axis),),
        out_specs=P(None, None, axis, None),
        check_vma=check_vma,
    )
    return fn(q, k, v, mask)


def _ulysses_local(q, k, v, *, axis, causal, scale):
    """Ulysses: all_to_all turns seq-sharded [B,H,Tl,D] into head-sharded
    [B,Hl,T,D], runs full-sequence attention locally, then swaps back."""
    # gather sequence, scatter heads
    q = lax.all_to_all(q, axis, split_axis=1, concat_axis=2, tiled=True)
    k = lax.all_to_all(k, axis, split_axis=1, concat_axis=2, tiled=True)
    v = lax.all_to_all(v, axis, split_axis=1, concat_axis=2, tiled=True)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        T = logits.shape[-1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", w, v.astype(jnp.float32)).astype(q.dtype)
    # scatter sequence back, gather heads
    return lax.all_to_all(o, axis, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(q, k, v, mesh, *, axis: str = "seq", causal: bool = False,
                      scale: float | None = None):
    """Ulysses-style sequence parallelism (head all-to-all). Requires
    n_heads % axis_size == 0."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    fn = shard_map(
        functools.partial(_ulysses_local, axis=axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
    )
    return fn(q, k, v)


def _ulysses_causal_guard(n_heads, mesh, axis):
    size = mesh.shape[axis]
    if n_heads % size:
        raise ValueError(f"ulysses needs n_heads ({n_heads}) divisible by "
                         f"mesh axis '{axis}' size ({size})")


def sequence_parallel_encoder(params, x, mesh, *, n_heads: int,
                              axis: str = "seq", causal: bool = False,
                              impl: str = "ring", activation: str = "gelu"):
    """TransformerEncoderLayer forward with activations sequence-sharded.

    Takes the SAME param dict as nn.layers.attention.TransformerEncoderLayer
    (pre-norm form) and produces identical outputs, but every activation is
    sharded [B, T/n, D] over the mesh's ``axis``: LN, QKV/output projections
    and the MLP are per-token (no communication), and only the attention core
    communicates — ppermute KV rotation (impl="ring") or head all-to-all
    (impl="ulysses"). This is the long-context training path the reference
    lacks entirely (its only tool is single-device truncated BPTT,
    MultiLayerConfiguration.tBPTTLength — SURVEY.md §5).

    x: [B, T, D] with T divisible by the axis size. Returns [B, T, D].

    impl="zigzag" (causal only) uses the load-balanced zig-zag ring core
    and runs ENTIRELY in the permuted domain: pass x already permuted with
    ``zigzag_shard(x, mesh, seq_axis=1)`` (done ONCE per run, together with
    labels/masks); the output comes back zig-zag-permuted too. All
    per-token math in the block is order-agnostic, so stacking layers and
    computing per-token losses needs no unpermute — that is the "at scale"
    path with zero per-step gathers.
    """
    from deeplearning4j_tpu.nn.layers.base import resolve_activation

    act = resolve_activation(activation)
    if impl == "ulysses":
        _ulysses_causal_guard(n_heads, mesh, axis)
    elif impl == "zigzag":
        if not causal:
            raise ValueError("impl='zigzag' is the load-balanced CAUSAL "
                             "ring; use impl='ring' for non-causal")
        _zigzag_guard(x.shape[1], mesh.shape[axis], x.shape[-1] // n_heads)
    elif impl != "ring":
        raise ValueError(
            f"impl must be 'ring', 'zigzag' or 'ulysses', got {impl!r}")
    # decided here (not in the traced body) so check_vma below can match
    if impl == "ring":
        _ring_local, _check_vma = _select_ring_core(
            x.shape[-1] // n_heads, x.shape[1] // mesh.shape[axis])
    elif impl == "zigzag":
        def _ring_local(q, k, v, *, axis, causal, scale):
            return _ring_zigzag_local(q, k, v, axis=axis, scale=scale)

        _check_vma = False
    else:
        _ring_local, _check_vma = None, True

    def _ln(h, g, b):
        m = h.mean(-1, keepdims=True)
        v = h.var(-1, keepdims=True)
        return (h - m) * jax.lax.rsqrt(v + 1e-5) * g + b

    def block(p, xl):
        B, Tl, D = xl.shape
        dh = D // n_heads
        scale = 1.0 / (dh ** 0.5)

        h = _ln(xl, p["ln1_g"], p["ln1_b"])
        # per-token projections on the local shard
        def heads(w, b):
            y = h @ w + b
            return y.reshape(B, Tl, n_heads, dh).transpose(0, 2, 1, 3)

        q = heads(p["Wq"], p["bq"])
        k = heads(p["Wk"], p["bk"])
        v = heads(p["Wv"], p["bv"])
        local = _ulysses_local if impl == "ulysses" else _ring_local
        a = local(q, k, v, axis=axis, causal=causal, scale=scale)
        a = a.transpose(0, 2, 1, 3).reshape(B, Tl, D) @ p["Wo"] + p["bo"]
        xl = xl + a

        h = _ln(xl, p["ln2_g"], p["ln2_b"])
        m = act(h @ p["W1"] + p["b1"]) @ p["W2"] + p["b2"]
        return xl + m

    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P(), P(None, axis, None)),
        out_specs=P(None, axis, None),
        check_vma=_check_vma,
    )
    return fn(params, x)


# --------------------------------------------------------------------------
# zig-zag (load-balanced) causal ring attention
# --------------------------------------------------------------------------
#
# With contiguous sequence sharding, causal masking makes the ring
# triangular: device 0 attends 1 block, device n-1 attends n — wall-clock is
# set by the last device while the rest idle. Zig-zag sharding gives every
# device TWO stripes, one from each end (device i holds stripes i and
# 2n-1-i of 2n), which balances the visible work exactly: at t=0 each
# device runs two diagonal tiles + one full tile; at every later step each
# device runs exactly two full tiles (the pair (b_i, a_s) is always
# visible, and exactly one of (a_i, a_s) / (b_i, b_s) is, depending on the
# sign of i - s). The flash kernels stay the per-tile core, and the
# backward rotates dk/dv carries with their blocks exactly like the
# contiguous ring.


def zigzag_permutation(T: int, n: int):
    """(perm, inverse): sequence index permutation placing stripes
    [i, 2n-1-i] on device i. T must divide into 2n stripes."""
    if T % (2 * n):
        raise ValueError(f"zigzag needs T ({T}) divisible by 2*{n} stripes")
    S = T // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * S, (i + 1) * S))
        order.extend(range((2 * n - 1 - i) * S, (2 * n - i) * S))
    perm = np.asarray(order)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(T)
    return perm, inv


def _zz_none(B, H, S, D):
    return (jnp.zeros((B, H, S, D), jnp.float32),
            jnp.full((B, H, S, 1), -jnp.inf, jnp.float32))


def _ring_zigzag_fwd_impl(q, k, v, axis, scale, block_q, block_k):
    from deeplearning4j_tpu.ops.pallas.flash_attention import flash_block_fwd

    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    B, H, Tl, D = q.shape
    S = Tl // 2
    blk = functools.partial(flash_block_fwd, scale=scale,
                            block_q=block_q, block_k=block_k, vma=(axis,))
    qa, qb = q[:, :, :S], q[:, :, S:]
    ka, kb = k[:, :, :S], k[:, :, S:]
    va, vb = v[:, :, :S], v[:, :, S:]

    # t = 0: (a,a) diag, (b,b) diag, (b,a) full — all static
    oa, la = blk(qa, ka, va, causal=True)
    oa, la = oa.astype(jnp.float32), la
    ob1, lb1 = blk(qb, kb, vb, causal=True)
    ob2, lb2 = blk(qb, ka, va, causal=False)
    ob, lb = _merge_lse(ob1.astype(jnp.float32), lb1, ob2, lb2)

    k_cur, v_cur = k, v
    for t in range(1, n):
        k_cur = _rotate(k_cur, axis, n)
        v_cur = _rotate(v_cur, axis, n)
        kac, kbc = k_cur[:, :, :S], k_cur[:, :, S:]
        vac, vbc = v_cur[:, :, :S], v_cur[:, :, S:]
        s = (my - t) % n
        # always visible: (b_i, a_s) full
        ob_c, lb_c = blk(qb, kac, vac, causal=False)
        ob, lb = _merge_lse(ob, lb, ob_c, lb_c)
        # exactly one of (a_i, a_s) / (b_i, b_s), by sign of i - s
        def _f32(pair):
            o, l = pair
            return o.astype(jnp.float32), l  # match the dead branch's dtype

        contrib = lax.cond(
            my > s,
            lambda kv: (*_f32(blk(qa, kv[0], kv[1], causal=False)),
                        *_zz_none(B, H, S, D)),
            lambda kv: (*_zz_none(B, H, S, D),
                        *_f32(blk(qb, kv[2], kv[3], causal=False))),
            (kac, vac, kbc, vbc))
        oa_c, la_c, ob2_c, lb2_c = contrib
        oa, la = _merge_lse(oa, la, oa_c, la_c)
        ob, lb = _merge_lse(ob, lb, ob2_c, lb2_c)
    out = jnp.concatenate([oa, ob], axis=2).astype(q.dtype)
    lse = jnp.concatenate([la, lb], axis=2)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_zigzag(q, k, v, axis, scale, block_q, block_k):
    return _ring_zigzag_fwd_impl(q, k, v, axis, scale, block_q, block_k)[0]


def _ring_zigzag_vjp_fwd(q, k, v, axis, scale, block_q, block_k):
    o, lse = _ring_zigzag_fwd_impl(q, k, v, axis, scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _ring_zigzag_vjp_bwd(axis, scale, block_q, block_k, res, do):
    from deeplearning4j_tpu.ops.pallas.flash_attention import (bwd_tiles,
                                                               flash_block_bwd)

    q, k, v, o, lse = res
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)
    B, H, Tl, D = q.shape
    S = Tl // 2
    bwq, bwk = bwd_tiles(block_q, block_k, D)
    blk = functools.partial(flash_block_bwd, scale=scale,
                            block_q=bwq, block_k=bwk, vma=(axis,))
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1,
                                                                 keepdims=True)
    qa, qb = q[:, :, :S], q[:, :, S:]
    doa, dob = do[:, :, :S], do[:, :, S:]
    la, lb = lse[:, :, :S], lse[:, :, S:]
    da, db = delta[:, :, :S], delta[:, :, S:]

    zq = jnp.zeros((B, H, S, D), jnp.float32)
    dqa = _pvary(zq, (axis,))
    dqb = _pvary(zq, (axis,))
    dk_carry = _pvary(jnp.zeros(k.shape, jnp.float32), (axis,))
    dv_carry = _pvary(jnp.zeros(v.shape, jnp.float32), (axis,))
    k_cur, v_cur = k, v
    for t in range(n):
        kac, kbc = k_cur[:, :, :S], k_cur[:, :, S:]
        vac, vbc = v_cur[:, :, :S], v_cur[:, :, S:]
        dka = jnp.zeros((B, H, S, D), jnp.float32)
        dva = jnp.zeros((B, H, S, D), jnp.float32)
        dkb = jnp.zeros((B, H, S, D), jnp.float32)
        dvb = jnp.zeros((B, H, S, D), jnp.float32)
        if t == 0:
            g1 = blk(qa, kac, vac, doa, la, da, causal=True)
            dqa, dka, dva = dqa + g1[0], dka + g1[1], dva + g1[2]
            g2 = blk(qb, kbc, vbc, dob, lb, db, causal=True)
            dqb, dkb, dvb = dqb + g2[0], dkb + g2[1], dvb + g2[2]
            g3 = blk(qb, kac, vac, dob, lb, db, causal=False)
            dqb, dka, dva = dqb + g3[0], dka + g3[1], dva + g3[2]
        else:
            s = (my - t) % n
            g3 = blk(qb, kac, vac, dob, lb, db, causal=False)
            dqb, dka, dva = dqb + g3[0], dka + g3[1], dva + g3[2]
            ga, gb = lax.cond(
                my > s,
                lambda kv: (blk(qa, kv[0], kv[1], doa, la, da, causal=False),
                            (zq, zq, zq)),
                lambda kv: ((zq, zq, zq),
                            blk(qb, kv[2], kv[3], dob, lb, db, causal=False)),
                (kac, vac, kbc, vbc))
            dqa, dka, dva = dqa + ga[0], dka + ga[1], dva + ga[2]
            dqb, dkb, dvb = dqb + gb[0], dkb + gb[1], dvb + gb[2]
        dk_carry = dk_carry + jnp.concatenate([dka, dkb], axis=2)
        dv_carry = dv_carry + jnp.concatenate([dva, dvb], axis=2)
        # carries rotate with K/V every step incl. the last (lands home);
        # K/V skip the final dead hop
        if t < n - 1:
            k_cur = _rotate(k_cur, axis, n)
            v_cur = _rotate(v_cur, axis, n)
        dk_carry = _rotate(dk_carry, axis, n)
        dv_carry = _rotate(dv_carry, axis, n)
    dq = jnp.concatenate([dqa, dqb], axis=2)
    return (dq.astype(q.dtype), dk_carry.astype(k.dtype),
            dv_carry.astype(v.dtype))


_ring_zigzag.defvjp(_ring_zigzag_vjp_fwd, _ring_zigzag_vjp_bwd)


def _ring_zigzag_local(q, k, v, *, axis, scale, block_q=512, block_k=1024):
    return _ring_zigzag(q, k, v, axis, scale,
                        min(block_q, q.shape[2] // 2),
                        min(block_k, k.shape[2] // 2))


def zigzag_shard(x, mesh, *, seq_axis: int, axis: str = "seq"):
    """Apply the zig-zag stripe permutation along ``seq_axis`` ONCE.

    ``seq_axis`` is intentionally required: the permutation silently
    "succeeds" on any axis whose length divides into 2n stripes, so a
    defaulted axis on a [B, T, D] vs [B, H, T, D] layout mix-up would
    corrupt data instead of erroring (2 for q/k/v, 1 for encoder inputs).

    The at-scale usage of the balanced causal ring: permute inputs (and
    anything position-aligned with them — labels, masks, position ids) one
    time up front, run N train steps / N layers on permuted data via
    ``ring_attention_zigzag(pre_permuted=True)`` or
    ``sequence_parallel_encoder(impl="zigzag")``, and ``zigzag_unshard``
    only what leaves the permuted domain. One O(T) gather per RUN instead
    of three gathers + one scatter per CALL. Position-wise computations
    (LN, projections, MLP, per-token losses) are order-agnostic, so entire
    transformer stacks run inside the permuted domain unchanged."""
    n = mesh.shape[axis]
    perm, _ = zigzag_permutation(x.shape[seq_axis], n)
    return jnp.take(x, perm, axis=seq_axis)


def zigzag_unshard(x, mesh, *, seq_axis: int, axis: str = "seq"):
    """Inverse of zigzag_shard (restore natural sequence order)."""
    n = mesh.shape[axis]
    _, inv = zigzag_permutation(x.shape[seq_axis], n)
    return jnp.take(x, inv, axis=seq_axis)


def _zigzag_guard(T, n, head_dim):
    if T % (2 * n):
        raise ValueError(f"zigzag needs T ({T}) divisible by 2*{n} stripes")
    if not _flash_core_ok(head_dim, T // (2 * n)):
        raise ValueError("zigzag ring runs on the flash core: needs "
                         "head_dim % 128 == 0 and stripe length % 8 == 0")


def ring_attention_zigzag(q, k, v, mesh, *, axis: str = "seq",
                          scale: float | None = None,
                          pre_permuted: bool = False):
    """Load-balanced CAUSAL ring attention (zig-zag stripe sharding).

    By default takes/returns NORMAL sequence order ([B, H, T, D]) and
    applies the stripe permutation internally (one gather per operand per
    call). At scale, permute once with ``zigzag_shard`` and pass
    ``pre_permuted=True``: inputs are then consumed — and the output
    returned — in zig-zag order with no per-call permutation at all.
    Requires T % (2 * mesh axis size) == 0 and the flash kernel's alignment
    (head_dim % 128 == 0)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    n = mesh.shape[axis]
    T = q.shape[2]
    _zigzag_guard(T, n, q.shape[-1])
    fn = shard_map(
        functools.partial(_ring_zigzag_local, axis=axis, scale=scale),
        mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
        check_vma=False,  # pallas interpret-mode VMA limitation (see above)
    )
    if pre_permuted:
        return fn(q, k, v)
    perm, inv = zigzag_permutation(T, n)
    out = fn(jnp.take(q, perm, axis=2), jnp.take(k, perm, axis=2),
             jnp.take(v, perm, axis=2))
    return jnp.take(out, inv, axis=2)
