"""Data-parallel training — the ParallelWrapper replacement.

Reference analog: org.deeplearning4j.parallelism.ParallelWrapper — N trainer
threads with per-device model replicas, prefetch queues, and either parameter
averaging or Strom-style threshold-encoded gradient sharing
(EncodedGradientsAccumulator, SURVEY.md §3.3). All of that machinery exists
because the reference must coordinate asynchronous device replicas by hand.

TPU-native: the SAME jitted train step, with the batch sharded over the
mesh's "data" axis and params replicated. XLA SPMD inserts one fused
all-reduce (psum over ICI) for the gradients — semantically identical to
synchronous gradient sharing with zero host involvement, no threads, no
queues, no encoding. Multi-host (the Spark/Aeron analog) is the same code
under jax.distributed; DCN collectives replace the parameter server.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from deeplearning4j_tpu.parallel.mesh import DeviceMesh


class ParallelWrapper:
    """Shards a model's training over a DeviceMesh data axis.

    Usage (mirrors the reference's wrapper-around-model pattern):

        wrapper = ParallelWrapper(model, mesh)   # mesh defaults to all devices
        wrapper.fit(iterator, epochs=2)

    The wrapped model's params/opt state are placed replicated on the mesh;
    each fit_batch shards the host batch over "data" and runs the model's own
    jitted train step under the mesh context — XLA partitions it SPMD.
    """

    def __init__(self, model, mesh: Optional[DeviceMesh] = None,
                 prefetch_buffer: int = 2):
        self.model = model
        self.mesh = mesh or DeviceMesh()
        self.prefetch_buffer = prefetch_buffer
        self._placed = False

    def _place(self):
        m = self.model
        m.params = self.mesh.replicate(m.params)
        m.state = self.mesh.replicate(m.state)
        m.opt_state = self.mesh.replicate(m.opt_state)
        self._placed = True

    def fit_batch(self, ds) -> float:
        if not self._placed:
            self._place()
        from deeplearning4j_tpu.nn.multilayer import _unpack

        x, y, mask, label_mask = _unpack(ds)
        n = np.asarray(x).shape[0] if not isinstance(x, (list, tuple, dict)) else None
        dp = self.mesh.shape["data"]
        if n is not None and n % dp:
            raise ValueError(f"batch size {n} not divisible by data-parallel degree {dp}")
        parts = (x, y) if mask is None else (x, y, mask)
        if label_mask is not None:
            parts = (x, y, mask, label_mask)
        batch = self.mesh.shard_batch(parts)
        with self.mesh.mesh:
            loss = self.model.fit_batch(batch)
        if self._lockstep():
            # multi-process CPU (Gloo): fit_batch's float(loss) does NOT
            # wait for the gradient/param psum (loss is computed pre-
            # update), so the all-reduce is still in flight when the host
            # moves on. Any later host-initiated collective (orbax save
            # barriers, broadcast_one_to_all) then interleaves with it on
            # the same Gloo pair and aborts the transport. Blocking on the
            # updated params serializes the rounds; TPU/GPU transports
            # don't need it and skip this branch.
            jax.block_until_ready((self.model.params, self.model.opt_state,
                                   self.model.state))
        return loss

    def _lockstep(self) -> bool:
        if not hasattr(self, "_lockstep_cached"):
            self._lockstep_cached = (jax.process_count() > 1
                                     and jax.default_backend() == "cpu")
        return self._lockstep_cached

    def fit(self, data, epochs: int = 1):
        from deeplearning4j_tpu.datasets.iterators import AsyncPrefetchIterator
        from deeplearning4j_tpu.optimize.async_dispatch import drain_scores

        if self.prefetch_buffer and hasattr(data, "reset"):
            # single-process: the prefetch thread shards each batch onto the
            # mesh, overlapping H2D with the previous step's compute
            # (fit_batch's shard_batch then passes it through unchanged).
            # Multi-process stages host-side: make_array_from_callback from
            # a second thread would interleave on the Gloo transport.
            sharder = (self.mesh.shard_batch
                       if jax.process_count() == 1 else None)
            data = AsyncPrefetchIterator(data, queue_size=self.prefetch_buffer,
                                         device_put=False, sharder=sharder)
        for _ in range(epochs):
            try:
                for ds in data:
                    self.fit_batch(ds)
            except BaseException:
                drain_scores(self.model, suppress=True)
                raise
            drain_scores(self.model)
            if hasattr(data, "reset"):
                data.reset()
            self.model.epoch_count += 1
        return self.model

    def average_params(self):
        """No-op kept for API parity: synchronous SPMD keeps replicas identical
        by construction (the reference needed explicit averaging because its
        replicas drifted between averaging rounds)."""
        return self.model.params
