"""Tensor (model) parallelism — megatron-style parameter sharding.

Reference analog: NONE — the reference has no tensor parallelism (SURVEY.md
§2.4: "Model / tensor parallel: absent"). This is net-new capability designed
TPU-first: instead of hand-written split layers (Megatron's ColumnParallel /
RowParallelLinear), we annotate each parameter with a PartitionSpec over the
mesh's "model" axis and let XLA GSPMD partition the (unchanged) jitted train
step, inserting the all-reduces/all-gathers over ICI itself.

The rule table plays the role Megatron's layer classes play:
    Dense / Output W [in, out]        -> P(None, "model")   (column parallel)
    Dense b [out]                     -> P("model")
    Conv kernel [kh, kw, cin, cout]   -> P(None, None, None, "model")
    Embedding W [vocab, dim]          -> P(None, "model")
    Attention qkv [in, h*d]           -> P(None, "model")    (head split)
    Attention out-proj [h*d, out]     -> P("model", None)    (row parallel)
    LSTM/RNN kernels [in, 4H]         -> P(None, "model")    (gate split)
    Norm scales / scalars             -> replicated

Consecutive column-parallel layers force a resharding between them; GSPMD
inserts the minimal collective, which on TPU rides ICI. Correctness is
independent of the rules (they are layout hints); tests check numerical
equality with the unsharded model on a virtual mesh.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DeviceMesh

# (layer-class-name substring, param-name) -> spec builder taking ndim.
# Checked in order; first match wins. None entries mean replicate.


def _col(ndim):  # shard last dim over "model"
    return P(*([None] * (ndim - 1) + ["model"]))


def _row(ndim):  # shard first dim over "model"
    return P(*(["model"] + [None] * (ndim - 1)))


# Structure-based megatron role tables (r4, VERDICT r3 #5): keyed on the
# LAYER CLASS and its OWN parameter roles, not name-string heuristics. The
# canonical megatron transformer block: QKV projections and the MLP
# up-projection are column-parallel (their biases split with the columns);
# the attention output projection and MLP down-projection are row-parallel
# (their biases replicate — they add AFTER the row all-reduce); norms
# replicate. Correctness never depends on these (they are GSPMD layout
# hints); parity vs single-device is asserted on the BERT zoo model in
# tests/test_parallel.py.
_MEGATRON_ROLES = {
    "TransformerEncoderLayer": {
        "Wq": "col", "Wk": "col", "Wv": "col", "W1": "col",
        "bq": "col", "bk": "col", "bv": "col", "b1": "col",
        "Wo": "row", "W2": "row", "bo": "rep", "b2": "rep",
        "ln1_g": "rep", "ln1_b": "rep", "ln2_g": "rep", "ln2_b": "rep",
    },
    "SelfAttentionLayer": {
        "Wq": "col", "Wk": "col", "Wv": "col", "Wo": "row",
    },
    "LearnedSelfAttentionLayer": {
        "Wq": "col", "Wk": "col", "Wv": "col", "Wo": "row", "Q": "rep",
    },
    # r5 (VERDICT r4 #4): the conv flagship. Conv kernels are
    # [kh, kw, cin, cout] — output-channel column split (the megatron
    # column rule lifted to conv); the bias splits with the columns. BN
    # scale/shift replicate (its stats are per-channel, GSPMD broadcasts
    # the replicated vector against the channel-sharded activation).
    # These are layout HINTS: parity vs the unsharded model is asserted
    # on a conv+BN net in tests and on tiny ResNet-50 in the dryrun.
    "ConvolutionLayer": {"W": "col", "b": "col"},
    "SeparableConvolution2DLayer": {"dW": "rep", "pW": "col", "b": "col"},
    "Deconvolution2DLayer": {"W": "col", "b": "col"},
    "BatchNormalizationLayer": {"gamma": "rep", "beta": "rep"},
}


def default_rules(layer, name: str, ndim: int) -> P:
    """Megatron-style default spec for one parameter: the structure-based
    role table for layers whose block structure is known, name heuristics
    for the rest."""
    cls = type(layer).__name__
    if ndim == 0:
        return P()
    roles = _MEGATRON_ROLES.get(cls)
    if roles is not None and name in roles:
        kind = roles[name]
        if kind == "col":
            return _col(ndim)
        if kind == "row":
            return _row(ndim)
        return P()
    if "Norm" in cls:
        return P()
    if name in ("Wo", "out_W", "proj_W"):  # attention output projection
        return _row(ndim)
    if name.startswith(("W", "kernel")) or name in ("gamma_w",):
        return _col(ndim)
    if name in ("b", "bias", "gb"):
        return _col(ndim)  # bias lives with column split
    if name.startswith("R"):  # recurrent kernels [H, 4H] — gate split
        return _col(ndim)
    return P()


def _divisible(shape, spec, mesh: DeviceMesh) -> bool:
    for dim, ax in zip(shape, spec):
        if ax is not None and (dim % mesh.shape[ax] != 0):
            return False
    return True


class TensorParallel:
    """Places a model's parameters model-parallel on a mesh and runs its own
    jitted train step under the mesh — GSPMD partitions everything else.

    Usage::

        mesh = DeviceMesh(data=2, model=4)
        tp = TensorParallel(model, mesh)
        tp.fit_batch((x, y))

    ``rules(layer, param_name, ndim) -> PartitionSpec`` can override the
    megatron-style defaults. Params whose dims don't divide the mesh axis are
    silently replicated (same degrade-gracefully behavior as the reference's
    platform-helper fallbacks).
    """

    def __init__(self, model, mesh: Optional[DeviceMesh] = None,
                 rules: Optional[Callable] = None):
        self.model = model
        self.mesh = mesh or DeviceMesh(model=jax.device_count())
        self.rules = rules or default_rules
        self._placed = False

    # ------------------------------------------------------------- placement
    def _named_params(self):
        """(layer, param_tree) pairs mirroring model.params — the MLN
        layer list, or the CG vertex dict (r5: the conv flagship is a
        ComputationGraph). Returns (pairs, rebuild) where rebuild maps the
        spec'd trees back into model.params' container shape."""
        m = self.model
        if hasattr(m, "layers"):                    # MultiLayerNetwork
            return list(zip(m.layers, m.params)), list
        from deeplearning4j_tpu.nn.conf.graph import LayerVertex

        names = [n for n in m.params]               # ComputationGraph
        pairs = []
        for n in names:
            v = m.conf.vertices[n]
            layer = v.layer if isinstance(v, LayerVertex) else v
            pairs.append((layer, m.params[n]))
        return pairs, lambda specs: dict(zip(names, specs))

    def param_specs(self):
        """Pytrees of PartitionSpec, mirroring model.params (list for MLN,
        name-keyed dict for ComputationGraph)."""
        pairs, rebuild = self._named_params()
        specs = []
        for layer, p in pairs:
            def spec_for(path, leaf, _layer=layer):
                name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
                s = self.rules(_layer, name, np.ndim(leaf))
                if not _divisible(np.shape(leaf), s, self.mesh):
                    return P()
                return s

            specs.append(jax.tree_util.tree_map_with_path(spec_for, p))
        return rebuild(specs)

    def place(self):
        specs = self.param_specs()
        mesh = self.mesh.mesh
        self.model.params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            self.model.params, specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        # state + optimizer state: replicate initially; after the first step
        # they adopt GSPMD's propagated shardings (we reassign from outputs).
        self.model.state = self.mesh.replicate(self.model.state)
        self.model.opt_state = self.mesh.replicate(self.model.opt_state)
        self._placed = True
        return self

    # ---------------------------------------------------------------- train
    def fit_batch(self, ds) -> float:
        if not self._placed:
            self.place()
        from deeplearning4j_tpu.nn.multilayer import _unpack

        x, y, mask, label_mask = _unpack(ds)
        dp = self.mesh.shape["data"]
        n = np.asarray(x).shape[0]
        if n % max(dp, 1):
            raise ValueError(f"batch {n} not divisible by data axis {dp}")
        parts = (x, y) if mask is None else (x, y, mask)
        if label_mask is not None:
            parts = (x, y, mask, label_mask)
        batch = self.mesh.shard_batch(parts)
        with self.mesh.mesh:
            return self.model.fit_batch(batch)

    def fit(self, data, epochs: int = 1):
        for _ in range(epochs):
            for ds in data:
                self.fit_batch(ds)
            if hasattr(data, "reset"):
                data.reset()
            self.model.epoch_count += 1
        return self.model

    def output(self, x):
        if not self._placed:
            self.place()
        with self.mesh.mesh:
            return self.model.output(jax.device_put(
                np.asarray(x), self.mesh.batch_sharding(np.ndim(x))))
