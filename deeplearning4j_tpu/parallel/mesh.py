"""Device-mesh abstraction.

Reference analog: none directly — the reference pins devices per trainer
thread via AffinityManager (org.nd4j.linalg.api.concurrency.AffinityManager)
and routes parameter-server traffic via MeshOrganizer
(org.nd4j.parameterserver.distributed.v2.util.MeshOrganizer). TPU-first, the
mesh IS the programming model: a jax.sharding.Mesh over axes
(data, model, pipe, seq) with NamedSharding partition specs; XLA emits the
ICI/DCN collectives.

Axis conventions used framework-wide:
    "data"  - batch / data parallel (psum of grads)
    "model" - tensor parallel (megatron-style param splits)
    "pipe"  - pipeline stages
    "seq"   - sequence/context parallel (ring attention)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class DeviceMesh:
    """Wraps jax.sharding.Mesh with framework axis conventions + helpers."""

    AXES = ("data", "model", "pipe", "seq")

    def __init__(self, data: int = 0, model: int = 1, pipe: int = 1, seq: int = 1,
                 devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        if data <= 0:
            rest = model * pipe * seq
            if n % rest:
                raise ValueError(f"{n} devices not divisible by model*pipe*seq={rest}")
            data = n // rest
        shape = (data, model, pipe, seq)
        if int(np.prod(shape)) != n:
            raise ValueError(f"mesh shape {shape} != {n} devices")
        arr = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(arr, self.AXES)
        self.shape = dict(zip(self.AXES, shape))

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.shape.values())))

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding for a PartitionSpec given as axis names (None = replicated)."""
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, ndim: int = 1) -> NamedSharding:
        """Shard dim 0 over 'data' (and 'seq' dim 1 if seq > 1 and ndim >= 2)."""
        spec: list = ["data"] + [None] * (ndim - 1)
        return NamedSharding(self.mesh, P(*spec))

    def shard_batch(self, tree):
        """Device-put a host batch with dim-0 sharded over the data axis.

        Multi-process: built with make_array_from_callback (each process
        feeds its addressable shards from the full host batch it already
        holds). device_put onto a cross-process sharding would run
        multihost_utils.assert_equal — a broadcast_one_to_all collective
        per batch, which on the Gloo CPU transport races any still-in-
        flight train-step collective and aborts the pair (gloo EnforceNotMet
        "op.preamble.length <= op.nbytes")."""

        def put(x):
            sh = self.batch_sharding(np.ndim(x))
            if isinstance(x, jax.Array) and x.sharding == sh:
                # already laid out (an AsyncPrefetchIterator staged it with
                # this mesh's sharder): re-putting would serialize the H2D
                # transfer the prefetch thread just overlapped
                return x
            if jax.process_count() > 1:
                a = np.asarray(x)
                return jax.make_array_from_callback(a.shape, sh,
                                                    lambda idx: a[idx])
            return jax.device_put(x, sh)

        return jax.tree_util.tree_map(put, tree)

    def replicate(self, tree):
        return jax.device_put(tree, self.replicated())

    def __enter__(self):
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)


def multi_slice_mesh(n_slices: int, axes: Sequence[str] = ("data",),
                     devices: Optional[Sequence] = None) -> Mesh:
    """Mesh with a leading "dcn" axis grouping devices by slice.

    Reference analog: the tier split in the reference's distributed stack —
    fast intra-node exchange vs Aeron UDP across nodes (SURVEY.md §2.4).
    TPU-native: collectives over the trailing axes ride ICI within a slice;
    collectives over "dcn" cross the data-center network between slices.
    On real multi-slice pods devices are grouped by their slice_index; on
    virtual/CPU device sets they are split evenly in order, which is how the
    driver's dryrun and the test harness simulate two slices on one host.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % n_slices:
        raise ValueError(f"{n} devices not divisible into {n_slices} slices")
    per = n // n_slices
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        devices.sort(key=lambda d: (d.slice_index, d.id))
        # every "dcn" row must stay within ONE physical slice — mixing
        # slices in a row would route the trailing (ICI) axis collectives
        # over DCN, the exact slow path this mesh exists to avoid
        for r in range(n_slices):
            row = devices[r * per:(r + 1) * per]
            if len({d.slice_index for d in row}) != 1:
                n_real = len({d.slice_index for d in devices})
                raise ValueError(
                    f"n_slices={n_slices} does not match the pod's "
                    f"{n_real} physical slices (a dcn row would span "
                    f"multiple slices)")
    shape = (n_slices, per)
    arr = np.asarray(devices).reshape(shape)
    if len(axes) != 1:
        # split the per-slice extent over the trailing axes evenly by
        # caller-specified factorization: axes like ("data", "model") with
        # sizes inferred is ambiguous — require per-slice extent = product
        raise ValueError("multi_slice_mesh currently takes one ICI axis; "
                         "build custom shapes with jax.sharding.Mesh")
    return Mesh(arr, ("dcn",) + tuple(axes))
