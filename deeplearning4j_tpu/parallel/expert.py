"""Expert parallelism — switch-routed mixture of experts.

Reference analog: NONE — SURVEY.md §2.4 lists expert parallel as absent from
the reference. Net-new, TPU-first: top-1 (switch) routing implemented as the
dense dispatch/combine einsums of the Mesh-TensorFlow/GShard lineage — the
dispatch tensor turns token routing into two batched matmuls, and with the
expert-stacked weights sharded over the mesh's "model" axis
(P("model", None, None)) GSPMD partitions expert compute across devices and
inserts the all-to-alls itself; no hand-written routing transport.

Capacity semantics: each expert processes at most
ceil(tokens/experts * capacity_factor); overflow tokens pass through the
residual (standard switch-transformer behavior).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


def init_moe_params(key, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / np.sqrt(d_model)
    scale_out = 1.0 / np.sqrt(d_hidden)
    return {
        "router_W": jax.random.normal(k1, (d_model, n_experts), dtype) * scale_in,
        "W1": jax.random.normal(k2, (n_experts, d_model, d_hidden), dtype) * scale_in,
        "b1": jnp.zeros((n_experts, 1, d_hidden), dtype),
        "W2": jax.random.normal(k3, (n_experts, d_hidden, d_model), dtype) * scale_out,
        "b2": jnp.zeros((n_experts, 1, d_model), dtype),
    }


def moe_param_specs():
    """PartitionSpecs sharding experts over the "model" mesh axis."""
    return {"router_W": P(), "W1": P("model", None, None), "b1": P("model", None, None),
            "W2": P("model", None, None), "b2": P("model", None, None)}


def place_moe_params(params, mesh):
    specs = moe_param_specs()
    return {k: jax.device_put(v, NamedSharding(mesh.mesh, specs[k]))
            for k, v in params.items()}


def switch_moe(params, x, *, capacity_factor: float = 1.25,
               activation=jax.nn.relu, overflow_passes: int = 2):
    """Top-1 switch MoE feed-forward. x [..., D] -> (y [..., D], aux_loss).

    aux_loss is the switch-transformer load-balancing term
    (n_experts * Σ_e fraction_e * mean_gate_e).

    ``overflow_passes``: tokens past their first-choice expert's capacity
    fall back to their next-best expert with spare room (the Switch
    Transformer "no token left behind" pass; GShard's top-k fallback).
    Under an imbalanced router — exactly the early-training state the aux
    loss exists to fix — pure top-1 dropping starves a large token
    fraction of BOTH output and expert gradient, which stalls training;
    the fallback keeps those tokens learning while the aux loss
    rebalances. 1 = strict top-1 dropping. Each token is still processed
    by exactly one expert either way.
    """
    orig_shape = x.shape
    D = orig_shape[-1]
    xt = x.reshape(-1, D)
    N = xt.shape[0]
    E = params["router_W"].shape[1]
    C = max(1, int(np.ceil(N / E * capacity_factor)))

    logits = xt @ params["router_W"]                     # [N, E]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    order = jnp.argsort(-gates, axis=-1)                 # ranked choices
    onehot = jax.nn.one_hot(order[:, 0], E, dtype=jnp.float32)  # [N, E]

    # greedy multi-pass placement: pass p lets every still-unplaced token
    # try its rank-p expert, consuming the capacity earlier passes left
    pos_oh = jnp.zeros((N, E, C), jnp.float32)           # [N,E,C] dispatch
    gate_val = jnp.zeros((N,), jnp.float32)
    placed = jnp.zeros((N,), jnp.float32)
    used = jnp.zeros((E,), jnp.float32)                  # capacity consumed
    for p in range(max(1, min(overflow_passes, E))):
        oh = (jax.nn.one_hot(order[:, p], E, dtype=jnp.float32)
              * (1.0 - placed)[:, None])                 # [N, E]
        pos = ((jnp.cumsum(oh, axis=0) - 1.0) + used[None, :]) * oh
        keep = oh * (pos < C)
        pos_oh = pos_oh + jax.nn.one_hot(
            pos.astype(jnp.int32), C) * keep[..., None]
        placed_now = keep.sum(-1)                        # [N] 0/1
        gate_val = gate_val + (gates * keep).sum(-1)
        used = used + keep.sum(0)
        placed = placed + placed_now

    # dispatch -> expert compute (batched over E; shard E over "model") -> combine
    xin = jnp.einsum("nec,nd->ecd", pos_oh, xt.astype(jnp.float32))
    h = activation(jnp.einsum("ecd,edh->ech", xin, params["W1"]) + params["b1"])
    out = jnp.einsum("ech,ehd->ecd", h, params["W2"]) + params["b2"]
    yt = jnp.einsum("nec,ecd->nd", pos_oh, out) * gate_val[:, None]
    # tokens no pass could place contribute zero -> caller's residual
    # connection passes them through

    # load-balancing auxiliary loss (first-choice routing fractions, the
    # standard switch term — fallback placement doesn't change the target)
    fraction = onehot.mean(0)                             # tokens per expert
    mean_gate = gates.mean(0)
    aux = E * jnp.sum(fraction * mean_gate)
    return yt.astype(x.dtype).reshape(orig_shape), aux


def switch_moe_reference(params, x, *, capacity_factor: float = 1.25,
                         activation=jax.nn.relu, overflow_passes: int = 2):
    """Loop-over-experts reference (for parity tests): identical math —
    including the greedy multi-pass overflow placement — no dispatch
    tensors."""
    orig_shape = x.shape
    D = orig_shape[-1]
    xt = np.asarray(x, np.float32).reshape(-1, D)
    N = xt.shape[0]
    rw = np.asarray(params["router_W"], np.float32)
    E = rw.shape[1]
    C = max(1, int(np.ceil(N / E * capacity_factor)))
    logits = xt @ rw
    g = np.exp(logits - logits.max(-1, keepdims=True))
    g = g / g.sum(-1, keepdims=True)
    order = np.argsort(-g, axis=-1)
    y = np.zeros_like(xt)
    counts = np.zeros(E, int)
    placed = np.zeros(N, bool)
    for p in range(max(1, min(overflow_passes, E))):
        for n in range(N):
            if placed[n]:
                continue
            e = order[n, p]
            if counts[e] >= C:
                continue
            counts[e] += 1
            placed[n] = True
            pre = xt[n] @ np.asarray(params["W1"][e]) + np.asarray(params["b1"][e])[0]
            h = np.asarray(activation(jnp.asarray(pre)))
            out = h @ np.asarray(params["W2"][e]) + np.asarray(params["b2"][e])[0]
            y[n] = out * g[n, e]
    return y.reshape(orig_shape)
