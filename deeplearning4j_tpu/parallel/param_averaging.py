"""Parameter averaging with local steps — the actual semantics of the
reference's ParameterAveragingTrainingMaster (local SGD).

Reference analog: org.deeplearning4j.spark.impl.paramavg.
ParameterAveragingTrainingMaster — each Spark worker fits its replica for
``averagingFrequency`` iterations on its own shard, then parameters are
averaged cluster-wide (RDD reduce) and redistributed. Between averages the
replicas genuinely DIVERGE; that divergence (and the reduced communication
frequency) is the point of the algorithm — it is NOT equivalent to
synchronous data-parallel SGD.

TPU-native: replicas are a leading device axis on the param/optimizer trees,
sharded over the mesh's data axis inside one SPMD program. Local steps touch
no collective at all; every K-th step ends with one pmean of the params
(and a pmean of the optimizer state, matching the reference's
``averageUpdaterState=true`` default). The whole K-step round is a single
``lax.scan`` inside one jitted shard_map call, so the per-step cost is the
same fused train step the single-device path runs.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel._compat import shard_map


class ParameterAveragingTrainer:
    """Local-SGD trainer: K local steps per replica, then average.

    loss_fn(params, x, y) -> scalar loss on the LOCAL shard. ``updater`` is
    any framework updater (stateful ones are fine: the state lives
    per-replica and is averaged with the params, the reference's
    averageUpdaterState behavior).
    """

    def __init__(self, loss_fn: Callable, updater, mesh, *,
                 axis: str = "data", averaging_frequency: int = 1,
                 average_updater_state: bool = True):
        from deeplearning4j_tpu.optimize.updaters import get_updater

        self.loss_fn = loss_fn
        self.updater = get_updater(updater)
        self.mesh = mesh
        self.axis = axis
        if int(averaging_frequency) < 1:
            raise ValueError(f"averaging_frequency must be >= 1, got "
                             f"{averaging_frequency}")
        self.freq = int(averaging_frequency)
        self.average_updater_state = average_updater_state
        self._round = None

    def init(self, params):
        n = self.mesh.shape[self.axis]
        rep = jax.tree_util.tree_map(
            lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), params)
        opt = self.updater.init_state(params)
        opt_rep = jax.tree_util.tree_map(
            lambda s: jnp.broadcast_to(s[None], (n,) + s.shape), opt)
        self._round = None  # re-init invalidates the cached compiled round
        return {"params": rep, "opt": opt_rep, "step": jnp.asarray(0, jnp.int32)}

    def _build(self, carry):
        loss_fn, updater = self.loss_fn, self.updater
        axis = self.axis
        avg_opt = self.average_updater_state

        def round_fn(carry, xs, ys):
            """One averaging round: K purely-local steps, then ONE pmean.
            xs/ys: [K, local_batch, ...] — K microbatches for this replica."""
            params = jax.tree_util.tree_map(lambda t: t[0], carry["params"])
            opt = jax.tree_util.tree_map(lambda t: t[0], carry["opt"])

            def local_step(state, batch):
                p, o, i = state
                x, y = batch
                loss, g = jax.value_and_grad(loss_fn)(p, x, y)
                upd, o2 = updater.update(g, o, p, i)
                p2 = jax.tree_util.tree_map(lambda a, d: a - d, p, upd)
                return (p2, o2, i + 1), loss

            (params, opt, step), losses = lax.scan(
                local_step, (params, opt, carry["step"]), (xs, ys))
            # the round's single collective: average the diverged replicas
            params = jax.tree_util.tree_map(lambda t: lax.pmean(t, axis), params)
            if avg_opt:
                opt = jax.tree_util.tree_map(lambda t: lax.pmean(t, axis), opt)
            return ({"params": jax.tree_util.tree_map(lambda t: t[None], params),
                     "opt": jax.tree_util.tree_map(lambda t: t[None], opt),
                     "step": step},
                    lax.pmean(losses.mean(), axis))

        spec_rep = {
            "params": jax.tree_util.tree_map(lambda _: P(axis),
                                             carry["params"]),
            "opt": jax.tree_util.tree_map(lambda _: P(axis), carry["opt"]),
            "step": P(),
        }
        fn = shard_map(
            round_fn, mesh=self.mesh,
            in_specs=(spec_rep, P(None, axis), P(None, axis)),
            out_specs=(spec_rep, P()),
        )
        return jax.jit(fn)

    def fit_round(self, carry, x, y):
        """One full averaging round over a global batch.

        x/y: [K * global_batch, ...] — split into K sequential microbatches;
        each replica sees K local shards, steps K times locally, then the
        single parameter average runs. Returns (carry, mean loss)."""
        if self._round is None:
            self._round = self._build(carry)
        x, y = jnp.asarray(x), jnp.asarray(y)
        K = self.freq
        if x.shape[0] % K:
            raise ValueError(f"batch {x.shape[0]} not divisible into "
                             f"{K} local steps")
        dp = self.mesh.shape[self.axis]
        if (x.shape[0] // K) % dp:
            raise ValueError(f"per-step batch {x.shape[0] // K} not "
                             f"divisible by data-parallel degree {dp}")
        xs = x.reshape((K, x.shape[0] // K) + x.shape[1:])
        ys = y.reshape((K, y.shape[0] // K) + y.shape[1:])
        return self._round(carry, xs, ys)

    def params(self, carry):
        """The (replica-identical) averaged params as a plain tree."""
        return jax.tree_util.tree_map(lambda t: t[0], carry["params"])
