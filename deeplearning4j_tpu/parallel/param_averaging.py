"""Parameter averaging with local steps — the actual semantics of the
reference's ParameterAveragingTrainingMaster (local SGD).

Reference analog: org.deeplearning4j.spark.impl.paramavg.
ParameterAveragingTrainingMaster — each Spark worker fits its replica for
``averagingFrequency`` iterations on its own shard, then parameters are
averaged cluster-wide (RDD reduce) and redistributed. Between averages the
replicas genuinely DIVERGE; that divergence (and the reduced communication
frequency) is the point of the algorithm — it is NOT equivalent to
synchronous data-parallel SGD.

TPU-native: replicas are a leading device axis on the param/optimizer trees,
sharded over the mesh's data axis inside one SPMD program. Local steps touch
no collective at all; every K-th step ends with one pmean of the params
(and a pmean of the optimizer state, matching the reference's
``averageUpdaterState=true`` default). The whole K-step round is a single
``lax.scan`` inside one jitted shard_map call, so the per-step cost is the
same fused train step the single-device path runs.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.parallel._compat import shard_map


class ParameterAveragingTrainer:
    """Local-SGD trainer: K local steps per replica, then average.

    loss_fn(params, x, y) -> scalar loss on the LOCAL shard. ``updater`` is
    any framework updater (stateful ones are fine: the state lives
    per-replica and is averaged with the params, the reference's
    averageUpdaterState behavior).

    ``stateful=True`` (r4) switches the functional contract to
    loss_fn(params, state, rng, x, y) -> (loss, new_state) — the
    MultiLayerNetwork/ComputationGraph ``as_loss_fn`` surface — so models
    with BatchNorm running stats and dropout train on this path: network
    state is carried per-replica across the K local steps, float state
    leaves (running stats) are AVERAGED at sync like the reference master
    averages them with the params, and each local step draws a distinct
    per-replica dropout key (deterministically folded from the round key,
    the step counter, and the replica index, so the round stays one
    replicated SPMD program).
    """

    def __init__(self, loss_fn: Callable, updater, mesh, *,
                 axis: str = "data", averaging_frequency: int = 1,
                 average_updater_state: bool = True, stateful: bool = False,
                 max_grad_norm: float = 0.0, skip_average=None):
        from deeplearning4j_tpu.optimize.updaters import get_updater

        self.loss_fn = loss_fn
        self.updater = get_updater(updater)
        self.mesh = mesh
        self.axis = axis
        # global-norm gradient clipping inside each LOCAL step, mirroring
        # the fit path's conf.max_grad_norm (r5); 0 = off
        self.max_grad_norm = float(max_grad_norm)
        # top-level param entries (MLN layer list / CG vertex dict, bools
        # aligned with the entries) whose averaging collective is SKIPPED
        # (r5): frozen entries never diverge, so averaging them wastes
        # collective bytes — and on the virtual-CPU test mesh XLA's
        # scan+psum rewrite costs 1 ulp even over identical replicas,
        # which would wiggle params that must stay bit-identical
        self.skip_average = skip_average
        if int(averaging_frequency) < 1:
            raise ValueError(f"averaging_frequency must be >= 1, got "
                             f"{averaging_frequency}")
        self.freq = int(averaging_frequency)
        self.average_updater_state = average_updater_state
        self.stateful = stateful
        self._round = None
        self._round_keys = None

    def init(self, params, state=None, rng=None):
        n = self.mesh.shape[self.axis]

        def rep(tree):
            return jax.tree_util.tree_map(
                lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), tree)

        opt = self.updater.init_state(params)
        self._round = None  # re-init invalidates the cached compiled round
        self._round_keys = None
        carry = {"params": rep(params), "opt": rep(opt),
                 "step": jnp.asarray(0, jnp.int32)}
        if self.stateful:
            carry["state"] = rep(state if state is not None else {})
            key = rng if rng is not None else jax.random.key(0)
            carry["rng"] = jax.random.key_data(key)
        return carry

    def _build(self, carry, batch_keys):
        from deeplearning4j_tpu.nn.multilayer import global_norm_clip

        loss_fn, updater = self.loss_fn, self.updater
        axis = self.axis
        avg_opt = self.average_updater_state
        stateful = self.stateful
        max_gn = self.max_grad_norm
        skip = self.skip_average
        has_mask = "mask" in batch_keys
        has_lmask = "label_mask" in batch_keys
        # elastic rounds (an "active" flag in the batch): the average is
        # renormalized over the surviving replicas — a lost worker's local
        # steps are excluded, and because every replica leaves the round
        # holding the (survivor-weighted) average, the lost one re-enters
        # the next round synced to the group: re-admission is the algebra,
        # not a special case
        has_active = "active" in batch_keys

        def round_fn(carry, batch):
            """One averaging round: K purely-local steps, then ONE pmean.
            batch: dict of [K, local_batch, ...] arrays — K microbatches
            for this replica ("x"/"y" always; "mask"/"label_mask" (r5)
            when the stream carries them; "active" is the per-replica
            survival flag and rides OUTSIDE the K-step scan)."""
            batch = dict(batch)
            active = batch.pop("active", None)
            params = jax.tree_util.tree_map(lambda t: t[0], carry["params"])
            opt = jax.tree_util.tree_map(lambda t: t[0], carry["opt"])
            if stateful:
                net_state0 = jax.tree_util.tree_map(lambda t: t[0],
                                                    carry["state"])
                round_key = jax.random.wrap_key_data(carry["rng"])

            def local_step(state, mb):
                x, y = mb["x"], mb["y"]
                if stateful:
                    p, o, s, i = state
                    k = jax.random.fold_in(
                        jax.random.fold_in(round_key, i),
                        lax.axis_index(axis))
                    extra, kw = (), {}
                    if has_mask or has_lmask:
                        extra = (mb.get("mask"), mb.get("label_mask"))
                    if "denom" in mb:
                        kw["denom"] = mb["denom"]
                    (loss, s2), g = jax.value_and_grad(
                        loss_fn, has_aux=True)(p, s, k, x, y, *extra, **kw)
                else:
                    p, o, i = state
                    loss, g = jax.value_and_grad(loss_fn)(p, x, y)
                if max_gn > 0:
                    g = global_norm_clip(g, max_gn)
                upd, o2 = updater.update(g, o, p, i)
                p2 = jax.tree_util.tree_map(lambda a, d: a - d, p, upd)
                if stateful:
                    return (p2, o2, s2, i + 1), loss
                return (p2, o2, i + 1), loss

            if stateful:
                (params, opt, net_state, step), losses = lax.scan(
                    local_step, (params, opt, net_state0, carry["step"]),
                    batch)
            else:
                (params, opt, step), losses = lax.scan(
                    local_step, (params, opt, carry["step"]), batch)
            # the round's single collective: average the diverged replicas
            # (frozen entries pass through untouched — see skip_average).
            # Elastic rounds weight the mean by each replica's active flag
            # and renormalize by the survivor count.
            if has_active:
                w = active[0]                           # this shard's 0/1
                survivors = lax.psum(w, axis)
                pleaf = lambda a: lax.psum(a * w, axis) / survivors
            else:
                pleaf = lambda a: lax.pmean(a, axis)

            def avg_state_leaf(t):
                # running stats (floats) are averaged at sync, like the
                # reference's parameter averaging of the full param
                # vector; integer leaves (counters) advance identically
                # per replica and pass through
                if jnp.issubdtype(t.dtype, jnp.floating):
                    return pleaf(t)
                return t

            def avg_tree(tree):
                pm = lambda t: jax.tree_util.tree_map(pleaf, t)
                if skip is None:
                    return pm(tree)
                if isinstance(tree, dict):
                    return {k: (tree[k] if skip.get(k) else pm(tree[k]))
                            for k in tree}
                return [t if s else pm(t) for t, s in zip(tree, skip)]

            params = avg_tree(params)
            if avg_opt:
                opt = avg_tree(opt)
            out = {"params": jax.tree_util.tree_map(lambda t: t[None], params),
                   "opt": jax.tree_util.tree_map(lambda t: t[None], opt),
                   "step": step}
            if stateful:
                net_state = jax.tree_util.tree_map(avg_state_leaf, net_state)
                out["state"] = jax.tree_util.tree_map(lambda t: t[None],
                                                      net_state)
                out["rng"] = jax.random.key_data(
                    jax.random.fold_in(round_key, step))
            return out, pleaf(losses.mean())

        spec_rep = {
            "params": jax.tree_util.tree_map(lambda _: P(axis),
                                             carry["params"]),
            "opt": jax.tree_util.tree_map(lambda _: P(axis), carry["opt"]),
            "step": P(),
        }
        if stateful:
            spec_rep["state"] = jax.tree_util.tree_map(lambda _: P(axis),
                                                       carry["state"])
            spec_rep["rng"] = P()
        batch_specs = {k: (P(None) if k == "denom"
                           else P(axis) if k == "active"
                           else P(None, axis))
                       for k in batch_keys}
        fn = shard_map(
            round_fn, mesh=self.mesh,
            in_specs=(spec_rep, batch_specs),
            out_specs=(spec_rep, P()),
            # the model loss may route through Pallas kernels (fused
            # LSTM/GRU, flash attention), whose calls don't carry vma
            # metadata — same decision as parallel/sequence.py
            check_vma=False,
        )
        return jax.jit(fn)

    def fit_round(self, carry, x, y, mask=None, label_mask=None, lost=None):
        """One full averaging round over a global batch.

        x/y: [K * global_batch, ...] arrays — or dicts of them (r5: the
        ComputationGraph multi-input/-output shape; every leaf shares the
        batch axis) — split into K sequential microbatches; each replica
        sees K local shards, steps K times locally, then the single
        parameter average runs. ``mask``/``label_mask`` (r5): optional
        [K * global_batch, T] masks riding the same split — the stateful
        as_loss_fn surface normalizes each local step by its shard's
        valid count (single-input/-output only).

        ``lost``: replica indices whose contribution this round is DROPPED
        (crashed/straggling workers): the average renormalizes over the
        survivors, and every replica — including the lost ones — leaves
        the round holding that survivor average, so a recovered worker is
        re-admitted in sync next round. Returns (carry, loss)."""
        import numpy as np

        if (mask is not None or label_mask is not None) and not self.stateful:
            raise ValueError(
                "masked batches need stateful=True (the as_loss_fn surface "
                "that takes (mask, label_mask))")
        K = self.freq
        dp = self.mesh.shape[self.axis]
        denom = None
        if K == 1 and (mask is not None or label_mask is not None):
            # K=1 IS sync DP: each replica normalizes its shard's summed
            # loss by global_valid/dp so the post-step parameter mean
            # equals one global-batch step EXACTLY, padding distribution
            # notwithstanding. K>1 keeps local-valid normalization — each
            # worker's local step is its own fit step (the reference's
            # per-worker minibatch semantics). Computed from the incoming
            # host arrays BEFORE device placement (no device round-trip).
            nm = np.asarray(label_mask if label_mask is not None else mask)
            denom = jnp.asarray(
                np.maximum(nm.reshape(K, -1).sum(axis=1), 1.0) / dp,
                jnp.float32)
        batch = {"x": jax.tree_util.tree_map(jnp.asarray, x),
                 "y": jax.tree_util.tree_map(jnp.asarray, y)}
        if mask is not None:
            batch["mask"] = jnp.asarray(mask)
        if label_mask is not None:
            batch["label_mask"] = jnp.asarray(label_mask)
        n = jax.tree_util.tree_leaves(batch["x"])[0].shape[0]
        for leaf in jax.tree_util.tree_leaves((batch["x"], batch["y"])):
            if leaf.shape[0] != n:
                raise ValueError(
                    f"every x/y slot must share the batch axis: got "
                    f"{leaf.shape[0]} rows vs {n}")
        if n % K:
            raise ValueError(f"batch {n} not divisible into {K} local steps")
        if (n // K) % dp:
            raise ValueError(f"per-step batch {n // K} not "
                             f"divisible by data-parallel degree {dp}")
        batch = jax.tree_util.tree_map(
            lambda v: v.reshape((K, n // K) + v.shape[1:]), batch)
        if denom is not None:
            batch["denom"] = denom
        if lost:
            bad = [i for i in lost if not 0 <= int(i) < dp]
            if bad:
                raise ValueError(f"lost replica indices {bad} outside the "
                                 f"{dp}-replica data axis")
            if len(set(int(i) for i in lost)) >= dp:
                raise ValueError("cannot drop every replica from a round")
            act = np.ones(dp, np.float32)
            act[[int(i) for i in lost]] = 0.0
            batch["active"] = jnp.asarray(act)
        keys = frozenset(batch)
        if self._round is None or self._round_keys != keys:
            self._round = self._build(carry, keys)
            self._round_keys = keys
        mon = monitoring.localsgd_monitor()
        if mon is None:
            return self._round(carry, batch)
        # sync duration = wall time of the whole round (K local steps +
        # the pmean sync), blocked on the loss so the device work is in it
        with monitoring.span("localsgd.round", k=K, dp=dp):
            t0 = time.perf_counter()
            carry, loss = self._round(carry, batch)
            jax.block_until_ready(loss)
            mon.sync_seconds.observe(time.perf_counter() - t0)
        mon.rounds.inc()
        return carry, loss

    def params(self, carry):
        """The (replica-identical) averaged params as a plain tree."""
        return jax.tree_util.tree_map(lambda t: t[0], carry["params"])

    def state(self, carry):
        """The network state tree after the last sync (stateful mode):
        float leaves are replica-identical post-average; integer leaves are
        taken from replica 0 (identical by construction — every replica
        runs the same step count)."""
        if not self.stateful:
            raise ValueError("state() requires stateful=True")
        return jax.tree_util.tree_map(lambda t: t[0], carry["state"])
