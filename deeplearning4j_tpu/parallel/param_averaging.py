"""Parameter averaging with local steps — the actual semantics of the
reference's ParameterAveragingTrainingMaster (local SGD).

Reference analog: org.deeplearning4j.spark.impl.paramavg.
ParameterAveragingTrainingMaster — each Spark worker fits its replica for
``averagingFrequency`` iterations on its own shard, then parameters are
averaged cluster-wide (RDD reduce) and redistributed. Between averages the
replicas genuinely DIVERGE; that divergence (and the reduced communication
frequency) is the point of the algorithm — it is NOT equivalent to
synchronous data-parallel SGD.

TPU-native: replicas are a leading device axis on the param/optimizer trees,
sharded over the mesh's data axis inside one SPMD program. Local steps touch
no collective at all; every K-th step ends with one pmean of the params
(and a pmean of the optimizer state, matching the reference's
``averageUpdaterState=true`` default). The whole K-step round is a single
``lax.scan`` inside one jitted shard_map call, so the per-step cost is the
same fused train step the single-device path runs.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel._compat import shard_map


class ParameterAveragingTrainer:
    """Local-SGD trainer: K local steps per replica, then average.

    loss_fn(params, x, y) -> scalar loss on the LOCAL shard. ``updater`` is
    any framework updater (stateful ones are fine: the state lives
    per-replica and is averaged with the params, the reference's
    averageUpdaterState behavior).

    ``stateful=True`` (r4) switches the functional contract to
    loss_fn(params, state, rng, x, y) -> (loss, new_state) — the
    MultiLayerNetwork/ComputationGraph ``as_loss_fn`` surface — so models
    with BatchNorm running stats and dropout train on this path: network
    state is carried per-replica across the K local steps, float state
    leaves (running stats) are AVERAGED at sync like the reference master
    averages them with the params, and each local step draws a distinct
    per-replica dropout key (deterministically folded from the round key,
    the step counter, and the replica index, so the round stays one
    replicated SPMD program).
    """

    def __init__(self, loss_fn: Callable, updater, mesh, *,
                 axis: str = "data", averaging_frequency: int = 1,
                 average_updater_state: bool = True, stateful: bool = False):
        from deeplearning4j_tpu.optimize.updaters import get_updater

        self.loss_fn = loss_fn
        self.updater = get_updater(updater)
        self.mesh = mesh
        self.axis = axis
        if int(averaging_frequency) < 1:
            raise ValueError(f"averaging_frequency must be >= 1, got "
                             f"{averaging_frequency}")
        self.freq = int(averaging_frequency)
        self.average_updater_state = average_updater_state
        self.stateful = stateful
        self._round = None

    def init(self, params, state=None, rng=None):
        n = self.mesh.shape[self.axis]

        def rep(tree):
            return jax.tree_util.tree_map(
                lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), tree)

        opt = self.updater.init_state(params)
        self._round = None  # re-init invalidates the cached compiled round
        carry = {"params": rep(params), "opt": rep(opt),
                 "step": jnp.asarray(0, jnp.int32)}
        if self.stateful:
            carry["state"] = rep(state if state is not None else {})
            key = rng if rng is not None else jax.random.key(0)
            carry["rng"] = jax.random.key_data(key)
        return carry

    def _build(self, carry):
        loss_fn, updater = self.loss_fn, self.updater
        axis = self.axis
        avg_opt = self.average_updater_state
        stateful = self.stateful

        def avg_state_leaf(t):
            # running stats (floats) are averaged at sync, like the
            # reference's parameter averaging of the full param vector;
            # integer leaves (counters) advance identically per replica
            # and pass through
            if jnp.issubdtype(t.dtype, jnp.floating):
                return lax.pmean(t, axis)
            return t

        def round_fn(carry, xs, ys):
            """One averaging round: K purely-local steps, then ONE pmean.
            xs/ys: [K, local_batch, ...] — K microbatches for this replica."""
            params = jax.tree_util.tree_map(lambda t: t[0], carry["params"])
            opt = jax.tree_util.tree_map(lambda t: t[0], carry["opt"])
            if stateful:
                net_state0 = jax.tree_util.tree_map(lambda t: t[0],
                                                    carry["state"])
                round_key = jax.random.wrap_key_data(carry["rng"])

            def local_step(state, batch):
                x, y = batch
                if stateful:
                    p, o, s, i = state
                    k = jax.random.fold_in(
                        jax.random.fold_in(round_key, i),
                        lax.axis_index(axis))
                    (loss, s2), g = jax.value_and_grad(
                        loss_fn, has_aux=True)(p, s, k, x, y)
                else:
                    p, o, i = state
                    loss, g = jax.value_and_grad(loss_fn)(p, x, y)
                upd, o2 = updater.update(g, o, p, i)
                p2 = jax.tree_util.tree_map(lambda a, d: a - d, p, upd)
                if stateful:
                    return (p2, o2, s2, i + 1), loss
                return (p2, o2, i + 1), loss

            if stateful:
                (params, opt, net_state, step), losses = lax.scan(
                    local_step, (params, opt, net_state0, carry["step"]),
                    (xs, ys))
            else:
                (params, opt, step), losses = lax.scan(
                    local_step, (params, opt, carry["step"]), (xs, ys))
            # the round's single collective: average the diverged replicas
            params = jax.tree_util.tree_map(lambda t: lax.pmean(t, axis), params)
            if avg_opt:
                opt = jax.tree_util.tree_map(lambda t: lax.pmean(t, axis), opt)
            out = {"params": jax.tree_util.tree_map(lambda t: t[None], params),
                   "opt": jax.tree_util.tree_map(lambda t: t[None], opt),
                   "step": step}
            if stateful:
                net_state = jax.tree_util.tree_map(avg_state_leaf, net_state)
                out["state"] = jax.tree_util.tree_map(lambda t: t[None],
                                                      net_state)
                out["rng"] = jax.random.key_data(
                    jax.random.fold_in(round_key, step))
            return out, lax.pmean(losses.mean(), axis)

        spec_rep = {
            "params": jax.tree_util.tree_map(lambda _: P(axis),
                                             carry["params"]),
            "opt": jax.tree_util.tree_map(lambda _: P(axis), carry["opt"]),
            "step": P(),
        }
        if stateful:
            spec_rep["state"] = jax.tree_util.tree_map(lambda _: P(axis),
                                                       carry["state"])
            spec_rep["rng"] = P()
        fn = shard_map(
            round_fn, mesh=self.mesh,
            in_specs=(spec_rep, P(None, axis), P(None, axis)),
            out_specs=(spec_rep, P()),
        )
        return jax.jit(fn)

    def fit_round(self, carry, x, y):
        """One full averaging round over a global batch.

        x/y: [K * global_batch, ...] — split into K sequential microbatches;
        each replica sees K local shards, steps K times locally, then the
        single parameter average runs. Returns (carry, mean loss)."""
        if self._round is None:
            self._round = self._build(carry)
        x, y = jnp.asarray(x), jnp.asarray(y)
        K = self.freq
        if x.shape[0] % K:
            raise ValueError(f"batch {x.shape[0]} not divisible into "
                             f"{K} local steps")
        dp = self.mesh.shape[self.axis]
        if (x.shape[0] // K) % dp:
            raise ValueError(f"per-step batch {x.shape[0] // K} not "
                             f"divisible by data-parallel degree {dp}")
        xs = x.reshape((K, x.shape[0] // K) + x.shape[1:])
        ys = y.reshape((K, y.shape[0] // K) + y.shape[1:])
        return self._round(carry, xs, ys)

    def params(self, carry):
        """The (replica-identical) averaged params as a plain tree."""
        return jax.tree_util.tree_map(lambda t: t[0], carry["params"])

    def state(self, carry):
        """The network state tree after the last sync (stateful mode):
        float leaves are replica-identical post-average; integer leaves are
        taken from replica 0 (identical by construction — every replica
        runs the same step count)."""
        if not self.stateful:
            raise ValueError("state() requires stateful=True")
        return jax.tree_util.tree_map(lambda t: t[0], carry["state"])
