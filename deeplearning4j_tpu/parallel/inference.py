"""Parallel inference — request batching over devices.

Reference analog: org.deeplearning4j.parallelism.ParallelInference — an
observable queue that coalesces single requests into batches and round-robins
them over per-device model replicas (INPLACE / BATCHED modes).

TPU-native: one jitted forward sharded over the mesh's data axis does the
replica fan-out; the host-side piece that survives is the batching queue.

Serving-gateway extensions (PR 2): the queue can be bounded (``max_queue``,
admission control maps ``queue.Full`` to HTTP 429), every request can carry
a monotonic-clock ``deadline`` (expired requests are shed at dispatch time
and resolved with a :class:`DeadlineExceeded` instead of blocking their
caller forever), forward-pass errors are fanned back to every waiter of the
batch instead of silently killing the worker thread, and ``stop(drain=True)``
flushes already-admitted requests before joining — the graceful-drain half
of the gateway lifecycle.

Self-healing (fault-injection PR): the worker is SUPERVISED. A crash that
escapes the forward-pass handler (ragged stack, injected ``infer_crash`` /
``worker_crash``, a bug anywhere in dispatch) fans the error back to the
in-flight batch and revives the loop in place; a thread found dead at submit
time is restarted before the request is admitted. Every revival increments
``restarts`` and ``dl4j_recovery_total{component="serving"}``, and
``healthy()`` feeds the gateway's degraded-state /healthz report.

Multi-tenant extensions (PR 11):

- **Priority lanes.** ``submit(..., klass="batch")`` routes a request to the
  low-priority lane; everything else (``klass=None`` or ``"interactive"``)
  rides the primary lane. Workers always drain the primary lane first, so
  interactive traffic preempts queued batch work without starving it (batch
  is served whenever the primary lane is empty). A counting semaphore gates
  both lanes, so batch-only load never waits on an empty primary lane.
- **Replicas.** ``replicas`` worker threads share the lanes;
  ``set_replicas(n)`` grows/shrinks the pool live (surplus workers retire
  at their next loop check) — the autoscaler's actuation point.
- **Queue-depth truth.** ``on_depth(backlog)`` fires every time requests
  leave the lanes — normal dispatch AND deadline sheds — so the per-model
  ``dl4j_serving_model_queue_depth`` gauge decays on the shed path too
  instead of freezing at its last submit-time value.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.monitoring import flight
from deeplearning4j_tpu.parallel.mesh import DeviceMesh


class DeadlineExceeded(Exception):
    """Posted to a request's result queue when its deadline passed before
    dispatch. Callers that submit with deadlines must check ``get()``
    results with :func:`resolve`."""


def resolve(result):
    """Turn a result-queue item into a value: raises when the worker posted
    an exception (deadline shed or forward-pass failure)."""
    if isinstance(result, BaseException):
        raise result
    return result


class ParallelInference:
    """Batched inference server around a model's output().

    batch_limit: max requests coalesced into one device batch;
    queue_timeout_s: max wait to fill a batch before running partial;
    max_queue: bound on admitted-but-undispatched requests PER LANE (0 =
    unbounded; when full, ``submit`` raises ``queue.Full`` — backpressure,
    not pile-up);
    replicas: worker threads sharing the lanes (autoscaler-adjustable via
    :meth:`set_replicas`);
    on_shed: optional callback(n, klass) invoked when n deadline-expired
    requests of priority class ``klass`` are shed at dispatch;
    on_depth: optional callback(backlog) invoked whenever requests leave
    the lanes (dispatch or shed) — the queue-depth gauge feed;
    name: worker-thread name prefix (threads are ``<name>-<idx>``) — the
    gateway registry passes ``pi-<model>`` so stack dumps and Perfetto
    thread tracks identify which model a worker serves.
    """

    def __init__(self, model, mesh: Optional[DeviceMesh] = None,
                 batch_limit: int = 32, queue_timeout_s: float = 0.005,
                 pad_batches: bool = True, max_queue: int = 0,
                 replicas: int = 1,
                 on_shed: Optional[Callable] = None,
                 on_depth: Optional[Callable[[int], None]] = None,
                 name: Optional[str] = None):
        self.model = model
        self.mesh = mesh
        self.name = name or "pi-worker"
        self.batch_limit = batch_limit
        self.queue_timeout_s = queue_timeout_s
        # r5 (serving perf): a partially-filled batch is zero-padded up to
        # the next power of two before dispatch, so the jitted forward
        # compiles at most log2(batch_limit)+1 programs instead of one per
        # observed batch size (a retrace storm under bursty load — every
        # new size stalled its whole batch behind an XLA compile)
        self.pad_batches = pad_batches
        self.max_queue = max_queue
        self.on_shed = on_shed
        self.on_depth = on_depth
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)       # primary
        self._q_lo: queue.Queue = queue.Queue(maxsize=max_queue)    # batch
        self._sem = threading.Semaphore(0)   # counts items across both lanes
        self._workers: Dict[int, threading.Thread] = {}
        self._target = max(1, int(replicas))
        self._stop = threading.Event()
        self._accepting = False
        # self-healing bookkeeping: how many times a worker loop was
        # revived after an unexpected death (crash escaping the per-batch
        # handler, or a thread found dead at submit time)
        self.restarts = 0
        self._restart_lock = threading.Lock()

    # --- synchronous one-shot API (ParallelInference.output) ---
    def output(self, x):
        if self.mesh is not None:
            with self.mesh.mesh:
                return self.model.output(x)
        return self.model.output(x)

    # --- single-worker compatibility shims (tests poke worker 0) ---
    @property
    def _worker(self) -> Optional[threading.Thread]:
        return self._workers.get(0)

    @_worker.setter
    def _worker(self, thread: Optional[threading.Thread]) -> None:
        if thread is None:
            self._workers.pop(0, None)
        else:
            self._workers[0] = thread

    # --- async batched API ---
    def start(self):
        self._stop.clear()
        self._accepting = True
        for i in range(self._target):
            self._spawn(i)
        return self

    def _spawn(self, idx: int) -> None:
        t = threading.Thread(target=self._run, args=(idx,),
                             name=f"{self.name}-{idx}", daemon=True)
        self._workers[idx] = t
        t.start()

    def replicas(self) -> int:
        """Live worker-thread count (the autoscaler's observed state)."""
        return sum(1 for w in self._workers.values() if w.is_alive())

    def set_replicas(self, n: int) -> int:
        """Grow/shrink the worker pool to ``n`` threads. Growth spawns
        immediately; shrink is cooperative — surplus workers retire at
        their next loop check, finishing their in-flight batch first.
        Returns the new target."""
        n = max(1, int(n))
        with self._restart_lock:
            self._target = n
            if not self._stop.is_set():
                for i in range(n):
                    w = self._workers.get(i)
                    if w is None or not w.is_alive():
                        self._spawn(i)
        return self._target

    def stop(self, drain: bool = False, timeout: float = 30.0):
        """Stop the workers. ``drain=True`` first stops admitting, flushes
        every already-queued request (bounded by ``timeout``), and only
        then joins — in-flight work completes instead of being orphaned."""
        self._accepting = False
        alive = [w for w in self._workers.values() if w.is_alive()]
        if drain and alive:
            end = time.monotonic() + timeout
            while self.backlog() and time.monotonic() < end:
                time.sleep(0.005)
        self._stop.set()
        for w in self._workers.values():
            if w.is_alive():
                w.join(timeout=max(5.0, timeout))

    def drain(self, timeout: float = 30.0):
        """Graceful shutdown: stop admitting, flush, join."""
        self.stop(drain=True, timeout=timeout)

    def backlog(self) -> int:
        """Admitted-but-undispatched request count across both lanes
        (approximate)."""
        return self._q.qsize() + self._q_lo.qsize()

    def lane_backlog(self, klass: Optional[str] = None) -> int:
        """Backlog of the lane ``klass`` routes to. Admission capacity
        checks use this rather than :meth:`backlog` so a saturated batch
        lane cannot starve interactive admission — lanes are bounded
        independently, exactly like ``submit`` routes them."""
        return (self._q_lo if klass == "batch" else self._q).qsize()

    def submit(self, x, deadline: Optional[float] = None,
               klass: Optional[str] = None, trace=None) -> "queue.Queue":
        """Submit one example [features...] -> a result queue of size 1.

        ``deadline``: optional ``time.monotonic()`` instant; a request still
        undispatched past it is resolved with :class:`DeadlineExceeded`
        rather than executed. ``klass``: priority class — ``"batch"`` rides
        the low-priority lane, anything else the primary lane. ``trace``:
        optional RequestTrace — the worker records the request's queue-wait
        and device-dispatch spans on it (None = zero tracing work). Raises
        ``queue.Full`` when a bounded lane is at capacity and
        ``RuntimeError`` when the server is not accepting (stopped or
        draining). Worker threads found dead (they should be running while
        accepting) are restarted before the request is admitted — no
        request enters a lane nothing is consuming.
        """
        if not self._accepting:
            raise RuntimeError("ParallelInference is not accepting requests "
                               "(stopped or draining)")
        if (self._workers
                and not any(w.is_alive() for w in self._workers.values())
                and not self._stop.is_set()):
            self._revive("dead_thread")
        out: queue.Queue = queue.Queue(maxsize=1)
        lane = self._q_lo if klass == "batch" else self._q
        lane.put_nowait((np.asarray(x), out, deadline, klass, trace,
                         time.monotonic() if trace is not None else 0.0))
        self._sem.release()
        return out

    def healthy(self) -> bool:
        """True while at least one worker is running (or the pool is
        intentionally stopped); False only in the degraded window between
        the last worker death and its revival."""
        return (not self._workers or self._stop.is_set()
                or any(w.is_alive() for w in self._workers.values()))

    def _record_restart(self, outcome: str):
        with self._restart_lock:
            self.restarts += 1
        mon = monitoring.recovery_monitor()
        if mon is not None:
            mon.recovery_total.labels(component="serving",
                                      outcome=outcome).inc()
        rec = flight.recorder()
        if rec is not None:
            # a dump-trigger kind: a worker death under load is exactly
            # the incident the black box exists for
            rec.record("worker_crash", severity="error", component="serving",
                       worker=self.name, outcome=outcome,
                       restarts=self.restarts)

    def _revive(self, outcome: str):
        """Restart dead worker threads (detected at submit time). Queued
        requests are preserved — the new threads drain them."""
        spawned = False
        with self._restart_lock:
            if self._stop.is_set():
                return
            for i in range(self._target):
                w = self._workers.get(i)
                if w is not None and not w.is_alive():
                    self._spawn(i)
                    spawned = True
        if spawned:
            self._record_restart(outcome)

    def _pop(self, timeout: float):
        """One request off the lanes, primary first; None on timeout. A
        semaphore permit guarantees an item exists across the two lanes,
        so batch-only load never stalls behind a blocking get on the empty
        primary lane."""
        if not self._sem.acquire(timeout=timeout):
            return None
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return self._q_lo.get_nowait()

    def _run(self, idx: int = 0):
        while not self._stop.is_set():
            if idx >= self._target:
                return          # autoscaler shrank the pool; retire quietly
            try:
                self._serve_once()
            except Exception:  # noqa: BLE001 — a crash that escaped the
                # forward-pass handler (ragged np.stack, injected
                # infer_crash, a bug outside the forward try) used to kill
                # the thread and hang every queued future. _serve_once
                # already fanned the error to the in-flight batch; revive
                # the loop in place and keep serving.
                self._record_restart("worker_restarted")
                continue

    def _serve_once(self):
        """Pull + dispatch one batch. Any exception after requests are
        dequeued is fanned back to every unresolved waiter before it
        propagates — no future is ever silently dropped."""
        first = self._pop(timeout=0.05)
        if first is None:
            return
        batch = [first]
        while len(batch) < self.batch_limit:
            item = self._pop(timeout=self.queue_timeout_s)
            if item is None:
                break
            batch.append(item)
        if self.on_depth is not None:
            # requests just left the lanes; every exit path below (shed,
            # dispatch, error fan-back) counts as a dequeue for the gauge
            self.on_depth(self.backlog())
        pending = list(batch)       # not yet resolved with a result/error
        try:
            from deeplearning4j_tpu import faults

            plan = faults.active()
            if plan is not None:
                if plan.fires("infer_crash") or plan.fires("worker_crash"):
                    raise faults.InferenceWorkerCrash(
                        "injected inference-worker crash")
                if plan.fires("slow_worker"):
                    time.sleep(plan.delay_s)
            # shed deadline-expired requests BEFORE dispatch: their callers
            # get an immediate DeadlineExceeded instead of riding (and
            # paying for) a device batch whose result nobody will read
            now = time.monotonic()
            live, shed = [], {}
            for item in batch:
                if item[2] is not None and now > item[2]:
                    item[1].put(DeadlineExceeded(
                        "deadline passed before dispatch"))
                    pending.remove(item)
                    shed[item[3]] = shed.get(item[3], 0) + 1
                    if item[4] is not None:
                        item[4].add_span("queue_wait", item[5], now)
                        item[4].event("shed", reason="deadline")
                else:
                    live.append(item)
                    if item[4] is not None:
                        item[4].add_span("queue_wait", item[5], now)
            if shed and self.on_shed is not None:
                for klass, n in shed.items():
                    self.on_shed(n, klass)
            if not live:
                return
            mon = monitoring.serving_monitor()
            if mon is not None:
                # batch-size distribution + queue backlog at dispatch time
                mon.batch_size.observe(len(live))
                mon.queue_depth.set(self.backlog())
            xs = np.stack([b[0] for b in live])
            n = xs.shape[0]
            if self.pad_batches and n > 1:
                bucket = min(1 << (n - 1).bit_length(), self.batch_limit)
                if bucket > n:
                    pad = np.zeros((bucket - n,) + xs.shape[1:], xs.dtype)
                    xs = np.concatenate([xs, pad])
            t_dis = time.monotonic()
            try:
                ys = np.asarray(self.output(xs))[:n]
            except Exception as e:  # noqa: BLE001 — an EXPECTED failure
                # mode (bad input, OOM): fan it back and keep the loop —
                # not a worker crash, so no restart is counted
                for item in live:
                    item[1].put(e)
                    pending.remove(item)
                return
            t_done = time.monotonic()
            for item, y in zip(live, ys):
                if item[4] is not None:
                    item[4].add_span("device_dispatch", t_dis, t_done,
                                     batch=len(live))
                item[1].put(y)
                pending.remove(item)
        except Exception as e:  # noqa: BLE001 — crash path: resolve every
            # still-pending waiter with the error, then escalate to _run
            # for the restart accounting
            for item in pending:
                item[1].put(e)
            raise
