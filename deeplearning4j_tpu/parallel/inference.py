"""Parallel inference — request batching over devices.

Reference analog: org.deeplearning4j.parallelism.ParallelInference — an
observable queue that coalesces single requests into batches and round-robins
them over per-device model replicas (INPLACE / BATCHED modes).

TPU-native: one jitted forward sharded over the mesh's data axis does the
replica fan-out; the host-side piece that survives is the batching queue.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

import numpy as np

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.parallel.mesh import DeviceMesh


class ParallelInference:
    """Batched inference server around a model's output().

    batch_limit: max requests coalesced into one device batch;
    queue_timeout_s: max wait to fill a batch before running partial.
    """

    def __init__(self, model, mesh: Optional[DeviceMesh] = None,
                 batch_limit: int = 32, queue_timeout_s: float = 0.005,
                 pad_batches: bool = True):
        self.model = model
        self.mesh = mesh
        self.batch_limit = batch_limit
        self.queue_timeout_s = queue_timeout_s
        # r5 (serving perf): a partially-filled batch is zero-padded up to
        # the next power of two before dispatch, so the jitted forward
        # compiles at most log2(batch_limit)+1 programs instead of one per
        # observed batch size (a retrace storm under bursty load — every
        # new size stalled its whole batch behind an XLA compile)
        self.pad_batches = pad_batches
        self._q: queue.Queue = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # --- synchronous one-shot API (ParallelInference.output) ---
    def output(self, x):
        if self.mesh is not None:
            with self.mesh.mesh:
                return self.model.output(x)
        return self.model.output(x)

    # --- async batched API ---
    def start(self):
        self._stop.clear()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        return self

    def stop(self):
        self._stop.set()
        if self._worker:
            self._worker.join(timeout=5)

    def submit(self, x) -> "queue.Queue":
        """Submit one example [features...] -> a result queue of size 1."""
        out: queue.Queue = queue.Queue(maxsize=1)
        self._q.put((np.asarray(x), out))
        return out

    def _run(self):
        while not self._stop.is_set():
            batch = []
            try:
                batch.append(self._q.get(timeout=0.05))
            except queue.Empty:
                continue
            while len(batch) < self.batch_limit:
                try:
                    batch.append(self._q.get(timeout=self.queue_timeout_s))
                except queue.Empty:
                    break
            mon = monitoring.serving_monitor()
            if mon is not None:
                # batch-size distribution + queue backlog at dispatch time
                mon.batch_size.observe(len(batch))
                mon.queue_depth.set(self._q.qsize())
            xs = np.stack([b[0] for b in batch])
            n = xs.shape[0]
            if self.pad_batches and n > 1:
                bucket = min(1 << (n - 1).bit_length(), self.batch_limit)
                if bucket > n:
                    pad = np.zeros((bucket - n,) + xs.shape[1:], xs.dtype)
                    xs = np.concatenate([xs, pad])
            ys = np.asarray(self.output(xs))[:n]
            for (x, out), y in zip(batch, ys):
                out.put(y)
