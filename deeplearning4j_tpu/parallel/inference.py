"""Parallel inference — request batching over devices.

Reference analog: org.deeplearning4j.parallelism.ParallelInference — an
observable queue that coalesces single requests into batches and round-robins
them over per-device model replicas (INPLACE / BATCHED modes).

TPU-native: one jitted forward sharded over the mesh's data axis does the
replica fan-out; the host-side piece that survives is the batching queue.

Serving-gateway extensions (PR 2): the queue can be bounded (``max_queue``,
admission control maps ``queue.Full`` to HTTP 429), every request can carry
a monotonic-clock ``deadline`` (expired requests are shed at dispatch time
and resolved with a :class:`DeadlineExceeded` instead of blocking their
caller forever), forward-pass errors are fanned back to every waiter of the
batch instead of silently killing the worker thread, and ``stop(drain=True)``
flushes already-admitted requests before joining — the graceful-drain half
of the gateway lifecycle.

Self-healing (fault-injection PR): the worker is SUPERVISED. A crash that
escapes the forward-pass handler (ragged stack, injected ``infer_crash``,
a bug anywhere in dispatch) fans the error back to the in-flight batch and
revives the loop in place; a thread found dead at submit time is restarted
before the request is admitted. Every revival increments ``restarts`` and
``dl4j_recovery_total{component="serving"}``, and ``healthy()`` feeds the
gateway's degraded-state /healthz report.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from deeplearning4j_tpu import monitoring
from deeplearning4j_tpu.parallel.mesh import DeviceMesh


class DeadlineExceeded(Exception):
    """Posted to a request's result queue when its deadline passed before
    dispatch. Callers that submit with deadlines must check ``get()``
    results with :func:`resolve`."""


def resolve(result):
    """Turn a result-queue item into a value: raises when the worker posted
    an exception (deadline shed or forward-pass failure)."""
    if isinstance(result, BaseException):
        raise result
    return result


class ParallelInference:
    """Batched inference server around a model's output().

    batch_limit: max requests coalesced into one device batch;
    queue_timeout_s: max wait to fill a batch before running partial;
    max_queue: bound on admitted-but-undispatched requests (0 = unbounded;
    when full, ``submit`` raises ``queue.Full`` — backpressure, not pile-up);
    on_shed: optional callback(n) invoked when n deadline-expired requests
    are shed at dispatch.
    """

    def __init__(self, model, mesh: Optional[DeviceMesh] = None,
                 batch_limit: int = 32, queue_timeout_s: float = 0.005,
                 pad_batches: bool = True, max_queue: int = 0,
                 on_shed: Optional[Callable[[int], None]] = None):
        self.model = model
        self.mesh = mesh
        self.batch_limit = batch_limit
        self.queue_timeout_s = queue_timeout_s
        # r5 (serving perf): a partially-filled batch is zero-padded up to
        # the next power of two before dispatch, so the jitted forward
        # compiles at most log2(batch_limit)+1 programs instead of one per
        # observed batch size (a retrace storm under bursty load — every
        # new size stalled its whole batch behind an XLA compile)
        self.pad_batches = pad_batches
        self.max_queue = max_queue
        self.on_shed = on_shed
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._accepting = False
        # self-healing bookkeeping: how many times the worker loop was
        # revived after an unexpected death (crash escaping the per-batch
        # handler, or a thread found dead at submit time)
        self.restarts = 0
        self._restart_lock = threading.Lock()

    # --- synchronous one-shot API (ParallelInference.output) ---
    def output(self, x):
        if self.mesh is not None:
            with self.mesh.mesh:
                return self.model.output(x)
        return self.model.output(x)

    # --- async batched API ---
    def start(self):
        self._stop.clear()
        self._accepting = True
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        return self

    def stop(self, drain: bool = False, timeout: float = 30.0):
        """Stop the worker. ``drain=True`` first stops admitting, flushes
        every already-queued request (bounded by ``timeout``), and only
        then joins — in-flight work completes instead of being orphaned."""
        self._accepting = False
        if drain and self._worker is not None and self._worker.is_alive():
            end = time.monotonic() + timeout
            while not self._q.empty() and time.monotonic() < end:
                time.sleep(0.005)
        self._stop.set()
        if self._worker:
            self._worker.join(timeout=max(5.0, timeout))

    def drain(self, timeout: float = 30.0):
        """Graceful shutdown: stop admitting, flush, join."""
        self.stop(drain=True, timeout=timeout)

    def backlog(self) -> int:
        """Admitted-but-undispatched request count (approximate)."""
        return self._q.qsize()

    def submit(self, x, deadline: Optional[float] = None) -> "queue.Queue":
        """Submit one example [features...] -> a result queue of size 1.

        ``deadline``: optional ``time.monotonic()`` instant; a request still
        undispatched past it is resolved with :class:`DeadlineExceeded`
        rather than executed. Raises ``queue.Full`` when a bounded queue is
        at capacity and ``RuntimeError`` when the server is not accepting
        (stopped or draining). A worker thread found dead (it should be
        running while accepting) is restarted before the request is
        admitted — no request enters a queue nothing is consuming.
        """
        if not self._accepting:
            raise RuntimeError("ParallelInference is not accepting requests "
                               "(stopped or draining)")
        if (self._worker is not None and not self._worker.is_alive()
                and not self._stop.is_set()):
            self._revive("dead_thread")
        out: queue.Queue = queue.Queue(maxsize=1)
        self._q.put_nowait((np.asarray(x), out, deadline))
        return out

    def healthy(self) -> bool:
        """True while the worker is running (or intentionally stopped);
        False only in the degraded window between a worker death and its
        revival."""
        return (self._worker is None or self._worker.is_alive()
                or self._stop.is_set())

    def _record_restart(self, outcome: str):
        with self._restart_lock:
            self.restarts += 1
        mon = monitoring.recovery_monitor()
        if mon is not None:
            mon.recovery_total.labels(component="serving",
                                      outcome=outcome).inc()

    def _revive(self, outcome: str):
        """Restart a dead worker thread (detected at submit time). Queued
        requests are preserved — the new thread drains them."""
        with self._restart_lock:
            if (self._worker is not None and not self._worker.is_alive()
                    and not self._stop.is_set()):
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()
            else:
                return
        mon = monitoring.recovery_monitor()
        if mon is not None:
            mon.recovery_total.labels(component="serving",
                                      outcome=outcome).inc()
        with self._restart_lock:
            self.restarts += 1

    def _run(self):
        while not self._stop.is_set():
            try:
                self._serve_once()
            except Exception:  # noqa: BLE001 — a crash that escaped the
                # forward-pass handler (ragged np.stack, injected
                # infer_crash, a bug outside the forward try) used to kill
                # the thread and hang every queued future. _serve_once
                # already fanned the error to the in-flight batch; revive
                # the loop in place and keep serving.
                self._record_restart("worker_restarted")
                continue

    def _serve_once(self):
        """Pull + dispatch one batch. Any exception after requests are
        dequeued is fanned back to every unresolved waiter before it
        propagates — no future is ever silently dropped."""
        batch = []
        try:
            batch.append(self._q.get(timeout=0.05))
        except queue.Empty:
            return
        while len(batch) < self.batch_limit:
            try:
                batch.append(self._q.get(timeout=self.queue_timeout_s))
            except queue.Empty:
                break
        pending = list(batch)       # not yet resolved with a result/error
        try:
            from deeplearning4j_tpu import faults

            plan = faults.active()
            if plan is not None and plan.fires("infer_crash"):
                raise faults.InferenceWorkerCrash(
                    "injected inference-worker crash")
            # shed deadline-expired requests BEFORE dispatch: their callers
            # get an immediate DeadlineExceeded instead of riding (and
            # paying for) a device batch whose result nobody will read
            now = time.monotonic()
            live, shed = [], 0
            for item in batch:
                if item[2] is not None and now > item[2]:
                    item[1].put(DeadlineExceeded(
                        "deadline passed before dispatch"))
                    pending.remove(item)
                    shed += 1
                else:
                    live.append(item)
            if shed and self.on_shed is not None:
                self.on_shed(shed)
            if not live:
                return
            mon = monitoring.serving_monitor()
            if mon is not None:
                # batch-size distribution + queue backlog at dispatch time
                mon.batch_size.observe(len(live))
                mon.queue_depth.set(self._q.qsize())
            xs = np.stack([b[0] for b in live])
            n = xs.shape[0]
            if self.pad_batches and n > 1:
                bucket = min(1 << (n - 1).bit_length(), self.batch_limit)
                if bucket > n:
                    pad = np.zeros((bucket - n,) + xs.shape[1:], xs.dtype)
                    xs = np.concatenate([xs, pad])
            try:
                ys = np.asarray(self.output(xs))[:n]
            except Exception as e:  # noqa: BLE001 — an EXPECTED failure
                # mode (bad input, OOM): fan it back and keep the loop —
                # not a worker crash, so no restart is counted
                for item in live:
                    item[1].put(e)
                    pending.remove(item)
                return
            for item, y in zip(live, ys):
                item[1].put(y)
                pending.remove(item)
        except Exception as e:  # noqa: BLE001 — crash path: resolve every
            # still-pending waiter with the error, then escalate to _run
            # for the restart accounting
            for item in pending:
                item[1].put(e)
            raise
