"""Parallelism over device meshes — XLA collectives replace the reference's
entire distribution stack.

Reference analog (SURVEY.md §2.4): ParallelWrapper (single-node multi-GPU
threads + gradient sharing), Spark ParameterAveragingTrainingMaster,
SharedTrainingMaster + VoidParameterServer over Aeron UDP, ParallelInference.
TPU-native redesign: one SPMD program over a jax.sharding.Mesh; gradients
all-reduce over ICI via compiler-emitted psum; multi-host runs the same code
under jax.distributed. TP/PP/SP are net-new capabilities the reference lacks.
"""

from deeplearning4j_tpu.parallel.mesh import DeviceMesh, multi_slice_mesh
from deeplearning4j_tpu.parallel.param_averaging import ParameterAveragingTrainer
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.tensor_parallel import TensorParallel
from deeplearning4j_tpu.parallel.pipeline import (
    GPipe, HeteroPipe, graph_stage_fn, pack_stage_params,
    pipeline_train_step, stack_stage_params, unpack_stage_params,
)
from deeplearning4j_tpu.parallel.expert import (
    init_moe_params, moe_param_specs, place_moe_params, switch_moe,
)
from deeplearning4j_tpu.parallel.spark import (
    ParameterAveragingTrainingMaster, RoundSupervisor, SharedTrainingMaster,
    SparkComputationGraph, SparkDl4jMultiLayer,
)
from deeplearning4j_tpu.parallel.distributed import (
    FaultTolerantTrainer, initialize_distributed,
)
from deeplearning4j_tpu.parallel.sequence import (
    ring_attention, ring_attention_zigzag, sequence_parallel_encoder,
    ulysses_attention, zigzag_shard, zigzag_unshard,
)
from deeplearning4j_tpu.parallel.compression import (
    EncodedGradientTrainer, message_density, threshold_encode,
)

__all__ = ["DeviceMesh", "multi_slice_mesh", "ParameterAveragingTrainer", "ParallelWrapper", "ParallelInference", "TensorParallel",
           "GPipe", "HeteroPipe", "graph_stage_fn", "pack_stage_params",
           "pipeline_train_step", "stack_stage_params", "unpack_stage_params",
           "init_moe_params", "moe_param_specs", "place_moe_params",
           "switch_moe", "FaultTolerantTrainer", "initialize_distributed",
           "SparkDl4jMultiLayer", "SparkComputationGraph",
           "ParameterAveragingTrainingMaster", "SharedTrainingMaster",
           "RoundSupervisor",
           "ring_attention", "ring_attention_zigzag", "ulysses_attention",
           "sequence_parallel_encoder", "zigzag_shard", "zigzag_unshard",
           "EncodedGradientTrainer", "threshold_encode", "message_density"]
