"""Pipeline parallelism — GPipe-style microbatched stage loop.

Reference analog: NONE — the reference has no pipeline parallelism (SURVEY.md
§2.4). Net-new, TPU-first design: the "pipe" mesh axis holds one stage per
device; microbatch activations rotate stage-to-stage with ``lax.ppermute``
over the ICI ring inside a ``lax.fori_loop``. The whole pipeline — all
bubbles, sends, and stage compute — is a single differentiable SPMD program,
so ``jax.grad`` of the pipelined forward IS pipelined backprop (ppermute's
transpose is the reverse rotation); no hand-written 1F1B schedule is needed
for correctness, and XLA overlaps the ppermute with stage compute.

Constraints (documented, enforced): every stage must map activations of one
fixed shape to the same shape (the classic homogeneous-block setting, e.g. a
stack of transformer blocks); stage parameters are passed stacked on a
leading ``n_stages`` axis and sharded over "pipe".
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DeviceMesh

from deeplearning4j_tpu.parallel._compat import pvary as _pvary, shard_map


def stack_stage_params(stage_params_list):
    """Stack per-stage param pytrees along a new leading axis (to be sharded
    over "pipe"). All stages must share a param structure."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params_list)


def _pipeline_local(params, x, *, stage_fn, n_micro, axis):
    """Per-device body under shard_map. params: leading dim 1 (this stage's
    slice); x: the full batch (replicated over "pipe")."""
    params = jax.tree_util.tree_map(lambda p: p[0], params)
    n_stages = lax.psum(1, axis)
    stage = lax.axis_index(axis)
    micro = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
    mshape = micro.shape[1:]

    carry0 = _pvary(jnp.zeros(mshape, x.dtype), (axis,))
    outs0 = _pvary(jnp.zeros((n_micro,) + mshape, x.dtype), (axis,))
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def body(t, state):
        carry, outs = state
        # stage 0 ingests microbatch t (clipped; out-of-range iterations feed
        # garbage that is never written to outs), others take the carry.
        feed = lax.dynamic_index_in_dim(
            micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        inp = jnp.where(stage == 0, feed, carry)
        out = stage_fn(params, inp)
        # last stage has finished microbatch t - (n_stages - 1) at step t
        widx = t - (n_stages - 1)
        write = jnp.logical_and(stage == n_stages - 1, widx >= 0)
        prev = lax.dynamic_index_in_dim(
            outs, jnp.clip(widx, 0, n_micro - 1), 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, out, prev), jnp.clip(widx, 0, n_micro - 1), 0)
        carry = lax.ppermute(out, axis, perm)
        return carry, outs

    total = n_micro + n_stages - 1
    _, outs = lax.fori_loop(0, total, body, (carry0, outs0))
    # outs is only valid on the last stage; broadcast it to every pipe device
    # (psum of a one-hot-masked tensor — GSPMD lowers this to a broadcast).
    outs = lax.psum(jnp.where(stage == n_stages - 1, outs, 0), axis)
    return outs.reshape(x.shape)


class GPipe:
    """Microbatched pipeline over the mesh "pipe" axis.

    ``stage_fn(stage_params, x) -> y`` with ``y.shape == x.shape``;
    ``params`` stacked on a leading n_stages axis (``stack_stage_params``).

        pipe = GPipe(stage_fn, mesh, n_microbatches=4)
        y = pipe(stacked_params, x)            # pipelined forward
        grads = jax.grad(loss_of(pipe))(...)   # pipelined backward for free
    """

    def __init__(self, stage_fn: Callable, mesh: DeviceMesh,
                 n_microbatches: int = 4, axis: str = "pipe"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.n_micro = n_microbatches
        self.axis = axis

    def __call__(self, stacked_params, x):
        n_stages = self.mesh.shape[self.axis]
        lead = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if lead != n_stages:
            raise ValueError(f"params stacked for {lead} stages but mesh "
                             f"'{self.axis}' axis has {n_stages}")
        if x.shape[0] % self.n_micro:
            raise ValueError(f"batch {x.shape[0]} not divisible by "
                             f"{self.n_micro} microbatches")
        fn = shard_map(
            functools.partial(_pipeline_local, stage_fn=self.stage_fn,
                              n_micro=self.n_micro, axis=self.axis),
            mesh=self.mesh.mesh,
            in_specs=(self._param_spec(stacked_params), P()),
            out_specs=P(),
        )
        return fn(stacked_params, x)

    def _param_spec(self, stacked_params):
        return jax.tree_util.tree_map(
            lambda p: P(*([self.axis] + [None] * (np.ndim(p) - 1))), stacked_params)

    def sequential_reference(self, stacked_params, x):
        """Unpipelined equivalent (for parity tests): apply stages in order."""
        n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        for i in range(n_stages):
            p = jax.tree_util.tree_map(lambda q: q[i], stacked_params)
            x = self.stage_fn(p, x)
        return x


def pipeline_train_step(pipe: GPipe, loss_fn: Callable, optimizer,
                        head_fn: Optional[Callable] = None):
    """Build a jitted pipelined train step.

    loss_fn(y_pred, y) -> scalar; head_fn(head_params, activations) -> y_pred
    (e.g. the output projection, run replicated after the pipeline).
    Returns step(params, opt_state, step_i, x, y) -> (params, opt_state, loss)
    where params = {"stages": stacked, "head": head_params or {}}.
    """

    def loss(params, x, y):
        h = pipe(params["stages"], x)
        pred = head_fn(params.get("head", {}), h) if head_fn is not None else h
        return loss_fn(pred, y)

    @jax.jit
    def step(params, opt_state, step_i, x, y):
        lval, grads = jax.value_and_grad(loss)(params, x, y)
        upd, opt_state = optimizer.update(grads, opt_state, params, step_i)
        params = jax.tree_util.tree_map(lambda p, d: p - d, params, upd)
        return params, opt_state, lval

    return step
