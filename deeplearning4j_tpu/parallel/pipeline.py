"""Pipeline parallelism — GPipe-style microbatched stage loop.

Reference analog: NONE — the reference has no pipeline parallelism (SURVEY.md
§2.4). Net-new, TPU-first design: the "pipe" mesh axis holds one stage per
device; microbatch activations rotate stage-to-stage with ``lax.ppermute``
over the ICI ring inside a ``lax.fori_loop``. The whole pipeline — all
bubbles, sends, and stage compute — is a single differentiable SPMD program,
so ``jax.grad`` of the pipelined forward IS pipelined backprop (ppermute's
transpose is the reverse rotation); no hand-written 1F1B schedule is needed
for correctness, and XLA overlaps the ppermute with stage compute.

Constraints (documented, enforced): every stage must map activations of one
fixed shape to the same shape (the classic homogeneous-block setting, e.g. a
stack of transformer blocks); stage parameters are passed stacked on a
leading ``n_stages`` axis and sharded over "pipe".
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DeviceMesh

from deeplearning4j_tpu.parallel._compat import pvary as _pvary, shard_map


def stack_stage_params(stage_params_list):
    """Stack per-stage param pytrees along a new leading axis (to be sharded
    over "pipe"). All stages must share a param structure."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params_list)


def _pipeline_local(params, x, *, stage_fn, n_micro, axis):
    """Per-device body under shard_map. params: leading dim 1 (this stage's
    slice); x: the full batch (replicated over "pipe")."""
    params = jax.tree_util.tree_map(lambda p: p[0], params)
    n_stages = lax.psum(1, axis)
    stage = lax.axis_index(axis)
    micro = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
    mshape = micro.shape[1:]

    carry0 = _pvary(jnp.zeros(mshape, x.dtype), (axis,))
    outs0 = _pvary(jnp.zeros((n_micro,) + mshape, x.dtype), (axis,))
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def body(t, state):
        carry, outs = state
        # stage 0 ingests microbatch t (clipped; out-of-range iterations feed
        # garbage that is never written to outs), others take the carry.
        feed = lax.dynamic_index_in_dim(
            micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        inp = jnp.where(stage == 0, feed, carry)
        out = stage_fn(params, inp)
        # last stage has finished microbatch t - (n_stages - 1) at step t
        widx = t - (n_stages - 1)
        write = jnp.logical_and(stage == n_stages - 1, widx >= 0)
        prev = lax.dynamic_index_in_dim(
            outs, jnp.clip(widx, 0, n_micro - 1), 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, out, prev), jnp.clip(widx, 0, n_micro - 1), 0)
        carry = lax.ppermute(out, axis, perm)
        return carry, outs

    total = n_micro + n_stages - 1
    _, outs = lax.fori_loop(0, total, body, (carry0, outs0))
    # outs is only valid on the last stage; broadcast it to every pipe device
    # (psum of a one-hot-masked tensor — GSPMD lowers this to a broadcast).
    outs = lax.psum(jnp.where(stage == n_stages - 1, outs, 0), axis)
    return outs.reshape(x.shape)


class GPipe:
    """Microbatched pipeline over the mesh "pipe" axis.

    ``stage_fn(stage_params, x) -> y`` with ``y.shape == x.shape``;
    ``params`` stacked on a leading n_stages axis (``stack_stage_params``).

        pipe = GPipe(stage_fn, mesh, n_microbatches=4)
        y = pipe(stacked_params, x)            # pipelined forward
        grads = jax.grad(loss_of(pipe))(...)   # pipelined backward for free
    """

    def __init__(self, stage_fn: Callable, mesh: DeviceMesh,
                 n_microbatches: int = 4, axis: str = "pipe"):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.n_micro = n_microbatches
        self.axis = axis

    def __call__(self, stacked_params, x):
        n_stages = self.mesh.shape[self.axis]
        lead = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if lead != n_stages:
            raise ValueError(f"params stacked for {lead} stages but mesh "
                             f"'{self.axis}' axis has {n_stages}")
        if x.shape[0] % self.n_micro:
            raise ValueError(f"batch {x.shape[0]} not divisible by "
                             f"{self.n_micro} microbatches")
        fn = shard_map(
            functools.partial(_pipeline_local, stage_fn=self.stage_fn,
                              n_micro=self.n_micro, axis=self.axis),
            mesh=self.mesh.mesh,
            in_specs=(self._param_spec(stacked_params), P()),
            out_specs=P(),
        )
        return fn(stacked_params, x)

    def _param_spec(self, stacked_params):
        return jax.tree_util.tree_map(
            lambda p: P(*([self.axis] + [None] * (np.ndim(p) - 1))), stacked_params)

    def sequential_reference(self, stacked_params, x):
        """Unpipelined equivalent (for parity tests): apply stages in order."""
        n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        for i in range(n_stages):
            p = jax.tree_util.tree_map(lambda q: q[i], stacked_params)
            x = self.stage_fn(p, x)
        return x


def pipeline_train_step(pipe: GPipe, loss_fn: Callable, optimizer,
                        head_fn: Optional[Callable] = None):
    """Build a jitted pipelined train step.

    loss_fn(y_pred, y) -> scalar; head_fn(head_params, activations) -> y_pred
    (e.g. the output projection, run replicated after the pipeline).
    Returns step(params, opt_state, step_i, x, y) -> (params, opt_state, loss)
    where params = {"stages": stacked, "head": head_params or {}}.
    """

    def loss(params, x, y):
        h = pipe(params["stages"], x)
        pred = head_fn(params.get("head", {}), h) if head_fn is not None else h
        return loss_fn(pred, y)

    @jax.jit
    def step(params, opt_state, step_i, x, y):
        lval, grads = jax.value_and_grad(loss)(params, x, y)
        upd, opt_state = optimizer.update(grads, opt_state, params, step_i)
        params = jax.tree_util.tree_map(lambda p, d: p - d, params, upd)
        return params, opt_state, lval

    return step


# --------------------------------------------------------------------- r5
# Heterogeneous-stage pipeline: the conv-net setting (VERDICT r4 #4 — PP
# over ResNet-50's four stage groups, whose activation shapes and param
# structures all differ). GPipe above requires homogeneous stages; here
# activations travel the ppermute ring in ONE fixed-size flat buffer
# (padded to the largest inter-stage activation), and each device holds
# only ITS stage's parameters — packed into one row of a
# [n_stages, max_flat] float32 buffer sharded over "pipe" — unpacking
# them with static shapes inside its lax.switch branch. The schedule,
# differentiability-for-free (grad of ppermute = reverse rotation), and
# single-SPMD-program properties are the same as GPipe's.


def pack_stage_params(stage_params_list):
    """Pack heterogeneous per-stage param pytrees into ([S, Lmax] float32
    buffer, metadata for unpack). Row s holds stage s's raveled leaves
    (jax.flatten_util.ravel_pytree), zero-padded; sharding the buffer
    P("pipe") gives each device only its own stage's parameters."""
    from jax.flatten_util import ravel_pytree

    metas, vecs = [], []
    for p in stage_params_list:
        vec, unravel = ravel_pytree(p)
        metas.append((unravel, vec.dtype, int(vec.shape[0])))
        vecs.append(vec.astype(jnp.float32))
    lmax = max((v.shape[0] for v in vecs), default=0)
    packed = jnp.stack([jnp.pad(v, (0, lmax - v.shape[0])) for v in vecs])
    return packed, metas


def unpack_stage_params(row, meta):
    """Rebuild one stage's pytree from its packed row (static slice)."""
    unravel, dtype, size = meta
    return unravel(row[:size].astype(dtype))


def _hetero_local(packed, x, *, stage_fns, metas, shapes, n_micro, axis):
    """Per-device body. packed: [1, Lmax] (this stage's row); x: the full
    [B, ...] stage-0 input, replicated over "pipe". shapes[s] is the
    PER-MICROBATCH activation shape fed INTO stage s (shapes[S] = the
    pipeline's output shape)."""
    row = packed[0]
    n_stages = len(stage_fns)
    stage = lax.axis_index(axis)
    mb = x.shape[0] // n_micro
    flat = [int(np.prod((mb,) + tuple(s))) for s in shapes]
    bmax = max(flat)

    micro = x.reshape((n_micro, mb) + x.shape[1:])
    micro_buf = jnp.pad(micro.reshape(n_micro, flat[0]).astype(jnp.float32),
                        ((0, 0), (0, bmax - flat[0])))

    def branch(s):
        def f(buf):
            p = unpack_stage_params(row, metas[s])
            xin = buf[:flat[s]].reshape((mb,) + tuple(shapes[s]))
            y = stage_fns[s](p, xin)
            yf = y.reshape(-1).astype(jnp.float32)
            return jnp.pad(yf, (0, bmax - flat[s + 1]))
        return f

    branches = [branch(s) for s in range(n_stages)]

    carry0 = _pvary(jnp.zeros((bmax,), jnp.float32), (axis,))
    outs0 = _pvary(jnp.zeros((n_micro, flat[-1]), jnp.float32), (axis,))
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def body(t, state):
        carry, outs = state
        feed = lax.dynamic_index_in_dim(
            micro_buf, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        inp = jnp.where(stage == 0, feed, carry)
        out = lax.switch(stage, branches, inp)
        widx = t - (n_stages - 1)
        write = jnp.logical_and(stage == n_stages - 1, widx >= 0)
        prev = lax.dynamic_index_in_dim(
            outs, jnp.clip(widx, 0, n_micro - 1), 0, keepdims=False)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, out[:flat[-1]], prev),
            jnp.clip(widx, 0, n_micro - 1), 0)
        carry = lax.ppermute(out, axis, perm)
        return carry, outs

    total = n_micro + n_stages - 1
    _, outs = lax.fori_loop(0, total, body, (carry0, outs0))
    outs = lax.psum(jnp.where(stage == n_stages - 1, outs, 0), axis)
    return outs.reshape((n_micro * mb,) + tuple(shapes[-1]))


class HeteroPipe:
    """Microbatched pipeline over "pipe" with HETEROGENEOUS stages.

    stage_fns: list of ``fn(stage_params, x) -> y`` — arbitrary per-stage
    param structure and activation shapes. ``shapes``: per-microbatch-row
    activation shapes, shapes[s] = input of stage s (WITHOUT the batch
    dim), length n_stages + 1 (last = pipeline output). Params come from
    :func:`pack_stage_params`.

        packed, metas = pack_stage_params([p0, p1, p2, p3])
        pipe = HeteroPipe(stage_fns, metas, shapes, mesh, n_microbatches=4)
        y = pipe(packed, x)                   # pipelined forward
        jax.grad(...)                          # pipelined backward for free
    """

    def __init__(self, stage_fns, metas, shapes, mesh: DeviceMesh,
                 n_microbatches: int = 4, axis: str = "pipe"):
        if len(shapes) != len(stage_fns) + 1:
            raise ValueError(f"shapes must list n_stages+1 activation "
                             f"shapes, got {len(shapes)} for "
                             f"{len(stage_fns)} stages")
        self.stage_fns = list(stage_fns)
        self.metas = list(metas)
        self.shapes = [tuple(s) for s in shapes]
        self.mesh = mesh
        self.n_micro = n_microbatches
        self.axis = axis

    def __call__(self, packed, x):
        n_stages = self.mesh.shape[self.axis]
        if len(self.stage_fns) != n_stages:
            raise ValueError(f"{len(self.stage_fns)} stages but mesh "
                             f"'{self.axis}' axis has {n_stages}")
        if x.shape[0] % self.n_micro:
            raise ValueError(f"batch {x.shape[0]} not divisible by "
                             f"{self.n_micro} microbatches")
        fn = shard_map(
            functools.partial(_hetero_local, stage_fns=self.stage_fns,
                              metas=self.metas, shapes=self.shapes,
                              n_micro=self.n_micro, axis=self.axis),
            mesh=self.mesh.mesh,
            in_specs=(P(self.axis, None), P()),
            out_specs=P(),
            check_vma=False,
        )
        return fn(packed, x)

    def sequential_reference(self, packed, x):
        """Unpipelined equivalent (for parity tests)."""
        for s, fn in enumerate(self.stage_fns):
            p = unpack_stage_params(packed[s], self.metas[s])
            x = fn(p, x)
        return x


def graph_stage_fn(model, names, entry):
    """``stage_fn(stage_params, x)`` applying a ComputationGraph vertex
    subsequence in topological order (r5 — the ResNet-50 pipeline stages).

    ``names``: a contiguous topological slice whose only external
    dependency is ``entry`` (the previous stage's output vertex / graph
    input); returns the LAST name's activation. Network state (BN running
    stats) is closed over frozen — stage bodies run inference-mode
    normalization, the standard GPipe conv setting.
    """
    conf = model.conf
    state = model.state
    names = list(names)
    name_set = set(names)
    for n in names:
        for dep in conf.vertex_inputs.get(n, []):
            if dep not in name_set and dep != entry:
                raise ValueError(
                    f"stage vertex '{n}' depends on '{dep}' outside the "
                    f"stage (entry is '{entry}') — stages must be "
                    f"contiguous cuts of the graph")

    def stage_fn(stage_params, x):
        acts = {entry: x}
        for n in names:
            v = conf.vertices[n]
            ins = [acts[d] for d in conf.vertex_inputs.get(n, [])]
            if n in conf.preprocessors:
                ins = [conf.preprocessors[n](ins[0])]
            out, _ = v.apply(stage_params.get(n, {}), state.get(n, {}),
                             ins, train=False)
            acts[n] = out
        return acts[names[-1]]

    return stage_fn
