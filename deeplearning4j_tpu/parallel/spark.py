"""Spark-API compatibility shims.

Reference analog: deeplearning4j-scaleout/spark —
org.deeplearning4j.spark.impl.multilayer.SparkDl4jMultiLayer +
paramavg.ParameterAveragingTrainingMaster / SharedTrainingMaster. Those
classes exist because the reference needs Spark to place replicas on
executors and a parameter server to reconcile them. On TPU the SAME user
intent ("train this config across the cluster") is one SPMD program over the
mesh, so these shims keep the reference's surface (builder with
batchSizePerWorker / averagingFrequency) while delegating to ParallelWrapper
for model-level training — synchronous SPMD is exact averaging at frequency
1 with zero communication code.

The REAL averaging_frequency>1 semantics (K genuinely-local steps per
replica, then one parameter average — local SGD, which is NOT equivalent to
sync DP) live in parallel/param_averaging.ParameterAveragingTrainer; use it
directly when the reduced-communication algorithm itself is wanted.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.mesh import DeviceMesh


@dataclasses.dataclass
class ParameterAveragingTrainingMaster:
    """Config carrier (ParameterAveragingTrainingMaster.Builder analog)."""

    batch_size_per_worker: int = 32
    averaging_frequency: int = 1  # accepted; SPMD is exact averaging every step
    worker_prefetch_num_batches: int = 2

    class Builder:
        def __init__(self, rdd_data_set_num_examples: int = 1):
            self._batch = 32
            self._freq = 1
            self._prefetch = 2

        def batch_size_per_worker(self, n: int):
            self._batch = n
            return self

        def averaging_frequency(self, n: int):
            self._freq = n
            return self

        def worker_prefetch_num_batches(self, n: int):
            self._prefetch = n
            return self

        def build(self) -> "ParameterAveragingTrainingMaster":
            return ParameterAveragingTrainingMaster(
                batch_size_per_worker=self._batch,
                averaging_frequency=self._freq,
                worker_prefetch_num_batches=self._prefetch)


# SharedTrainingMaster (gradient sharing over Aeron) collapses to the same
# SPMD program; keep the name so reference users find it.
SharedTrainingMaster = ParameterAveragingTrainingMaster


class SparkDl4jMultiLayer:
    """SparkDl4jMultiLayer(sc, conf, trainingMaster) analog.

    The "SparkContext" slot takes a DeviceMesh (or None for all devices) —
    the mesh IS the cluster. fit() trains data-parallel over it.
    """

    def __init__(self, mesh: Optional[DeviceMesh], network_or_conf,
                 training_master: Optional[ParameterAveragingTrainingMaster] = None):
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(network_or_conf, MultiLayerConfiguration):
            self.network = MultiLayerNetwork(network_or_conf).init()
        else:
            self.network = network_or_conf
        self.training_master = training_master or ParameterAveragingTrainingMaster()
        self._wrapper = ParallelWrapper(
            self.network, mesh or DeviceMesh(),
            prefetch_buffer=self.training_master.worker_prefetch_num_batches)

    def fit(self, data, epochs: int = 1):
        """fit(rdd-like iterator of DataSets).

        The iterator is re-batched to batch_size_per_worker x data-parallel
        degree (the reference re-splits the RDD to batchSizePerWorker per
        executor; here the global SPMD batch is the per-worker size times the
        mesh's data axis)."""
        dp = self._wrapper.mesh.shape["data"]
        global_batch = self.training_master.batch_size_per_worker * dp
        self._wrapper.fit(_RebatchingIterator(data, global_batch, dp),
                          epochs=epochs)
        return self.network

    def get_network(self):
        return self.network


class _RebatchingIterator:
    """Re-batches an iterator of DataSets to a fixed global batch size
    (like the reference's RDD repartitioning), preserving feature masks.

    The tail that doesn't fill a whole global batch is NOT dropped: it is
    flushed truncated down to the largest multiple of the data-parallel
    degree, so small datasets still train (only examples that can't shard
    evenly are lost)."""

    def __init__(self, source, batch_size: int, dp: int = 1):
        self._source = source
        self._batch = batch_size
        self._dp = max(1, dp)

    def reset(self):
        if hasattr(self._source, "reset"):
            self._source.reset()

    def __iter__(self):
        import numpy as np

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.multilayer import _unpack

        feats, labels, masks = [], [], []
        have, any_mask, any_unmasked = 0, False, False

        def _cat(n):
            fx = np.concatenate(feats)
            fy = np.concatenate(labels)
            fm = np.concatenate(masks) if any_mask else None
            return (DataSet(fx[:n], fy[:n],
                            None if fm is None else fm[:n]),
                    fx[n:], fy[n:], None if fm is None else fm[n:])

        for ds in self._source:
            x, y, mask = _unpack(ds)
            feats.append(np.asarray(x))
            labels.append(np.asarray(y))
            if mask is not None:
                any_mask = True
                masks.append(np.asarray(mask))
            else:
                any_unmasked = True
            if any_mask and any_unmasked:
                raise ValueError("mixed masked/unmasked DataSets in one stream")
            have += feats[-1].shape[0]
            while have >= self._batch:
                out, fx, fy, fm = _cat(self._batch)
                yield out
                feats, labels = [fx], [fy]
                masks = [fm] if fm is not None else []
                have = fx.shape[0]
        tail = (have // self._dp) * self._dp
        if tail:
            out, _, _, _ = _cat(tail)
            yield out


class SparkComputationGraph(SparkDl4jMultiLayer):
    """SparkComputationGraph analog — same collapse, graph models."""

    def __init__(self, mesh, network_or_conf, training_master=None):
        from deeplearning4j_tpu.nn.conf.builders import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(network_or_conf, ComputationGraphConfiguration):
            network_or_conf = ComputationGraph(network_or_conf).init()
        super().__init__(mesh, network_or_conf, training_master)
