"""Spark-API compatibility shims.

Reference analog: deeplearning4j-scaleout/spark —
org.deeplearning4j.spark.impl.multilayer.SparkDl4jMultiLayer +
paramavg.ParameterAveragingTrainingMaster / SharedTrainingMaster. Those
classes exist because the reference needs Spark to place replicas on
executors and a parameter server to reconcile them. On TPU the SAME user
intent ("train this config across the cluster") is one SPMD program over the
mesh, so these shims keep the reference's surface (builder with
batchSizePerWorker / averagingFrequency) while delegating to ParallelWrapper
for model-level training — synchronous SPMD is exact averaging at frequency
1 with zero communication code.

averaging_frequency > 1 is HONORED (r3): fit() routes to
parallel/param_averaging.ParameterAveragingTrainer — K genuinely-local
steps per replica, then one parameter average (local SGD, NOT equivalent
to sync DP) — over the model's functional loss (MultiLayerNetwork
.as_loss_fn), and writes the averaged parameters back into the network.
averaging_frequency == 1 stays on the plain SPMD ParallelWrapper path
(sync DP IS exact averaging every step, with the model's own fused
updater inside the jitted step).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.mesh import DeviceMesh


@dataclasses.dataclass
class ParameterAveragingTrainingMaster:
    """Config carrier (ParameterAveragingTrainingMaster.Builder analog)."""

    batch_size_per_worker: int = 32
    averaging_frequency: int = 1  # >1 routes fit() to real local SGD
    worker_prefetch_num_batches: int = 2

    class Builder:
        def __init__(self, rdd_data_set_num_examples: int = 1):
            self._batch = 32
            self._freq = 1
            self._prefetch = 2

        def batch_size_per_worker(self, n: int):
            self._batch = n
            return self

        def averaging_frequency(self, n: int):
            self._freq = n
            return self

        def worker_prefetch_num_batches(self, n: int):
            self._prefetch = n
            return self

        def build(self) -> "ParameterAveragingTrainingMaster":
            return ParameterAveragingTrainingMaster(
                batch_size_per_worker=self._batch,
                averaging_frequency=self._freq,
                worker_prefetch_num_batches=self._prefetch)


# SharedTrainingMaster (gradient sharing over Aeron) collapses to the same
# SPMD program; keep the name so reference users find it.
SharedTrainingMaster = ParameterAveragingTrainingMaster


class SparkDl4jMultiLayer:
    """SparkDl4jMultiLayer(sc, conf, trainingMaster) analog.

    The "SparkContext" slot takes a DeviceMesh (or None for all devices) —
    the mesh IS the cluster. fit() trains data-parallel over it.
    """

    def __init__(self, mesh: Optional[DeviceMesh], network_or_conf,
                 training_master: Optional[ParameterAveragingTrainingMaster] = None):
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(network_or_conf, MultiLayerConfiguration):
            self.network = MultiLayerNetwork(network_or_conf).init()
        else:
            self.network = network_or_conf
        self.training_master = training_master or ParameterAveragingTrainingMaster()
        self._wrapper = ParallelWrapper(
            self.network, mesh or DeviceMesh(),
            prefetch_buffer=self.training_master.worker_prefetch_num_batches)

    def fit(self, data, epochs: int = 1):
        """fit(rdd-like iterator of DataSets).

        The iterator is re-batched to batch_size_per_worker x data-parallel
        degree (the reference re-splits the RDD to batchSizePerWorker per
        executor; here the global SPMD batch is the per-worker size times the
        mesh's data axis). averaging_frequency > 1 runs the real local-SGD
        algorithm (see module docstring)."""
        dp = self._wrapper.mesh.shape["data"]
        global_batch = self.training_master.batch_size_per_worker * dp
        K = int(self.training_master.averaging_frequency)
        if K <= 1:
            self._wrapper.fit(_RebatchingIterator(data, global_batch, dp),
                              epochs=epochs)
            return self.network
        return self._fit_local_sgd(data, epochs, global_batch, dp, K)

    def _fit_local_sgd(self, data, epochs, global_batch, dp, K):
        import warnings

        import numpy as np

        from deeplearning4j_tpu.nn.multilayer import _unpack
        from deeplearning4j_tpu.parallel.param_averaging import (
            ParameterAveragingTrainer,
        )

        self._check_local_sgd_supported(K)
        # r4: the stateful functional surface — BN running stats and the
        # dropout rng thread through, so those configs train here now.
        # r5: the trainer carries the NETWORK'S OWN updater selection
        # (NoOp for frozen layers, per-layer overrides, global default)
        # via PerEntryUpdater, plus conf.max_grad_norm clipping — so
        # transfer-learning configs and clipped models train here too
        from deeplearning4j_tpu.optimize.updaters import PerEntryUpdater

        loss_fn, (params0, state0) = self.network.as_loss_fn(train=True)
        net_ups = self.network._updaters
        per_entry = (dict(net_ups) if isinstance(net_ups, dict)
                     else list(net_ups))
        from deeplearning4j_tpu.optimize.updaters import NoOp

        # frozen entries never diverge: skip their averaging collective
        # so they stay bit-identical through local SGD
        skip = ({k: isinstance(u, NoOp) for k, u in per_entry.items()}
                if isinstance(per_entry, dict)
                else [isinstance(u, NoOp) for u in per_entry])
        trainer = ParameterAveragingTrainer(
            loss_fn, PerEntryUpdater(per_entry), self._wrapper.mesh.mesh,
            averaging_frequency=K, stateful=True,
            max_grad_norm=getattr(self.network.conf, "max_grad_norm", 0.0),
            skip_average=skip)
        carry = trainer.init(params0, state=state0,
                             rng=self.network._next_key())
        # one averaging round consumes K global batches; the accumulator
        # carries ACROSS epoch boundaries (a small dataset may hold fewer
        # than K batches per epoch — rounds must still complete, exactly
        # like the reference master carrying its iteration count across
        # RDD passes)
        conf = self.network.conf
        # the multi path serves ComputationGraphs fed MultiDataSets —
        # dispatch on the STREAM's shape, not just graph arity (a
        # 1-in/1-out graph legitimately trains from MultiDataSet RDDs in
        # the reference's SparkComputationGraph, and the DataSet rebatcher
        # would silently mis-shard its list-of-arrays features)
        multi = False
        if not hasattr(self.network, "layers"):     # ComputationGraph
            multi = (len(conf.network_inputs) > 1
                     or len(conf.network_outputs) > 1)
            if not multi:
                peek = next(iter(data), None)
                multi = isinstance(getattr(peek, "features", None),
                                   (list, tuple, dict))
                if hasattr(data, "reset"):
                    data.reset()
        if multi:
            carry, have, dropped_tail = self._run_multi_rounds(
                data, epochs, global_batch, K, trainer, carry)
        else:
            xs, ys, ms, lms, have = [], [], [], [], 0
            dropped_tail = 0
            for _ in range(epochs):
                for ds in _RebatchingIterator(data, global_batch, dp):
                    if ds.features.shape[0] != global_batch:
                        # rounds reshape into K x (global_batch/dp)
                        # microbatch shards; a truncated tail would
                        # mis-shard the whole round, so it is dropped
                        # (counted + warned below)
                        dropped_tail += ds.features.shape[0]
                        continue
                    # r5: masked DataSets ride the rounds — as_loss_fn
                    # takes (mask, label_mask) and normalizes each local
                    # step by its shard's valid count. _unpack gives
                    # fit_batch's canonical routing (a labels-only mask
                    # plays both roles); the rebatcher enforces an
                    # all-masked-or-none stream, so presence is uniform
                    # across rounds
                    x_, y_, m_, lm_ = _unpack(ds)
                    xs.append(np.asarray(x_))
                    ys.append(np.asarray(y_))
                    if m_ is not None:
                        ms.append(np.asarray(m_))
                    if lm_ is not None:
                        lms.append(np.asarray(lm_))
                    have += 1
                    if have == K:
                        carry, loss = trainer.fit_round(
                            carry, np.concatenate(xs), np.concatenate(ys),
                            mask=np.concatenate(ms) if ms else None,
                            label_mask=np.concatenate(lms) if lms else None)
                        self.network.score_value = float(loss)
                        xs, ys, ms, lms, have = [], [], [], [], 0
                if hasattr(data, "reset"):
                    data.reset()
        if have or dropped_tail:
            warnings.warn(
                f"local-SGD fit dropped {have} trailing batch(es) that did "
                f"not fill an averaging round of {K} and {dropped_tail} "
                f"tail example(s) that did not fill a global batch; size "
                f"the dataset/epochs accordingly for full coverage")
        # averaged parameters AND network state (BN running stats, r4)
        # flow back into the model (the reference's post-fit network
        # state: the master serializes PARAMS; updater moments restart
        # fresh, so re-init the model's own opt state to match the new
        # params rather than leaving stale moments)
        self.network.params = trainer.params(carry)
        self.network.state = trainer.state(carry)
        ups = self.network._updaters
        if isinstance(self.network.params, dict):   # ComputationGraph
            self.network.opt_state = {
                n: ups[n].init_state(p)
                for n, p in self.network.params.items()}
        else:                                        # MultiLayerNetwork
            self.network.opt_state = [
                u.init_state(p) for u, p in zip(ups, self.network.params)]
        return self.network

    def _run_multi_rounds(self, data, epochs, global_batch, K, trainer,
                          carry):
        """r5: MULTI-input/-output ComputationGraph local SGD (reference:
        SparkComputationGraph trains MultiDataSet RDDs). The stream's
        MultiDataSets are pooled per slot and re-cut into global batches;
        each round ships dict x/y keyed by the graph's input/output names
        through the same trainer (fit_round accepts pytrees). Masked
        MultiDataSets are rejected with guidance — multi-output mask
        routing lives in the fit path. Returns (carry, pending_batches,
        dropped_rows)."""
        import numpy as np

        conf = self.network.conf
        in_names = list(conf.network_inputs)
        out_names = list(conf.network_outputs)
        pool_x = [[] for _ in in_names]
        pool_y = [[] for _ in out_names]
        pooled = 0
        round_x, round_y, have = [], [], 0

        def slots(arrs, names, what):
            if isinstance(arrs, dict):
                return [np.asarray(arrs[n]) for n in names]
            arrs = list(arrs)
            if len(arrs) != len(names):
                raise ValueError(f"MultiDataSet carries {len(arrs)} {what} "
                                 f"arrays; the graph has {len(names)}")
            return [np.asarray(a) for a in arrs]

        def pop_global_batch():
            nonlocal pooled
            cx = [np.concatenate(p) if len(p) > 1 else p[0] for p in pool_x]
            cy = [np.concatenate(p) if len(p) > 1 else p[0] for p in pool_y]
            for i, a in enumerate(cx):
                pool_x[i] = [a[global_batch:]]
            for i, a in enumerate(cy):
                pool_y[i] = [a[global_batch:]]
            pooled -= global_batch
            return ([a[:global_batch] for a in cx],
                    [a[:global_batch] for a in cy])

        for _ in range(epochs):
            for ds in data:
                if (getattr(ds, "features_mask", None) is not None
                        or getattr(ds, "labels_mask", None) is not None):
                    raise NotImplementedError(
                        "masked MultiDataSets are not supported on the "
                        "local-SGD path; fit the ComputationGraph "
                        "directly (fit_batch routes per-output masks)")
                fa = slots(ds.features, in_names, "feature")
                la = slots(ds.labels, out_names, "label")
                for i, a in enumerate(fa):
                    pool_x[i].append(a)
                for i, a in enumerate(la):
                    pool_y[i].append(a)
                pooled += fa[0].shape[0]
                while pooled >= global_batch:
                    gx, gy = pop_global_batch()
                    round_x.append(gx)
                    round_y.append(gy)
                    have += 1
                    if have == K:
                        x_dict = {n: np.concatenate([r[i] for r in round_x])
                                  for i, n in enumerate(in_names)}
                        y_dict = {n: np.concatenate([r[i] for r in round_y])
                                  for i, n in enumerate(out_names)}
                        carry, loss = trainer.fit_round(carry, x_dict,
                                                        y_dict)
                        self.network.score_value = float(loss)
                        round_x, round_y, have = [], [], 0
            if hasattr(data, "reset"):
                data.reset()
        return carry, have, pooled

    def _check_local_sgd_supported(self, K):
        """The K>1 path optimizes the model through its FUNCTIONAL loss
        (as_loss_fn). r4: that surface threads (state, rng) and includes
        l1/l2 terms, so BatchNorm, dropout and regularization train here.
        r5: the trainer carries the network's per-entry updater selection
        (PerEntryUpdater: NoOp for frozen layers, per-layer overrides)
        and conf.max_grad_norm clipping, so transfer-learning and clipped
        configs train here too; multi-input/-output graphs ride dict
        rounds (_run_multi_rounds). What remains rejected is center loss
        (centers state and the center term live in the fit path) and
        MASKED MultiDataSets (multi-output mask routing lives in the fit
        path)."""
        net = self.network
        conf = net.conf
        problems = []
        if hasattr(net, "layers"):           # MultiLayerNetwork
            named = [(str(i), l) for i, l in enumerate(net.layers)]
        else:                                # ComputationGraph
            from deeplearning4j_tpu.nn.conf.graph import LayerVertex

            named = [(n, v.layer) for n, v in conf.vertices.items()
                     if isinstance(v, LayerVertex)]
        for i, l in named:
            if type(l).__name__ == "CenterLossOutputLayer":
                problems.append(f"layer {i} center loss (centers state "
                                "and center term need the fit path)")
        if problems:
            raise NotImplementedError(
                "averaging_frequency>1 routes through the functional "
                "local-SGD trainer, which does not carry: "
                + "; ".join(problems)
                + ". Use averaging_frequency=1 (exact sync averaging) or "
                "parallel.ParameterAveragingTrainer with a custom loss.")

    def get_network(self):
        return self.network


class _RebatchingIterator:
    """Re-batches an iterator of DataSets to a fixed global batch size
    (like the reference's RDD repartitioning), preserving feature masks.

    The tail that doesn't fill a whole global batch is NOT dropped: it is
    flushed truncated down to the largest multiple of the data-parallel
    degree, so small datasets still train (only examples that can't shard
    evenly are lost)."""

    def __init__(self, source, batch_size: int, dp: int = 1):
        self._source = source
        self._batch = batch_size
        self._dp = max(1, dp)

    def reset(self):
        if hasattr(self._source, "reset"):
            self._source.reset()

    def __iter__(self):
        import numpy as np

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.multilayer import _unpack

        feats, labels, masks, lmasks = [], [], [], []
        have, any_mask, any_unmasked = 0, False, False
        any_lmask, any_no_lmask = False, False

        def _cat(n):
            fx = np.concatenate(feats)
            fy = np.concatenate(labels)
            fm = np.concatenate(masks) if any_mask else None
            lm = np.concatenate(lmasks) if any_lmask else None
            return (DataSet(fx[:n], fy[:n],
                            None if fm is None else fm[:n],
                            None if lm is None else lm[:n]),
                    fx[n:], fy[n:],
                    None if fm is None else fm[n:],
                    None if lm is None else lm[n:])

        for ds in self._source:
            x, y, mask, lmask = _unpack(ds)
            if isinstance(lmask, (list, tuple, dict)):
                # the r5 per-output MultiDataSet shape: np.asarray would
                # stack it [n_out, B, T] and the batch-axis slicing below
                # would silently corrupt it
                raise ValueError(
                    "per-output labels masks (list/dict) are not supported "
                    "on the spark re-batching path; use a single labels "
                    "mask array or fit the ComputationGraph directly")
            feats.append(np.asarray(x))
            labels.append(np.asarray(y))
            if mask is not None:
                any_mask = True
                masks.append(np.asarray(mask))
            else:
                any_unmasked = True
            if lmask is not None:
                any_lmask = True
                lmasks.append(np.asarray(lmask))
            else:
                any_no_lmask = True
            if any_lmask and any_no_lmask:
                raise ValueError("mixed labels-masked/unmasked DataSets "
                                 "in one stream")
            if any_mask and any_unmasked:
                raise ValueError("mixed masked/unmasked DataSets in one stream")
            have += feats[-1].shape[0]
            while have >= self._batch:
                out, fx, fy, fm, lm = _cat(self._batch)
                yield out
                feats, labels = [fx], [fy]
                masks = [fm] if fm is not None else []
                lmasks = [lm] if lm is not None else []
                have = fx.shape[0]
        tail = (have // self._dp) * self._dp
        if tail:
            out, _, _, _, _ = _cat(tail)
            yield out


class SparkComputationGraph(SparkDl4jMultiLayer):
    """SparkComputationGraph analog — same collapse, graph models."""

    def __init__(self, mesh, network_or_conf, training_master=None):
        from deeplearning4j_tpu.nn.conf.builders import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(network_or_conf, ComputationGraphConfiguration):
            network_or_conf = ComputationGraph(network_or_conf).init()
        super().__init__(mesh, network_or_conf, training_master)
