"""Spark-API compatibility shims.

Reference analog: deeplearning4j-scaleout/spark —
org.deeplearning4j.spark.impl.multilayer.SparkDl4jMultiLayer +
paramavg.ParameterAveragingTrainingMaster / SharedTrainingMaster. Those
classes exist because the reference needs Spark to place replicas on
executors and a parameter server to reconcile them. On TPU the SAME user
intent ("train this config across the cluster") is one SPMD program over the
mesh, so these shims keep the reference's surface (builder with
batchSizePerWorker / averagingFrequency) while delegating to ParallelWrapper
for model-level training — synchronous SPMD is exact averaging at frequency
1 with zero communication code.

averaging_frequency > 1 is HONORED (r3): fit() routes to
parallel/param_averaging.ParameterAveragingTrainer — K genuinely-local
steps per replica, then one parameter average (local SGD, NOT equivalent
to sync DP) — over the model's functional loss (MultiLayerNetwork
.as_loss_fn), and writes the averaged parameters back into the network.
averaging_frequency == 1 stays on the plain SPMD ParallelWrapper path
(sync DP IS exact averaging every step, with the model's own fused
updater inside the jitted step).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
from deeplearning4j_tpu.parallel.mesh import DeviceMesh


@dataclasses.dataclass
class ParameterAveragingTrainingMaster:
    """Config carrier (ParameterAveragingTrainingMaster.Builder analog).

    ``straggler_timeout_s`` (> 0 enables it) is the per-round straggler
    budget for K>1 local SGD: a worker whose round overruns it has its
    contribution dropped and the average renormalized over the survivors
    (it re-enters synced the next round). 0 keeps the classic behavior —
    every round waits for every worker."""

    batch_size_per_worker: int = 32
    averaging_frequency: int = 1  # >1 routes fit() to real local SGD
    worker_prefetch_num_batches: int = 2
    straggler_timeout_s: float = 0.0

    class Builder:
        def __init__(self, rdd_data_set_num_examples: int = 1):
            self._batch = 32
            self._freq = 1
            self._prefetch = 2
            self._straggler = 0.0

        def batch_size_per_worker(self, n: int):
            self._batch = n
            return self

        def averaging_frequency(self, n: int):
            self._freq = n
            return self

        def worker_prefetch_num_batches(self, n: int):
            self._prefetch = n
            return self

        def straggler_timeout_s(self, s: float):
            self._straggler = float(s)
            return self

        def build(self) -> "ParameterAveragingTrainingMaster":
            return ParameterAveragingTrainingMaster(
                batch_size_per_worker=self._batch,
                averaging_frequency=self._freq,
                worker_prefetch_num_batches=self._prefetch,
                straggler_timeout_s=self._straggler)


class RoundSupervisor:
    """Host-side failure detector for local-SGD rounds.

    In-process SPMD has no per-worker heartbeats — one program either runs
    or doesn't — so the failure SIGNAL comes from the fault plan
    (``worker_crash`` / ``collective_delay``), standing in for the
    coordination-service heartbeat a real pod controller watches. The
    RESPONSE is real and fully exercised: the flagged replica's
    contribution is dropped from the round, the average renormalizes over
    survivors (ParameterAveragingTrainer's elastic round), and the worker
    is re-admitted — synced to the survivor average — the round after its
    fault clears. Every action lands in
    ``dl4j_recovery_total{component="localsgd"}``.
    """

    def __init__(self, dp: int, straggler_timeout_s: float = 0.0):
        self.dp = max(1, int(dp))
        self.timeout_s = float(straggler_timeout_s)
        self.round = 0
        self._lost_last: set = set()
        self.dropped = 0
        self.readmitted = 0

    def _record(self, outcome: str, n: int = 1):
        from deeplearning4j_tpu import monitoring

        mon = monitoring.recovery_monitor()
        if mon is not None:
            mon.recovery_total.labels(component="localsgd",
                                      outcome=outcome).inc(n)

    def lost_for_round(self):
        """Consult the fault plan for this round; returns the sorted list
        of replica indices to drop (usually empty)."""
        import time as _time

        from deeplearning4j_tpu import faults

        lost = set()
        plan = faults.active()
        if plan is not None:
            rnd = self.round
            if plan.fires("worker_crash", round=rnd):
                lost.add(rnd % self.dp)        # deterministic victim
                self._record("dropped_worker")
            if plan.fires("collective_delay", round=rnd):
                victim = (rnd + 1) % self.dp
                if self.timeout_s > 0 and plan.delay_s > self.timeout_s:
                    # the straggler overran the round budget: survivors
                    # wait only the budget, then drop its contribution
                    _time.sleep(self.timeout_s)
                    lost.add(victim)
                    self._record("dropped_straggler")
                else:
                    # no budget (or within it): the whole round waits —
                    # exactly the stall the timeout exists to bound
                    _time.sleep(plan.delay_s)
        back = self._lost_last - lost
        if back:
            self.readmitted += len(back)
            self._record("readmitted", len(back))
        self.dropped += len(lost)
        self._lost_last = set(lost)
        self.round += 1
        return sorted(lost)


# SharedTrainingMaster (gradient sharing over Aeron) collapses to the same
# SPMD program; keep the name so reference users find it.
SharedTrainingMaster = ParameterAveragingTrainingMaster


class SparkDl4jMultiLayer:
    """SparkDl4jMultiLayer(sc, conf, trainingMaster) analog.

    The "SparkContext" slot takes a DeviceMesh (or None for all devices) —
    the mesh IS the cluster. fit() trains data-parallel over it.
    """

    def __init__(self, mesh: Optional[DeviceMesh], network_or_conf,
                 training_master: Optional[ParameterAveragingTrainingMaster] = None):
        from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(network_or_conf, MultiLayerConfiguration):
            self.network = MultiLayerNetwork(network_or_conf).init()
        else:
            self.network = network_or_conf
        self.training_master = training_master or ParameterAveragingTrainingMaster()
        self._wrapper = ParallelWrapper(
            self.network, mesh or DeviceMesh(),
            prefetch_buffer=self.training_master.worker_prefetch_num_batches)

    def fit(self, data, epochs: int = 1):
        """fit(rdd-like iterator of DataSets).

        The iterator is re-batched to batch_size_per_worker x data-parallel
        degree (the reference re-splits the RDD to batchSizePerWorker per
        executor; here the global SPMD batch is the per-worker size times the
        mesh's data axis). averaging_frequency > 1 runs the real local-SGD
        algorithm (see module docstring)."""
        dp = self._wrapper.mesh.shape["data"]
        global_batch = self.training_master.batch_size_per_worker * dp
        K = int(self.training_master.averaging_frequency)
        if K <= 1:
            # a MultiDataSet stream needs the slot-aware rebatcher — the
            # DataSet one would np.asarray a LIST of feature arrays into
            # a stacked mess (r5)
            multi, data = self._peek_multi(data)
            rebatcher = (_RebatchingMultiIterator if multi
                         else _RebatchingIterator)
            self._wrapper.fit(rebatcher(data, global_batch, dp),
                              epochs=epochs)
            return self.network
        return self._fit_local_sgd(data, epochs, global_batch, dp, K)

    def _peek_multi(self, data):
        """(is_multidataset_stream, stream) — peeks the first item of a
        ComputationGraph stream without losing it: resettable sources are
        reset; one-shot generators get the peeked item stitched back
        (MultiLayerNetwork streams can never be multi, so they are not
        peeked at all)."""
        if hasattr(self.network, "layers"):          # MultiLayerNetwork
            return False, data
        it = iter(data)
        peek = next(it, None)
        multi = isinstance(getattr(peek, "features", None),
                           (list, tuple, dict))
        if hasattr(data, "reset"):
            data.reset()
            return multi, data
        if peek is None:
            return multi, data
        import itertools

        return multi, itertools.chain([peek], it)

    def _fit_local_sgd(self, data, epochs, global_batch, dp, K):
        import warnings

        import numpy as np

        from deeplearning4j_tpu.nn.multilayer import _unpack
        from deeplearning4j_tpu.parallel.param_averaging import (
            ParameterAveragingTrainer,
        )

        self._check_local_sgd_supported(K)
        # r4: the stateful functional surface — BN running stats and the
        # dropout rng thread through, so those configs train here now.
        # r5: the trainer carries the NETWORK'S OWN updater selection
        # (NoOp for frozen layers, per-layer overrides, global default)
        # via PerEntryUpdater, plus conf.max_grad_norm clipping — so
        # transfer-learning configs and clipped models train here too
        from deeplearning4j_tpu.optimize.updaters import PerEntryUpdater

        loss_fn, (params0, state0) = self.network.as_loss_fn(train=True)
        net_ups = self.network._updaters
        per_entry = (dict(net_ups) if isinstance(net_ups, dict)
                     else list(net_ups))
        from deeplearning4j_tpu.optimize.updaters import NoOp

        # frozen entries never diverge: skip their averaging collective
        # so they stay bit-identical through local SGD
        skip = ({k: isinstance(u, NoOp) for k, u in per_entry.items()}
                if isinstance(per_entry, dict)
                else [isinstance(u, NoOp) for u in per_entry])
        trainer = ParameterAveragingTrainer(
            loss_fn, PerEntryUpdater(per_entry), self._wrapper.mesh.mesh,
            averaging_frequency=K, stateful=True,
            max_grad_norm=getattr(self.network.conf, "max_grad_norm", 0.0),
            skip_average=skip)
        carry = trainer.init(params0, state=state0,
                             rng=self.network._next_key())
        # one averaging round consumes K global batches; the accumulator
        # carries ACROSS epoch boundaries (a small dataset may hold fewer
        # than K batches per epoch — rounds must still complete, exactly
        # like the reference master carrying its iteration count across
        # RDD passes)
        conf = self.network.conf
        supervisor = RoundSupervisor(
            dp, self.training_master.straggler_timeout_s)
        self._round_supervisor = supervisor     # introspectable post-fit
        # the multi path serves ComputationGraphs fed MultiDataSets —
        # dispatch on the STREAM's shape, not just graph arity (a
        # 1-in/1-out graph legitimately trains from MultiDataSet RDDs in
        # the reference's SparkComputationGraph, and the DataSet rebatcher
        # would silently mis-shard its list-of-arrays features)
        multi = False
        if not hasattr(self.network, "layers"):     # ComputationGraph
            multi = (len(conf.network_inputs) > 1
                     or len(conf.network_outputs) > 1)
            if not multi:
                multi, data = self._peek_multi(data)
        if multi:
            carry, have, dropped_tail = self._run_multi_rounds(
                data, epochs, global_batch, K, trainer, carry, supervisor)
        else:
            xs, ys, ms, lms, have = [], [], [], [], 0
            dropped_tail = 0
            for _ in range(epochs):
                for ds in _RebatchingIterator(data, global_batch, dp):
                    if ds.features.shape[0] != global_batch:
                        # rounds reshape into K x (global_batch/dp)
                        # microbatch shards; a truncated tail would
                        # mis-shard the whole round, so it is dropped
                        # (counted + warned below)
                        dropped_tail += ds.features.shape[0]
                        continue
                    # r5: masked DataSets ride the rounds — as_loss_fn
                    # takes (mask, label_mask) and normalizes each local
                    # step by its shard's valid count. _unpack gives
                    # fit_batch's canonical routing (a labels-only mask
                    # plays both roles); the rebatcher enforces an
                    # all-masked-or-none stream, so presence is uniform
                    # across rounds
                    x_, y_, m_, lm_ = _unpack(ds)
                    xs.append(np.asarray(x_))
                    ys.append(np.asarray(y_))
                    if m_ is not None:
                        ms.append(np.asarray(m_))
                    if lm_ is not None:
                        lms.append(np.asarray(lm_))
                    have += 1
                    if have == K:
                        carry, loss = trainer.fit_round(
                            carry, np.concatenate(xs), np.concatenate(ys),
                            mask=np.concatenate(ms) if ms else None,
                            label_mask=np.concatenate(lms) if lms else None,
                            lost=supervisor.lost_for_round() or None)
                        self.network.score_value = float(loss)
                        xs, ys, ms, lms, have = [], [], [], [], 0
                if hasattr(data, "reset"):
                    data.reset()
        if have or dropped_tail:
            # one unit on both the single and multi paths: ROWS. `have`
            # counts pooled global batches stranded in an incomplete
            # round; `dropped_tail` already counts rows that never filled
            # a global batch (ADVICE r5 — the old message mixed units)
            dropped_rows = have * global_batch + dropped_tail
            from deeplearning4j_tpu import monitoring

            mon = monitoring.localsgd_monitor()
            if mon is not None:
                mon.dropped_rows.inc(dropped_rows)
            warnings.warn(
                f"local-SGD fit dropped {dropped_rows} sample row(s): "
                f"{have} pooled global batch(es) ({have * global_batch} "
                f"rows) stranded short of an averaging round of {K}, plus "
                f"{dropped_tail} tail row(s) that did not fill a global "
                f"batch; size the dataset/epochs accordingly for full "
                f"coverage")
        # averaged parameters AND network state (BN running stats, r4)
        # flow back into the model (the reference's post-fit network
        # state: the master serializes PARAMS; updater moments restart
        # fresh, so re-init the model's own opt state to match the new
        # params rather than leaving stale moments)
        self.network.params = trainer.params(carry)
        self.network.state = trainer.state(carry)
        ups = self.network._updaters
        if isinstance(self.network.params, dict):   # ComputationGraph
            self.network.opt_state = {
                n: ups[n].init_state(p)
                for n, p in self.network.params.items()}
        else:                                        # MultiLayerNetwork
            self.network.opt_state = [
                u.init_state(p) for u, p in zip(ups, self.network.params)]
        return self.network

    def _run_multi_rounds(self, data, epochs, global_batch, K, trainer,
                          carry, supervisor):
        """r5: MULTI-input/-output ComputationGraph local SGD (reference:
        SparkComputationGraph trains MultiDataSet RDDs). The stream runs
        through _RebatchingMultiIterator (same pooling the K=1 path
        uses); each round ships dict x/y keyed by the graph's
        input/output names through the same trainer (fit_round accepts
        pytrees), with the shared features mask and a single-array labels
        mask riding along. Per-output labels-mask lists/dicts are
        rejected by the rebatcher (that routing lives in the fit path).
        Returns (carry, pending_batches, dropped_rows)."""
        import numpy as np

        conf = self.network.conf
        in_names = list(conf.network_inputs)
        out_names = list(conf.network_outputs)

        def named(arrs, names, what):
            if isinstance(arrs, dict):
                return {n: np.asarray(arrs[n]) for n in names}
            arrs = list(arrs)
            if len(arrs) != len(names):
                raise ValueError(f"MultiDataSet carries {len(arrs)} {what} "
                                 f"arrays; the graph has {len(names)}")
            return dict(zip(names, (np.asarray(a) for a in arrs)))

        class _Epochs:
            """Chain the source's epochs into ONE stream so the rebatcher
            pools rows ACROSS epoch boundaries (a small dataset's partial
            batches still complete rounds — the r4 accumulator-across-
            epochs semantics)."""

            def __iter__(self):
                for e in range(epochs):
                    yield from data
                    if hasattr(data, "reset") and e + 1 < epochs:
                        data.reset()

        round_x, round_y, round_m, round_lm, have = [], [], [], [], 0
        # dp=global_batch: the K>1 round needs EXACT global batches (a
        # truncated tail would mis-shard the whole round), so the
        # rebatcher's tail flush is told to emit only full ones
        rebatcher = _RebatchingMultiIterator(_Epochs(), global_batch,
                                             dp=global_batch)
        for mds in rebatcher:
            round_x.append(named(mds.features, in_names, "feature"))
            round_y.append(named(mds.labels, out_names, "label"))
            if mds.features_mask is not None:
                round_m.append(np.asarray(mds.features_mask))
            if mds.labels_mask is not None:
                round_lm.append(np.asarray(mds.labels_mask))
            have += 1
            if have == K:
                x_dict = {n: np.concatenate([r[n] for r in round_x])
                          for n in in_names}
                y_dict = {n: np.concatenate([r[n] for r in round_y])
                          for n in out_names}
                carry, loss = trainer.fit_round(
                    carry, x_dict, y_dict,
                    mask=(np.concatenate(round_m) if round_m
                          else None),
                    label_mask=(np.concatenate(round_lm) if round_lm
                                else None),
                    lost=supervisor.lost_for_round() or None)
                self.network.score_value = float(loss)
                round_x, round_y, round_m, round_lm, have = \
                    [], [], [], [], 0
        return carry, have, getattr(rebatcher, "dropped_rows", 0)

    def _check_local_sgd_supported(self, K):
        """The K>1 path optimizes the model through its FUNCTIONAL loss
        (as_loss_fn). r4: that surface threads (state, rng) and includes
        l1/l2 terms, so BatchNorm, dropout and regularization train here.
        r5: the trainer carries the network's per-entry updater selection
        (PerEntryUpdater: NoOp for frozen layers, per-layer overrides)
        and conf.max_grad_norm clipping, so transfer-learning and clipped
        configs train here too; multi-input/-output graphs ride dict
        rounds (_run_multi_rounds), including shared-features-mask /
        single-labels-mask MultiDataSets. What remains rejected is
        center loss (centers state and the center term live in the fit
        path) and PER-OUTPUT labels-mask lists/dicts (that routing lives
        in the fit path)."""
        net = self.network
        conf = net.conf
        problems = []
        if hasattr(net, "layers"):           # MultiLayerNetwork
            named = [(str(i), l) for i, l in enumerate(net.layers)]
        else:                                # ComputationGraph
            from deeplearning4j_tpu.nn.conf.graph import LayerVertex

            named = [(n, v.layer) for n, v in conf.vertices.items()
                     if isinstance(v, LayerVertex)]
        for i, l in named:
            if type(l).__name__ == "CenterLossOutputLayer":
                problems.append(f"layer {i} center loss (centers state "
                                "and center term need the fit path)")
        if problems:
            raise NotImplementedError(
                "averaging_frequency>1 routes through the functional "
                "local-SGD trainer, which does not carry: "
                + "; ".join(problems)
                + ". Use averaging_frequency=1 (exact sync averaging) or "
                "parallel.ParameterAveragingTrainer with a custom loss.")

    def get_network(self):
        return self.network


class _RebatchingIterator:
    """Re-batches an iterator of DataSets to a fixed global batch size
    (like the reference's RDD repartitioning), preserving feature masks.

    The tail that doesn't fill a whole global batch is NOT dropped: it is
    flushed truncated down to the largest multiple of the data-parallel
    degree, so small datasets still train (only examples that can't shard
    evenly are lost)."""

    def __init__(self, source, batch_size: int, dp: int = 1):
        self._source = source
        self._batch = batch_size
        self._dp = max(1, dp)

    def reset(self):
        if hasattr(self._source, "reset"):
            self._source.reset()

    def __iter__(self):
        import numpy as np

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.multilayer import _unpack

        feats, labels, masks, lmasks = [], [], [], []
        have, any_mask, any_unmasked = 0, False, False
        any_lmask, any_no_lmask = False, False

        def _cat(n):
            fx = np.concatenate(feats)
            fy = np.concatenate(labels)
            fm = np.concatenate(masks) if any_mask else None
            lm = np.concatenate(lmasks) if any_lmask else None
            return (DataSet(fx[:n], fy[:n],
                            None if fm is None else fm[:n],
                            None if lm is None else lm[:n]),
                    fx[n:], fy[n:],
                    None if fm is None else fm[n:],
                    None if lm is None else lm[n:])

        for ds in self._source:
            x, y, mask, lmask = _unpack(ds)
            if isinstance(lmask, (list, tuple, dict)):
                # the r5 per-output MultiDataSet shape: np.asarray would
                # stack it [n_out, B, T] and the batch-axis slicing below
                # would silently corrupt it
                raise ValueError(
                    "per-output labels masks (list/dict) are not supported "
                    "on the spark re-batching path; use a single labels "
                    "mask array or fit the ComputationGraph directly")
            feats.append(np.asarray(x))
            labels.append(np.asarray(y))
            if mask is not None:
                any_mask = True
                masks.append(np.asarray(mask))
            else:
                any_unmasked = True
            if lmask is not None:
                any_lmask = True
                lmasks.append(np.asarray(lmask))
            else:
                any_no_lmask = True
            if any_lmask and any_no_lmask:
                raise ValueError("mixed labels-masked/unmasked DataSets "
                                 "in one stream")
            if any_mask and any_unmasked:
                raise ValueError("mixed masked/unmasked DataSets in one stream")
            have += feats[-1].shape[0]
            while have >= self._batch:
                out, fx, fy, fm, lm = _cat(self._batch)
                yield out
                feats, labels = [fx], [fy]
                masks = [fm] if fm is not None else []
                lmasks = [lm] if lm is not None else []
                have = fx.shape[0]
        tail = (have // self._dp) * self._dp
        if tail:
            out, _, _, _, _ = _cat(tail)
            yield out


class _RebatchingMultiIterator:
    """MultiDataSet twin of _RebatchingIterator (r5): pools per-slot
    feature/label arrays — plus the SHARED features mask and a
    single-array labels mask — and re-cuts them into fixed global
    batches; the tail flushes truncated to the largest dp multiple.
    Per-output labels-mask lists/dicts are rejected (that routing lives
    in the graph's fit path). Slot order/keys are preserved (list or
    dict features both work, matching ComputationGraph._as_input_dict)."""

    def __init__(self, source, batch_size: int, dp: int = 1):
        self._source = source
        self._batch = batch_size
        self._dp = max(1, dp)

    def reset(self):
        if hasattr(self._source, "reset"):
            self._source.reset()

    @staticmethod
    def _slots(arrs, keys=None):
        """(keys_or_None, list_of_arrays). ``keys`` (from the stream's
        first item) pins slot order for every later dict — items whose
        dicts iterate in a different order must not silently swap slots —
        and mismatched key sets fail loud."""
        import numpy as np

        if isinstance(arrs, dict):
            if keys is None:
                keys = list(arrs)
            elif set(keys) != set(arrs):
                raise ValueError(
                    f"MultiDataSet slot keys changed mid-stream: "
                    f"{sorted(arrs)} vs {sorted(keys)}")
            return keys, [np.asarray(arrs[k]) for k in keys]
        return None, [np.asarray(a) for a in
                      (arrs if isinstance(arrs, (list, tuple)) else [arrs])]

    def __iter__(self):
        import numpy as np

        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        fkeys = lkeys = None
        pool_f = pool_l = None
        pool_m, pool_lm = [], []
        any_mask = any_unmasked = any_lmask = any_no_lmask = False
        have = 0

        def _cut(n):
            nonlocal have
            cf = [np.concatenate(p) if len(p) > 1 else p[0] for p in pool_f]
            cl = [np.concatenate(p) if len(p) > 1 else p[0] for p in pool_l]
            cm = (np.concatenate(pool_m) if any_mask else None)
            clm = (np.concatenate(pool_lm) if any_lmask else None)
            for i, a in enumerate(cf):
                pool_f[i] = [a[n:]]
            for i, a in enumerate(cl):
                pool_l[i] = [a[n:]]
            pool_m[:] = [cm[n:]] if cm is not None else []
            pool_lm[:] = [clm[n:]] if clm is not None else []
            have -= n
            feats = ([a[:n] for a in cf] if fkeys is None
                     else dict(zip(fkeys, (a[:n] for a in cf))))
            labels = ([a[:n] for a in cl] if lkeys is None
                      else dict(zip(lkeys, (a[:n] for a in cl))))
            return MultiDataSet(feats, labels,
                                features_mask=None if cm is None
                                else cm[:n],
                                labels_mask=None if clm is None
                                else clm[:n])

        self.dropped_rows = 0
        for ds in self._source:
            lm = getattr(ds, "labels_mask", None)
            if isinstance(lm, (list, tuple, dict)):
                raise ValueError(
                    "per-output labels masks (list/dict) are not supported "
                    "on the spark re-batching path; use a single labels "
                    "mask array or fit the ComputationGraph directly")
            fm = getattr(ds, "features_mask", None)
            fk, fa = self._slots(ds.features, fkeys)
            lk, la = self._slots(ds.labels, lkeys)
            if pool_f is None:
                fkeys, lkeys = fk, lk
                pool_f = [[] for _ in fa]
                pool_l = [[] for _ in la]
            for i, a in enumerate(fa):
                pool_f[i].append(a)
            for i, a in enumerate(la):
                pool_l[i].append(a)
            if fm is not None:
                any_mask = True
                pool_m.append(np.asarray(fm))
            else:
                any_unmasked = True
            if lm is not None:
                any_lmask = True
                pool_lm.append(np.asarray(lm))
            else:
                any_no_lmask = True
            if any_mask and any_unmasked:
                raise ValueError(
                    "mixed masked/unmasked MultiDataSets in one stream")
            if any_lmask and any_no_lmask:
                raise ValueError("mixed labels-masked/unmasked "
                                 "MultiDataSets in one stream")
            have += fa[0].shape[0]
            while have >= self._batch:
                yield _cut(self._batch)
        if pool_f is not None:
            tail = (have // self._dp) * self._dp
            if tail:
                yield _cut(tail)
            self.dropped_rows = have   # rows below the dp multiple


class SparkComputationGraph(SparkDl4jMultiLayer):
    """SparkComputationGraph analog — same collapse, graph models."""

    def __init__(self, mesh, network_or_conf, training_master=None):
        from deeplearning4j_tpu.nn.conf.builders import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(network_or_conf, ComputationGraphConfiguration):
            network_or_conf = ComputationGraph(network_or_conf).init()
        super().__init__(mesh, network_or_conf, training_master)
