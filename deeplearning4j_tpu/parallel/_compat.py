"""Version shims shared by the shard_map-based parallel modules."""

from __future__ import annotations

from jax import lax

try:  # jax >= 0.6 moved shard_map to jax.shard_map
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

if hasattr(lax, "pcast"):  # jax >= 0.9; pvary is deprecated
    def pvary(x, axes):
        return lax.pcast(x, axes, to="varying")
else:  # pragma: no cover
    pvary = lax.pvary

__all__ = ["shard_map", "pvary"]
