"""Version shims shared by the shard_map-based parallel modules."""

from __future__ import annotations

import inspect

from jax import lax

try:  # jax >= 0.6 moved shard_map to jax.shard_map
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

if hasattr(lax, "pcast"):  # jax >= 0.9; pvary is deprecated
    def pvary(x, axes):
        return lax.pcast(x, axes, to="varying")
elif hasattr(lax, "pvary"):
    pvary = lax.pvary
else:  # pragma: no cover — jax < 0.7: no varying-manual-axes tracking at
    # all (shard_map's check_rep treats body-created constants as
    # replicated until proven otherwise), so the annotation is a no-op
    def pvary(x, axes):
        return x

if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:  # pragma: no cover — the kwarg was named check_rep before jax 0.7
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

__all__ = ["shard_map", "pvary"]
