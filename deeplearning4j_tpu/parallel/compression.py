"""Threshold-encoded gradient sharing — the EncodedGradientsAccumulator
analog, for bandwidth-constrained meshes.

Reference analog (SURVEY.md §2.4): org.deeplearning4j.optimize.solvers.
accumulation.EncodedGradientsAccumulator + ThresholdAlgorithm — Strom-style
encoding where each update message carries only the entries whose magnitude
clears a threshold, quantized to ±threshold, with the remainder accumulated
locally (error feedback) for later rounds; an adaptive algorithm tunes the
threshold toward a target message density.

TPU-native redesign: on an ICI mesh plain psum wins (no encoding needed —
ParallelWrapper's path). This module is the DCN/multi-slice experiment the
survey calls for: the SAME semantics expressed as one SPMD step under
shard_map — per-device grads on the local batch shard, error-feedback
residual carried in the training state, ternary ±thr quantization, one
all-reduce of the (highly compressible) encoded tensor, and a density-driven
threshold adaptation. No host threads, no IndexedTail queues — the entire
accumulator collapses into pure carried state.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel._compat import shard_map


def threshold_encode(g, thr):
    """Ternary Strom encoding of one tensor: entries |g| >= thr become
    ±thr, the rest 0. Returns (encoded, residual) — residual = g - encoded
    is the error feedback the reference accumulates for later rounds."""
    q = jnp.where(g >= thr, thr, jnp.where(g <= -thr, -thr, 0.0))
    return q, g - q


def message_density(encoded, thr):
    """Fraction of nonzero entries in an encoded tensor (the quantity the
    reference's ThresholdAlgorithm steers)."""
    total = sum(leaf.size for leaf in jax.tree_util.tree_leaves(encoded))
    nz = sum(jnp.sum(jnp.abs(leaf) > 0.5 * thr)
             for leaf in jax.tree_util.tree_leaves(encoded))
    return nz / total


class EncodedGradientTrainer:
    """Data-parallel trainer whose update exchange is threshold-encoded.

    loss_fn(params, x, y) -> scalar loss on the LOCAL batch shard.
    Matches the reference's semantics: each worker computes its LOCAL
    lr-scaled update, encodes it (entries |u| >= thr quantized to ±thr, the
    remainder kept as local error-feedback residual — what the reference's
    EncodedGradientsAccumulator stores between rounds), and every worker
    applies the SUM of all workers' decoded messages (the reference applies
    each peer's decoded update as it arrives). The step carries
    {params, residual, thr} inside one jitted shard_map over ``axis``:

        u_local  = lr * grad(loss_fn)(params, x_shard, y_shard) + residual
        q, resid = threshold_encode(u_local, thr)
        params  <- params - psum(q)          # the ONLY cross-device traffic
        thr     <- thr * (density > target ? grow : shrink)    # adaptive

    Per-step movement is bounded by n_devices * thr per coordinate, which is
    what makes Strom encoding stable; error feedback guarantees nothing is
    lost, only delayed. Momentum/Adam-class updaters belong on the
    plain-psum path (ParallelWrapper) — the reference's gradient-sharing
    mode has the same shape: the exchange carries updates, not gradients.
    """

    def __init__(self, loss_fn: Callable, updater, mesh, *, axis: str = "data",
                 ici_axis: Optional[str] = None,
                 threshold: float = 1e-3, adaptive: bool = True,
                 target_density: float = 0.01, adapt_rate: float = 1.05,
                 residual_clip: float = 5.0):
        from deeplearning4j_tpu.optimize.updaters import Sgd, get_updater

        self.loss_fn = loss_fn
        updater = get_updater(updater)
        if not isinstance(updater, Sgd):
            raise ValueError(
                "EncodedGradientTrainer exchanges lr-scaled updates (Strom "
                "encoding); use Sgd here — stateful updaters belong on the "
                "plain-psum ParallelWrapper path")
        self.lr = updater.lr
        self.mesh = mesh
        self.axis = axis
        # hierarchical (multi-slice) mode: gradients are pmean'd at FULL
        # precision over the intra-slice ICI axis first; only the
        # cross-slice ("dcn") exchange carries threshold-encoded messages —
        # compression where bandwidth is actually scarce, exactly the
        # reference's fast-local/encoded-remote split (Aeron tier, §2.4)
        self.ici_axis = ici_axis
        self.threshold = threshold
        self.adaptive = adaptive
        self.target_density = target_density
        self.adapt_rate = adapt_rate
        # ResidualClippingPostProcessor analog: unbounded error feedback lags
        # the optimizer by arbitrarily many steps and oscillates; the
        # reference clips stored residuals every few iterations for the same
        # reason. Clip to ±residual_clip * thr (0 disables).
        self.residual_clip = residual_clip
        self._step = None

    def init(self, params):
        # residuals are device-local (the reference's accumulator state is
        # per-worker too) — carried with a leading device axis, sharded over
        # the mesh axis, so the SPMD step sees its own residual block
        n_dev = self.mesh.shape[self.axis]
        return {
            "params": params,
            "residual": jax.tree_util.tree_map(
                lambda p: jnp.zeros((n_dev,) + p.shape, p.dtype), params),
            "thr": jnp.asarray(self.threshold, jnp.float32),
            "step": jnp.asarray(0, jnp.int32),
        }

    def _build(self, carry):
        loss_fn = self.loss_fn
        axis = self.axis
        adaptive = self.adaptive
        target = self.target_density
        rate = self.adapt_rate
        lr = self.lr

        ici_axis = self.ici_axis

        def local_step(carry, x, y):
            params = carry["params"]
            loss, g = jax.value_and_grad(loss_fn)(params, x, y)
            if ici_axis is not None:
                # full-precision all-reduce inside the slice (ICI is cheap);
                # u below is then identical across the slice, so the encoded
                # exchange and residuals are per-slice quantities
                g = jax.tree_util.tree_map(
                    lambda t: lax.pmean(t, ici_axis), g)
                loss = lax.pmean(loss, ici_axis)
            loss = lax.pmean(loss, axis)
            thr = carry["thr"]
            step_lr = lr(carry["step"]) if callable(lr) else lr
            u = jax.tree_util.tree_map(
                lambda gg, r: (step_lr * gg).astype(gg.dtype) + r[0],
                g, carry["residual"])
            # two passes rather than one tree of (q, r) tuples: tuples are
            # ordinary pytree containers, so is_leaf=tuple would mangle any
            # params tree that itself contains tuples. thr cast to the leaf
            # dtype keeps bf16 state/exchange bf16.
            encoded = jax.tree_util.tree_map(
                lambda t: threshold_encode(t, thr.astype(t.dtype))[0], u)
            rclip = self.residual_clip

            def new_residual(t, q):
                r = t - q
                if rclip:
                    r = jnp.clip(r, (-rclip * thr).astype(t.dtype),
                                 (rclip * thr).astype(t.dtype))
                return r[None]

            residual = jax.tree_util.tree_map(new_residual, u, encoded)
            shared = jax.tree_util.tree_map(lambda t: lax.psum(t, axis), encoded)
            new_params = jax.tree_util.tree_map(lambda p, d: p - d, params, shared)
            if adaptive:
                dens = lax.pmean(message_density(encoded, thr), axis)
                thr = jnp.where(dens > target, thr * rate, thr / rate)
                thr = jnp.clip(thr, 1e-8, 1e2)
            return {
                "params": new_params,
                "residual": residual,
                "thr": thr,
                "step": carry["step"] + 1,
            }, loss

        rep = P()
        carry_in_specs = {
            "params": jax.tree_util.tree_map(lambda _: rep, carry["params"]),
            "residual": jax.tree_util.tree_map(lambda _: P(axis),
                                               carry["residual"]),
            "thr": rep,
            "step": rep,
        }
        # hierarchical mode shards the global batch over BOTH axes
        batch_spec = P((axis, ici_axis)) if ici_axis is not None else P(axis)
        fn = shard_map(
            local_step, mesh=self.mesh,
            in_specs=(carry_in_specs, batch_spec, batch_spec),
            out_specs=(carry_in_specs, rep),
        )
        return jax.jit(fn)

    def fit_batch(self, carry, x, y):
        """One encoded-exchange step over a global batch (sharded on ``axis``).
        Returns (new_carry, loss)."""
        if self._step is None:
            self._step = self._build(carry)
        return self._step(carry, jnp.asarray(x), jnp.asarray(y))
